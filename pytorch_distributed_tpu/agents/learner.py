"""Learner process: the compute-critical update loop.

Re-design of reference core/single_processes/dqn_learner.py:50-95 /
ddpg_learner.py:50-106.  Same cadence contract — gate on
``memory.size > learn_start`` with a sleep spin (reference dqn_learner.py:
51,102-103), one sampled minibatch per step, target-net update folded into
the step, global learner clock increment (reference :94-95), loss stats on
the ``learner_freq`` cadence (reference :99-101) — but the update itself is
one pure jitted XLA program (ops/losses.py) dispatched through
``ShardedLearner``: batch dp-sharded over the mesh, gradients all-reduced
over ICI, params/opt-state donated so the TrainState updates in place in
HBM.  Where the reference's Adam writes become instantly visible through
shared CUDA storage (reference :87), here the learner explicitly publishes
versioned parameter snapshots every ``param_publish_freq`` steps.

A single learner process drives the whole mesh; the reference's
``num_learners > 1`` hogwild hook (unsynchronized racing Adam steps,
SURVEY.md "known quirks") maps to widening the mesh's dp axis instead.

PER additions (the reference's TODO): queue-fed single-owner buffer
(memory/feeder.py) drained each step, |TD| priority write-back after every
update.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from pytorch_distributed_tpu.config import Options
from pytorch_distributed_tpu.factory import (
    EnvSpec, build_model, build_train_state_and_step, init_params,
    published_params,
)
from pytorch_distributed_tpu.agents.clocks import GlobalClock, LearnerStats
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.memory.device_replay import (
    DevicePerIngest, DeviceReplayIngest,
)
from pytorch_distributed_tpu.memory.device_sequence import (
    DeviceSequenceIngest,
)
from pytorch_distributed_tpu.memory.feeder import QueueOwner
from pytorch_distributed_tpu.utils import checkpoint as ckpt
from pytorch_distributed_tpu.utils import (
    bandwidth, flight_recorder, health, perf, tracing,
)
from pytorch_distributed_tpu.utils.faults import FaultInjector
from pytorch_distributed_tpu.utils.metrics import MetricsWriter
from pytorch_distributed_tpu.utils.profiling import StepTimer
from pytorch_distributed_tpu.utils.rngs import np_rng, process_seed


def run_learner(opt: Options, spec: EnvSpec, process_ind: int, memory: Any,
                param_store: ParamStore, clock: GlobalClock,
                stats: LearnerStats) -> None:
    from pytorch_distributed_tpu.factory import anakin_active

    if anakin_active(opt):
        # the co-located Anakin topology (ISSUE 12): this process IS
        # the actor fleet too — delegate to the duty-cycle driver.
        # Direct callers land here; the runtime dispatches earlier so
        # it can hand the shared ActorStats in (runtime.Topology.run).
        from pytorch_distributed_tpu.agents.anakin import (
            run_anakin_learner,
        )

        return run_anakin_learner(opt, spec, process_ind, memory,
                                  param_store, clock, stats)
    from pytorch_distributed_tpu.factory import replica_active

    if replica_active(opt):
        # the elastic multi-learner plane (ISSUE 15): N data-parallel
        # replicas over DCN, lease-fenced membership, generation-stamped
        # allreduce.  Delegation is gated the same LOUD-downgrade way as
        # megabatch: an unsupported family or a topology without a
        # registry/coordinator runs the solo loop and says so.
        from pytorch_distributed_tpu.parallel import dcn as dcn_mod

        rp = dcn_mod.resolve_replica(opt.replica_params)
        if opt.agent_type != "dqn":
            print(f"[learner] replicas={rp.replicas} is only supported "
                  f"for agent_type=dqn (got {opt.agent_type}); running "
                  f"the solo learner", flush=True)
        elif dcn_mod.local_registry() is None and not rp.coordinator:
            print(f"[learner] replicas={rp.replicas} needs the fleet "
                  f"gateway's ReplicaRegistry (fleet.py --role learner) "
                  f"or replica_params.coordinator; running the solo "
                  f"learner", flush=True)
        else:
            return run_replica_learner(opt, spec, process_ind, memory,
                                       param_store, clock, stats)
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from pytorch_distributed_tpu.parallel.learner import ShardedLearner
    from pytorch_distributed_tpu.parallel.mesh import make_mesh

    ap = opt.agent_params
    pp = opt.parallel_params

    # ---- model + train state (reference dqn_learner.py:21-39) ----
    # mesh first: sequence-parallel train steps (DTQN ring attention over
    # the sp axis) are built against it
    mesh = None
    if len(jax.devices()) > 1:
        mesh = make_mesh(pp.dp_size, pp.mp_size, pp.sp_size, pp.ep_size,
                         pp.pp_size)
    model = build_model(opt, spec)
    params = init_params(opt, spec, model, seed=opt.seed)
    if opt.model_file:
        # finetune-from-file (reference main.py:45)
        path = ckpt.params_path(opt.model_file) \
            if not opt.model_file.endswith(".msgpack") else opt.model_file
        params = ckpt.load_params(path, params)
    state, step_fn = build_train_state_and_step(opt, spec, model, params,
                                                mesh=mesh)
    state_shardings = None
    if mesh is not None and pp.mp_size > 1:
        # the one family wide enough for tensor parallelism: Megatron-split
        # DTQN FFN over mp (parallel/tensor_parallel.py)
        # exact match: the moe/pipe families have no _Block_ param paths,
        # so dtqn_state_shardings would silently no-op on them (their
        # splits are ep and pp respectively)
        assert opt.model_type == "dtqn-mlp", (
            f"mp_size>1 is only supported for dtqn-mlp "
            f"(got {opt.model_type})")
        from pytorch_distributed_tpu.parallel.tensor_parallel import (
            dtqn_state_shardings,
        )

        state_shardings = dtqn_state_shardings(state, mesh)
    if mesh is not None and pp.ep_size > 1:
        # expert parallelism: MoE expert kernels split over ep
        # (parallel/expert_parallel.py); mutually exclusive with the mp
        # split — the DTQN families are either dense (mp) or MoE (ep)
        assert opt.model_type == "dtqn-moe", (
            f"ep_size>1 is only supported for dtqn-moe "
            f"(got {opt.model_type})")
        assert pp.mp_size == 1, "ep and mp splits don't compose"
        from pytorch_distributed_tpu.parallel.expert_parallel import (
            moe_state_shardings,
        )

        state_shardings = moe_state_shardings(state, mesh)
    if mesh is not None and pp.pp_size > 1:
        # pipeline parallelism: stacked block layer axis over pp
        # (parallel/pipeline.py); exclusive with the other model splits
        assert opt.model_type == "dtqn-pipe", (
            f"pp_size>1 is only supported for dtqn-pipe "
            f"(got {opt.model_type})")
        assert pp.mp_size == 1 and pp.ep_size == 1, (
            "pp does not compose with mp/ep splits")
        from pytorch_distributed_tpu.parallel.pipeline import (
            pipeline_state_shardings,
        )

        state_shardings = pipeline_state_shardings(state, mesh)
    learner = ShardedLearner(step_fn, mesh, donate=pp.donate,
                             state_shardings=state_shardings)
    state = learner.place(state)

    # ---- resume: newest complete checkpoint epoch, else the legacy
    # single snapshot (utils/checkpoint.py docstring).  Epoch extras
    # (clock counters, evaluator best-score) restore BEFORE the first
    # publication so no worker ever observes pre-resume values.
    assert opt.resume in ("auto", "must", "never"), (
        f"unknown resume mode {opt.resume!r}")
    epoch = None
    if opt.resume != "never":
        epoch = ckpt.resolve_epoch(opt.model_name)
        if epoch is not None:
            state = learner.place(
                ckpt.load_epoch_state(epoch, jax.device_get(state)))
            clock.seed_actor_steps(int(epoch.extras.get("actor_step", 0)))
            # the sidecar (written WITH every best-params file) can be
            # ahead of the epoch's score when the record fell between
            # two commits — take the max so a resumed run never lets a
            # worse policy overwrite <refs>_best.msgpack
            best = max(float(epoch.extras.get("best_eval_reward",
                                              float("-inf"))),
                       ckpt.load_best_score(opt.model_name))
            clock.best_eval_reward.value = best
            print(f"[learner] resumed epoch {epoch.epoch} "
                  f"(step {epoch.learner_step}, "
                  f"actor_step +{int(epoch.extras.get('actor_step', 0))}, "
                  f"best_eval {best:g})")
        else:
            restored = ckpt.restore_train_state(opt.model_name,
                                                jax.device_get(state))
            if restored is not None:
                state = learner.place(restored)
                clock.best_eval_reward.value = ckpt.load_best_score(
                    opt.model_name)
                print("[learner] resumed legacy single-snapshot state")
            elif opt.resume == "must":
                raise RuntimeError(
                    f"resume='must' but no complete checkpoint epoch "
                    f"under {ckpt.ckpt_root(opt.model_name)} and no "
                    f"legacy snapshot at {ckpt.state_dir(opt.model_name)}")

    # ---- initial publication: actors block on version 1 ----
    def _publish(st) -> None:
        flat, _ = ravel_pytree(jax.device_get(published_params(opt, st)))
        param_store.publish(np.asarray(flat, dtype=np.float32))

    _publish(state)

    # Async publication path: the device->host parameter fetch can cost
    # seconds when the chip sits behind a network tunnel, and it used to
    # sit INSIDE the learner hot loop.  Now a publish crossing only
    # enqueues a cheap on-device copy of the param tree (jit outputs
    # never alias non-donated inputs, so the copy survives later donating
    # dispatches); a worker thread fetches + publishes in the background,
    # always taking the freshest snapshot (an in-flight fetch absorbs any
    # newer requests - actors only ever want the latest version anyway).
    # TPU only: a concurrent device_get against in-flight multi-device
    # programs deadlocks the CPU backend's collective rendezvous (see
    # ShardedLearner.host_params), so the CPU path publishes inline.
    import threading

    _pub_thread = None
    if jax.devices()[0].platform == "tpu":
        _copy_tree = jax.jit(
            lambda p: jax.tree_util.tree_map(jnp.copy, p))
        _pub_lock = threading.Lock()
        _pub_box: list = [None]
        _pub_event = threading.Event()
        _pub_stop = threading.Event()

        def _pub_worker() -> None:
            while True:
                _pub_event.wait()
                if _pub_stop.is_set():
                    return
                with _pub_lock:
                    snap, _pub_box[0] = _pub_box[0], None
                    _pub_event.clear()
                if snap is None:
                    continue
                try:
                    flat, _ = ravel_pytree(jax.device_get(snap))
                    param_store.publish(np.asarray(flat, dtype=np.float32))
                except Exception as e:  # noqa: BLE001 - keep publishing
                    # a transient fetch error (flaky tunnel) must not
                    # silently kill publication for the rest of the run —
                    # actors would act on frozen weights forever
                    print(f"[learner] async publish failed (will retry "
                          f"on next snapshot): {e}")

        _pub_thread = threading.Thread(target=_pub_worker,
                                       name="param-pub", daemon=True)
        _pub_thread.start()

        def _publish_async(st) -> None:
            with _pub_lock:
                _pub_box[0] = _copy_tree(published_params(opt, st))
                _pub_event.set()
    else:
        _publish_async = _publish

    is_per = isinstance(memory, QueueOwner)
    # the HBM segment ring presents the same fused-priority surface as the
    # HBM PER ring (attach / build_fused_step / beta / drain), so the
    # learner drives both through one path (memory/device_sequence.py)
    is_device_per = isinstance(memory, (DevicePerIngest,
                                        DeviceSequenceIngest))
    is_device = isinstance(memory, DeviceReplayIngest) and not is_device_per
    on_device = is_device or is_device_per
    # perf plane monitor (utils/perf.py, TPU_APEX_PERF=1): created for
    # every memory path — rates/watermarks/gauges work everywhere; the
    # FLOPs capture below is device-path only (the host path's step
    # runs through ShardedLearner, whose per-update FLOPs nobody
    # dispatch-amortizes)
    perf_mon = perf.get_monitor("learner", opt.perf_params)
    if perf_mon.enabled:
        # the MFU denominator scales by the dtype the model actually
        # computes in (ISSUE-13 satellite: an fp32 run scored against
        # the bf16 peak under-reports MFU 2x)
        _cd = getattr(model, "compute_dtype", None)
        if _cd is not None:
            perf_mon.set_compute_dtype(jnp.dtype(_cd).name)
    if not on_device:
        # megabatch serves the fused device-replay dispatch only — a
        # host-replay config with the knob set must say so LOUDLY (the
        # same downgrade convention as the unsupported-family case
        # below), not silently benchmark an unengaged lever
        from pytorch_distributed_tpu.utils.perf import resolve_mxu

        _m_req = resolve_mxu(opt.learner_perf_params).megabatch
        if _m_req > 1:
            print(f"[learner] megabatch={_m_req} requires a device "
                  f"replay (memory_type device/device-per; got "
                  f"{opt.memory_type}); host-path learner runs "
                  f"unbatched", flush=True)
    if on_device:
        # Attach the HBM ring on the learner's mesh and fuse sampling (and
        # for PER: priority write-back) into the train step — one XLA
        # program per DISPATCH, which covers ``steps_per_dispatch`` scanned
        # update steps: launch latency, not chip compute, bounds this loop
        # on tunnelled/congested setups (memory/device_replay.py
        # build_uniform_fused_step docstring).
        replay = memory.attach(mesh=mesh)
        beta_dev = None
        K = ap.steps_per_dispatch
        if K <= 0:  # auto: amortise dispatch on real accelerators only
            # 32 measured vs 8 on the tunnelled dev chip: ~2,350 vs
            # ~1,040 true (fetch-bounded) updates/s — dispatch latency
            # dominates until K~64-128; 32 keeps the cadence quantum
            # small while recovering most of the win (bench.py micro,
            # 2026-07-31)
            K = 32 if jax.devices()[0].platform == "tpu" else 1
        # ISSUE-13 megabatching: group the K scanned updates into K/M
        # widened-gather groups (one lane-filling batched backward per
        # group); the group step comes from the factory so the
        # sequential and megabatch paths share torso/optimizer gates
        from pytorch_distributed_tpu.factory import (
            build_megabatch_train_step, resolve_megabatch,
        )

        M, K_mb = resolve_megabatch(opt, K)
        mega_step = None
        if M > 1:
            mega_step = build_megabatch_train_step(opt, model)
            if mega_step is None:
                print(f"[learner] megabatch={M} is not supported for "
                      f"agent_type={opt.agent_type} (dqn/decoupled-ddpg "
                      f"only); running the sequential fused step at "
                      f"steps_per_dispatch={K}", flush=True)
                M = 1
            else:
                # only an ENGAGED megabatch inflates the dispatch
                # quantum — a downgrade keeps the configured K
                K = K_mb
        mb_kw = (dict(megabatch=M, megabatch_step=mega_step)
                 if M > 1 else {})
        if is_device_per:
            fused_per = replay.build_fused_step(step_fn, ap.batch_size,
                                                donate=pp.donate,
                                                steps_per_call=K,
                                                **mb_kw)

            def device_step(keys):
                nonlocal state
                state, replay.state, m = fused_per(state, replay.state,
                                                   keys, beta_dev)
                return m
        else:
            from pytorch_distributed_tpu.memory.device_replay import (
                build_uniform_fused_step, sample_rows,
            )

            if K > 1:
                fused = build_uniform_fused_step(
                    step_fn, ap.batch_size, steps_per_call=K,
                    donate=pp.donate, **mb_kw)

                def device_step(keys):
                    nonlocal state
                    state, m = fused(state, replay.state, keys)
                    return m
            else:
                fused = jax.jit(
                    lambda ts, rs, key: step_fn(
                        ts, sample_rows(rs, key, ap.batch_size)),
                    donate_argnums=(0,) if pp.donate else ())

                def device_step(key):
                    nonlocal state
                    state, m, _td = fused(state, replay.state, key)
                    return m

        # Capture the fused program's per-update FLOPs off its cost
        # analysis ONCE at startup — the same executable the loop
        # dispatches (the AOT lower/compile below dedups through the
        # persistent compile cache on TPU) — so live MFU is one
        # multiply per stats window.  The jit cache handle feeds the
        # retrace detector: this program must never recompile after
        # warmup.
        if perf_mon.enabled:
            _pf = fused_per if is_device_per else fused
            perf_mon.register_jit("fused_step",
                                  getattr(_pf, "_cache_size", None))
            # seed-derived even though these keys only feed .lower()
            # for the FLOP capture (apexlint rng-key-reuse: no literal-
            # seed streams outside utils.rngs)
            _pkeys = jax.random.split(
                jax.random.PRNGKey(process_seed(opt.seed, "learner",
                                                process_ind)),
                K + 1)[1:]
            _pkeys = (_pkeys.reshape(K, *_pkeys.shape[1:]) if K > 1
                      else _pkeys[0])
            if is_device_per:
                _pbeta = jax.device_put(np.float32(replay.beta(0)))
                perf_mon.capture_flops(
                    lambda: fused_per.lower(state, replay.state, _pkeys,
                                            _pbeta))
            else:
                perf_mon.capture_flops(
                    lambda: fused.lower(state, replay.state, _pkeys))
        if perf_mon.audit is not None:
            # transfer audit (opt-in): the fused dispatch is transfer-
            # free by construction — state, ring and keys are all
            # device-resident — so ANY implicit transfer it stages is a
            # regression; the audit attributes it to its call site and
            # retries with transfers allowed (utils/perf.TransferAudit)
            _unaudited_step = device_step

            def device_step(keys):  # noqa: F811 - deliberate rebind
                return perf_mon.audit.run(_unaudited_step, keys)

        # data-plane telemetry programs (ISSUE 8): a bounded provenance
        # gather and — for the PER ring — the in-jit priority X-ray;
        # each is ONE small D2H on the stats cadence, never per step
        from pytorch_distributed_tpu.memory.device_replay import (
            provenance_sample,
        )

        _prov_sample = (jax.jit(provenance_sample, static_argnames="n")
                        if getattr(replay.state, "prov", None) is not None
                        else None)
        _xray_dev = None
        if getattr(replay.state, "priority", None) is not None:
            from pytorch_distributed_tpu.memory.device_per import (
                priority_xray_device,
            )

            _xray_dev = jax.jit(priority_xray_device,
                                static_argnames="bins")
        # telemetry's own key stream, decoupled from the sampling
        # stream by a fold — never a draw from device_key's chain
        _tel_key = jax.random.fold_in(
            jax.random.PRNGKey(np_rng(opt.seed, "learner",
                                      process_ind).integers(2 ** 31)),
            0x7e1)

        device_key = jax.random.PRNGKey(
            np_rng(opt.seed, "learner", process_ind).integers(2 ** 31))
        saved_key = (epoch.extras.get("rng", {}).get("learner_device")
                     if epoch is not None else None)
        if saved_key:
            # resume the device sampling stream where the epoch froze it
            # (keys pre-split after the save are re-drawn — a bounded
            # overlap, not a reuse of the whole stream)
            device_key = ckpt.deserialize_prng_key(saved_key, device_key)
        key_buf: list = []  # pre-split sampling keys, one split per 64
        # the CPU backend's collective rendezvous needs per-step blocking
        # (see ShardedLearner.step)
        block_each_step = (mesh is not None
                           and mesh.devices.flat[0].platform == "cpu")

    # warm-start the replay from the SAME epoch the train state came from
    # (after attach, so device rings land in HBM) — state, replay and
    # counters are one digest-verified triple, never a mixed resume.  A
    # geometry change between runs fails loudly here (CheckpointMismatch)
    # instead of as a broadcast error deep in the first train step.
    if epoch is not None and opt.memory_params.checkpoint_replay:
        # the flag gates the restore leg exactly like the save leg (and
        # the legacy branch below): a user resuming with
        # checkpoint_replay=false has asked for a cold replay — e.g.
        # after a deliberate memory-geometry change — and must not trip
        # CheckpointMismatch on an artifact they opted out of
        rows = ckpt.load_epoch_replay(epoch, memory)
        if rows:
            print(f"[learner] replay restored from epoch {epoch.epoch}: "
                  f"{rows} rows")
    elif epoch is None and opt.memory_params.checkpoint_replay:
        if ckpt.load_replay(opt.model_name, memory):
            print(f"[learner] replay restored: {memory_size(memory)} rows")

    rng = np_rng(opt.seed, "learner", process_ind)
    lstep = int(jax.device_get(state.step))
    lstep0 = lstep  # checkpoint-resumed steps; pacing baselines on THIS run
    if epoch is not None:
        # the epoch binds the pacing baseline and host RNG to the counters
        # restored above: replay-ratio throttling continues on cumulative
        # (lstep - lstep0) vs the restored actor clock instead of
        # resetting every resume (and the sampling stream continues
        # instead of replaying itself)
        lstep0 = int(epoch.extras.get("lstep0", lstep0))
        ckpt.restore_np_rng(
            rng, epoch.extras.get("rng", {}).get("learner_host"))
    clock.set_learner_step(lstep)

    # ---- gate until the replay warms up (reference dqn_learner.py:51) ----
    # clamped to the actual buffer capacity (segments for sequence replay,
    # transitions elsewhere): a learn_start >= capacity would otherwise
    # spin forever since a full ring's size never exceeds its capacity
    cap = getattr(memory, "capacity", opt.memory_params.memory_size)
    learn_start = min(ap.learn_start, cap - 1)
    deadline = (time.monotonic() + ap.max_seconds) if ap.max_seconds > 0 \
        else float("inf")
    while not clock.done(ap.steps) and memory_size(memory) <= learn_start \
            and time.monotonic() < deadline:
        # replay starvation is a LEGITIMATE wait: keep the liveness mark
        # fresh so the hang watchdog never reads warmup as a hang
        clock.bump_progress("learner")
        time.sleep(0.05)

    # the latest step's metric refs, fetched to host only on the
    # learner_freq cadence (one device_get per window — per-step or
    # per-element fetches are round trips that throttle a tunnelled chip)
    last_metrics = None
    t_cadence = time.monotonic()
    last_stats_lstep = lstep
    timer = StepTimer("learner")
    # per-phase timings go straight to the run's JSONL stream (appends are
    # atomic line writes; the logger process keeps the aggregated scalars)
    timing_writer = MetricsWriter(opt.log_dir, enable_tensorboard=False,
                                  role="learner", run_id=opt.refs)
    # distributed-trace tail: sample/learn spans attach to the most recent
    # trace id the replay drain observed (utils/tracing.py), closing the
    # actor→gateway→feed→sample→learn chain; the learner also flushes the
    # in-process "feeder" and "gateway" tracers — both record on threads
    # of THIS process (the drain path and the DCN serve threads)
    tracer = tracing.get_tracer("learner")

    def _flush_traces(step: int) -> None:
        for t in (tracer, tracing.get_tracer("feeder"),
                  tracing.get_tracer("gateway")):
            t.flush_to(timing_writer, step=step)

    def _save_epoch() -> None:
        """One coordinated checkpoint epoch: train state + replay +
        clocks/counters/best-score/RNG, captured NOW and committed by the
        atomic manifest rename (utils/checkpoint.py save_epoch) — the
        crash-consistent replacement for the old separate
        save_train_state/save_replay writes."""
        extras = dict(
            learner_step=lstep,
            lstep0=lstep0,
            actor_step=int(clock.actor_step.value),
            best_eval_reward=float(clock.best_eval_reward.value),
            replay_size=int(getattr(memory, "size", 0)),
            # sentinel provenance: how many rollbacks/skips preceded
            # this epoch (ckpt_fsck context for post-rollback roots)
            rollbacks=int(clock.rollbacks.value),
            skipped_steps=int(clock.skipped_steps.value),
            rng=dict(
                learner_host=ckpt.serialize_np_rng(rng),
                learner_device=(ckpt.serialize_prng_key(device_key)
                                if on_device else None),
            ),
        )
        ckpt.save_epoch(
            opt.model_name, state=state,
            memory=memory if opt.memory_params.checkpoint_replay else None,
            extras=extras, retain=ap.checkpoint_retain)

    # ---- training health sentinel (utils/health.py): the in-jit guard
    # already skips non-finite steps inside the train program; here the
    # host side watches the metrics stream for SUSTAINED divergence
    # (consecutive anomalous stats windows) and rolls the whole triple —
    # params, opt state, replay, clocks, RNG — back to the last good
    # checkpoint epoch in-process, bounded by ``max_rollbacks`` before
    # failing fast.  ``LEARNER_FAULTS`` (poison_grad@N / hang@N) drills
    # the ladder deterministically (utils/faults.py).
    hp = health.resolve(opt.health_params)
    detector = health.AnomalyDetector(zmax=hp.anomaly_zmax,
                                      grad_spike=hp.grad_spike,
                                      threshold=hp.anomaly_threshold,
                                      ess_floor=hp.ess_floor)
    recorder = flight_recorder.get_recorder("learner")
    _linj = FaultInjector.from_env("learner")
    _poison = [False]   # a pending poison_grad verb (next host batch)
    _win_skips = [0]    # exact skip count this stats window (host paths)
    _last_td = [None]   # mean |TD| of the last applied host-PER step
    _last_idx = [None]  # last sampled host-batch indices (provenance)
    _rb = {"used": 0, "before": None}  # rollback budget + ladder position

    def _fatal_divergence(msg: str) -> None:
        recorder.record("divergence-fatal", step=lstep, detail=msg)
        flight_recorder.dump_all(f"learner divergence: {msg}")
        raise RuntimeError(f"[health] {msg}")

    def _rollback(reason: str) -> None:
        """Restore the last good epoch in-process and resume.  Each
        successive rollback targets an epoch strictly OLDER than the
        previous restore point (the newest epoch may itself hold
        already-diverged params), and every committed epoch newer than
        the target is fenced with a ROLLED_BACK marker so neither this
        run nor a later --resume can step back onto it."""
        nonlocal state, lstep, lstep0, device_key, key_buf
        if _rb["used"] >= hp.max_rollbacks:
            _fatal_divergence(
                f"divergence persists after {_rb['used']} rollback(s) "
                f"(max_rollbacks={hp.max_rollbacks}): {reason}")
        target = ckpt.resolve_epoch(opt.model_name, before=_rb["before"])
        if target is None:
            _fatal_divergence(
                f"sustained divergence ({reason}) with no resumable "
                f"checkpoint epoch to roll back to "
                f"(checkpoint_freq=0 or all epochs spent)")
        ckpt.fence_epochs_after(opt.model_name, target.epoch,
                                reason=reason)
        state = learner.place(
            ckpt.load_epoch_state(target, jax.device_get(state)))
        if opt.memory_params.checkpoint_replay and target.has_replay:
            rows = ckpt.load_epoch_replay(target, memory)
            if rows:
                print(f"[health] replay rolled back with the epoch: "
                      f"{rows} rows")
        lstep = (target.learner_step if target.learner_step >= 0
                 else int(jax.device_get(state.step)))
        lstep0 = int(target.extras.get("lstep0", lstep))
        ckpt.restore_np_rng(rng,
                            target.extras.get("rng", {}).get("learner_host"))
        if on_device:
            saved = target.extras.get("rng", {}).get("learner_device")
            if saved:
                device_key = ckpt.deserialize_prng_key(saved, device_key)
            key_buf.clear()  # pre-split keys belong to the abandoned tail
        clock.set_learner_step(lstep)
        with clock.rollbacks.get_lock():
            clock.rollbacks.value += 1
        _rb["used"] += 1
        _rb["before"] = target.epoch
        detector.reset()
        _win_skips[0] = 0  # pre-rollback skips belong to the dead tail
        recorder.record("rollback", epoch=target.epoch, step=lstep,
                        reason=reason, used=_rb["used"])
        flight_recorder.dump_all(
            f"health rollback #{_rb['used']} to epoch {target.epoch} "
            f"({reason})")
        print(f"[health] rolled back to epoch {target.epoch} "
              f"(step {lstep}) after {reason}; "
              f"{hp.max_rollbacks - _rb['used']} rollback(s) left",
              flush=True)

    # anchor the first rate window at loop entry (not process start:
    # warmup compiles must not dilute it); the anchor drain carries the
    # one-time flops_per_update row + startup watermarks, so write it
    if perf_mon.enabled:
        timing_writer.scalars(perf_mon.drain(step=lstep), step=lstep)
    while lstep < ap.steps and not clock.stop.is_set() \
            and time.monotonic() < deadline:
        clock.bump_progress("learner")
        for _action, _arg in _linj.data_frame(("poison_grad",)):
            _poison[0] = True
        if ap.max_replay_ratio > 0:
            # pacing gate: don't draw more than max_replay_ratio samples
            # per collected transition (config.py AgentParams docstring).
            # Baselined on THIS run's steps (lstep - lstep0): a resumed
            # checkpoint's cumulative count against a fresh actor clock
            # would stall the learner for hours.  Queue-backed memories
            # keep draining while throttled — a full ingest queue blocks
            # actors before they can advance the clock (deadlock).
            while (not clock.stop.is_set()
                   and time.monotonic() < deadline
                   and (lstep - lstep0 + 1) * ap.batch_size
                   > ap.max_replay_ratio * max(clock.actor_step.value, 1)):
                if hasattr(memory, "drain"):
                    memory.drain()
                # pacing throttle = flow control, not a hang
                clock.bump_progress("learner")
                time.sleep(0.002)
            if clock.stop.is_set():
                break
        if on_device:
            if _poison[0]:
                _poison[0] = False
                print("[faults:learner] poison_grad targets the "
                      "host-sampled batch; inert on the fused device "
                      "path (drill with poison_chunk instead)",
                      flush=True)
            with timer.phase("drain"):
                memory.drain()
            if not key_buf:
                # one split dispatch amortised over 64 dispatches — a
                # per-step split is a device round trip that dominates
                # when the chip sits behind a network tunnel; beta (PER)
                # anneals slowly and refreshes on the same cadence
                keys = jax.random.split(device_key, 64 * K + 1)
                device_key = keys[0]
                rest = keys[1:]
                # typed PRNG keys are (n,)-shaped, raw keys (n, 2) —
                # group into 64 dispatches of K either way
                key_buf = (list(rest.reshape(64, K, *rest.shape[1:]))
                           if K > 1 else list(rest))
                if is_device_per:
                    beta_dev = jax.device_put(
                        np.float32(replay.beta(lstep)))
            with timer.phase("step"), \
                    tracer.span("learn", trace_id=tracing.current_trace()):
                metrics = device_step(key_buf.pop())
                if block_each_step:
                    jax.block_until_ready(state.params)
        else:
            if is_per:
                with timer.phase("drain"):
                    memory.drain()
            with timer.phase("sample"), \
                    tracer.span("sample",
                                trace_id=tracing.current_trace()):
                batch = memory.sample(ap.batch_size, rng)
            _last_idx[0] = np.asarray(batch.index)
            if _poison[0]:
                # poison_grad drill: a non-finite loss injected into
                # THIS update — the in-jit guard must skip it with
                # params provably unchanged (tests/test_health.py)
                _poison[0] = False
                batch = batch._replace(reward=np.full_like(
                    np.asarray(batch.reward), np.nan))
                print("[faults:learner] poison_grad: NaN rewards "
                      "injected into this update's batch", flush=True)
            with timer.phase("step"), \
                    tracer.span("learn", trace_id=tracing.current_trace()):
                state, metrics, td_abs = learner.step(state, batch)
            skipped_now = 0.0
            if is_per and isinstance(metrics, dict) \
                    and health.SKIPPED_KEY in metrics:
                # the PER path must know NOW (write-back suppression)
                # and already syncs td_abs to host — one extra scalar
                # rides the same sync, giving exact per-step skip
                # accounting.  Uniform paths keep full async dispatch
                # and sample the flag on the stats cadence instead.
                skipped_now = float(jax.device_get(
                    metrics[health.SKIPPED_KEY]))
                if skipped_now >= 0.5:
                    _win_skips[0] += 1
            if is_per:
                with timer.phase("priorities"):
                    if skipped_now < 0.5:
                        td_np = np.asarray(td_abs)
                        # |TD| scale feeds the anomaly detector's
                        # td_explosion signal on the stats cadence
                        _last_td[0] = float(np.mean(np.abs(td_np)))
                        memory.update_priorities(np.asarray(batch.index),
                                                 td_np)
                    # skipped step: the guard zeroed td_abs — writing it
                    # back would crush real priorities to epsilon
        stride = K if on_device else 1
        prev = lstep
        lstep += stride
        clock.set_learner_step(lstep)  # reference dqn_learner.py:94-95
        perf_mon.note_updates(stride)  # one int add; no-op when disabled
        last_metrics = metrics

        # cadences fire on boundary crossings so a multi-step dispatch
        # (stride > 1) never skips them
        crossed = lambda freq: freq and lstep // freq != prev // freq
        if crossed(ap.param_publish_freq):
            with timer.phase("publish"):
                _publish_async(state)
        if crossed(ap.checkpoint_freq):
            _save_epoch()

        if crossed(ap.learner_freq):  # reference dqn_learner.py:99-101
            now = time.monotonic()
            # sampled (not averaged) losses: the window's last step stands
            # in for the window, one host fetch total
            vals = {k: float(v)
                    for k, v in jax.device_get(last_metrics).items()}
            stats.add(
                counter=1,
                critic_loss=vals.get("learner/critic_loss", 0.0),
                actor_loss=vals.get("learner/actor_loss", 0.0),
                q_mean=vals.get("learner/q_mean", 0.0),
                grad_norm=vals.get("learner/grad_norm", 0.0),
                moe_aux=vals.get("learner/moe_aux", 0.0),
                steps_per_sec=(lstep - last_stats_lstep)
                / max(now - t_cadence, 1e-9),
            )
            # ---- sentinel window: guard skips + rolling anomalies ----
            # host PER counted every step (_win_skips); other paths read
            # the sampled flag of the window's last step/dispatch (the
            # fused path's flag already sums over its K substeps,
            # utils/health.reduce_scan_metrics)
            skipped_w = float(_win_skips[0]) or vals.get(
                health.SKIPPED_KEY, 0.0)
            _win_skips[0] = 0
            if skipped_w:
                clock.add_skipped_steps(int(round(skipped_w)))
            # ---- data-plane X-ray (ISSUE 8): provenance of what the
            # learner is actually consuming + the PER priority
            # distribution, exported on this cadence and fed to the
            # detector.  Host paths read their sidecars directly; the
            # device paths pay ONE bounded D2H each (a 256-row
            # provenance gather / the in-jit bucket histogram).
            prov = None
            prov_fn = getattr(memory, "provenance_of", None)
            if prov_fn is not None and _last_idx[0] is not None:
                prov = prov_fn(_last_idx[0])
                prov = None if prov is None else np.asarray(prov)
            elif on_device and _prov_sample is not None:
                pr_dev, _ = _prov_sample(
                    replay.state, jax.random.fold_in(_tel_key, lstep),
                    n=256)
                prov = np.asarray(pr_dev)
            cur_version = int(getattr(param_store, "version", 0) or 0)
            ds = (health.provenance_stats(prov, cur_version, lstep)
                  if prov is not None else None)
            if ds is not None:
                timing_writer.histogram("learner/staleness",
                                        ds["staleness"].tolist(),
                                        step=lstep)
                timing_writer.histogram("learner/sample_age",
                                        ds["age"].tolist(), step=lstep)
                timing_writer.histogram("replay/actor_share",
                                        ds["shares"].tolist(),
                                        step=lstep)
                perf_mon.set_gauge("data/staleness_p50",
                                   float(np.median(ds["staleness"])))
                perf_mon.set_gauge("data/sample_age_p95",
                                   float(np.percentile(ds["age"], 95)))
                perf_mon.set_gauge("data/top_actor_share",
                                   float(ds["shares"].max()))
            xray = None
            # mass/rows kept SEPARATE from the X-ray: an all-zero leaf
            # set yields xray=None, and the detector must still see
            # (mass ~0, rows > 0) — the degenerate collapse the signal
            # was originally built for
            p_mass, p_rows = None, 0
            leaves_fn = getattr(memory, "priority_leaves", None)
            leaves = leaves_fn() if leaves_fn is not None else None
            if leaves is not None and len(leaves):
                p_mass = float(np.sum(leaves))
                p_rows = int(len(leaves))
                xray = health.priority_xray(leaves)
            elif on_device and _xray_dev is not None:
                counts, ess, rows_d, mass = jax.device_get(
                    _xray_dev(replay.state))
                rows_d = int(rows_d)
                p_mass, p_rows = float(mass), rows_d
                if rows_d:
                    xray = {"rows": rows_d, "mass": float(mass),
                            "ess": float(ess),
                            "ess_frac": float(ess) / rows_d,
                            "counts": np.asarray(counts),
                            "log10_lo": health.PRIORITY_XRAY_LOG10_LO,
                            "log10_hi": health.PRIORITY_XRAY_LOG10_HI}
            if xray is not None:
                timing_writer.bucket_histogram(
                    "replay/priority", xray["counts"],
                    log10_lo=xray["log10_lo"], log10_hi=xray["log10_hi"],
                    step=lstep,
                    extra={"ess": xray["ess"],
                           "ess_frac": xray["ess_frac"],
                           "mass": xray["mass"], "rows": xray["rows"]})
                timing_writer.scalars({
                    "replay/priority_ess": xray["ess"],
                    "replay/priority_ess_frac": xray["ess_frac"],
                }, step=lstep)
                perf_mon.set_gauge("data/priority_ess",
                                   xray["ess_frac"])
            anomalies = detector.observe(
                loss=vals.get("learner/critic_loss"),
                grad_norm=vals.get("learner/grad_norm"),
                td_mean=_last_td[0],
                priority_mass=p_mass,
                replay_rows=p_rows,
                skipped=skipped_w,
                priority_ess=xray["ess_frac"] if xray else None)
            if anomalies:
                recorder.record("anomaly", step=lstep, kinds=anomalies,
                                streak=detector.streak)
                print(f"[health] anomaly at step {lstep}: "
                      f"{'+'.join(anomalies)} (streak {detector.streak}"
                      f"/{hp.anomaly_threshold})", flush=True)
            timing_writer.scalars({
                "health/skipped_steps": float(clock.skipped_steps.value),
                "health/rollbacks": float(clock.rollbacks.value),
                "health/anomaly_streak": float(detector.streak),
            }, step=lstep)
            if hp.rollback and detector.should_rollback():
                _rollback("+".join(anomalies) if anomalies
                          else "anomaly streak")
            if perf_mon.enabled:
                # throughput-attribution gauges the monitor can't see
                # from inside: replay ratio on THIS run's steps (the
                # pacing gate's own accounting) and how full the ingest
                # queue is (1.0 = actors blocked on backpressure)
                perf_mon.set_gauge(
                    "learner/replay_ratio",
                    (lstep - lstep0) * ap.batch_size
                    / max(int(clock.actor_step.value), 1))
                _q = getattr(memory, "_q", None)
                if _q is not None and hasattr(_q, "qsize"):
                    try:
                        depth = int(_q.qsize())
                        bound = int(getattr(memory, "max_queue_chunks",
                                            0))
                        perf_mon.set_gauge("learner/ingest_queue_depth",
                                           depth)
                        if bound:
                            perf_mon.set_gauge(
                                "learner/ingest_queue_util",
                                depth / bound)
                    except (NotImplementedError, OSError):
                        pass  # macOS mp queues have no qsize
                timing_writer.scalars(perf_mon.drain(step=lstep),
                                      step=lstep)
            # bandwidth X-ray (ISSUE 18): the headline wire/replay/ckpt
            # series on the same stats cadence — wire/<link>/bytes_per_s
            # rates come from deltas against the previous emit
            wire_series = bandwidth.emit_scalars()
            if wire_series:
                timing_writer.scalars(wire_series, step=lstep)
            timing_writer.scalars(timer.drain(), step=lstep)
            _flush_traces(lstep)
            t_cadence = now
            last_stats_lstep = lstep

    # final publication + final checkpoint epoch so a next run can resume
    # — this is also the preemption path: a SIGTERM (runtime.py) trips
    # clock.stop, the loop above drains out, and the run's last complete
    # state is committed here before exit
    if _pub_thread is not None:
        _pub_stop.set()
        _pub_event.set()
        _pub_thread.join(timeout=120)
    _publish(state)
    _save_epoch()
    if perf_mon.enabled:
        # final partial window: short runs must still export their rates
        timing_writer.scalars(perf_mon.drain(step=lstep), step=lstep)
    _flush_traces(lstep)  # tail spans of the final partial window
    timing_writer.close()


def memory_size(memory: Any) -> int:
    if hasattr(memory, "drain"):
        memory.drain()
    return memory.size


# ---------------------------------------------------------------------------
# elastic multi-learner replica plane (ISSUE 15)
# ---------------------------------------------------------------------------

def _key_data(key) -> np.ndarray:
    """Raw uint32 view of a PRNG key (typed or raw) — the key-stream
    schedule the parity oracle compares bit-for-bit."""
    import jax

    try:
        return np.asarray(jax.random.key_data(key)).copy()
    except (TypeError, AttributeError):  # raw uint32 keys
        return np.asarray(key).copy()


class ReplicaLearnerDriver:
    """One data-parallel learner replica of the elastic plane
    (ISSUE 15): the composition of the grad/apply split
    (factory.build_replica_grad_apply), a LOCAL HBM-style PER ring
    (memory/device_per.DevicePerReplay — every replica holds the full
    ring; the merged write-backs keep the N rings ONE logical priority
    plane), and the lease-fenced, generation-stamped gradient exchange
    through the gateway registry (parallel/dcn.py).

    Determinism contract (the degraded-parity oracle's substrate):

    - **Params** are initialised from ``opt.seed`` identically on every
      replica; every applied update is the registry's reduced mean, so
      the N TrainStates can never diverge while membership is stable.
    - **Experience** is the deterministic shared stream: ingest rows are
      minted from a counter-keyed RNG (``np_rng(seed, "replica-ingest",
      counter)``) every replica advances identically, so the N rings
      hold the same rows.  (Sharding the gateway ingest across replicas
      is the named next ROADMAP step; this plane is the fault-tolerance
      composition it will ride on.)
    - **Keys**: round ``r``'s sample key is ``fold_in(fold_in(base, r),
      rank)`` with ``rank`` = this replica's index in the SORTED live
      membership of the previous completed round.  Rank folding — not
      world-size folding — is what makes degradation seamless: when N
      shrinks to 1, the survivor at rank 0 draws the EXACT key stream a
      solo driver draws, so from the degradation round onward it is
      bit-identical to the solo learner (tests/test_replicas.py).
    - **Priorities**: each round's |TD| write-back rides the round
      submission; the registry's reply carries every survivor's
      write-back in ascending-replica order and each replica applies
      ALL of them sequentially — identical scatter sequence, identical
      rings.  A fenced (stale-generation) write-back is a counted
      reject at the registry and never reaches any ring.

    Faults: the ``REPLICA_FAULTS`` env plane (utils/faults.py) is
    consulted once per round — ``kill@N`` / ``hang@N[:S]`` / ``crash@N``
    are the production drill verbs (tools/chaos_soak.py --kill-replica /
    --hang-replica)."""

    def __init__(self, opt: Options, spec: EnvSpec, replica_id: int,
                 channel, writer=None,
                 ingest_rows_per_round: int = 0):
        import jax

        from pytorch_distributed_tpu.factory import (
            build_replica_grad_apply, build_train_state_and_step,
        )
        from pytorch_distributed_tpu.memory.device_per import (
            DevicePerReplay,
        )

        self.opt = opt
        self.spec = spec
        self.replica = replica_id
        self.channel = channel
        self.writer = writer
        self.ingest_rows_per_round = ingest_rows_per_round
        ap = opt.agent_params
        mp_ = opt.memory_params
        model = build_model(opt, spec)
        params = init_params(opt, spec, model, seed=opt.seed)
        # state construction shared with the solo learner (identical
        # optimizer chain -> checkpoint-interchangeable TrainStates);
        # the returned fused step is discarded — replicas train through
        # the split halves
        state, _ = build_train_state_and_step(opt, spec, model, params)
        pair = build_replica_grad_apply(opt, model)
        assert pair is not None, (
            f"replica plane does not support agent_type={opt.agent_type}")
        grad_fn, apply_fn = pair
        self._grad = jax.jit(grad_fn)
        self._apply = jax.jit(apply_fn, donate_argnums=0)
        self.state = jax.device_put(state)
        self.replay = DevicePerReplay(
            mp_.memory_size, spec.state_shape, spec.action_shape,
            state_dtype=np.dtype(mp_.state_dtype),
            action_dtype=spec.action_dtype,
            priority_exponent=mp_.priority_exponent,
            importance_weight=mp_.priority_weight,
            importance_anneal_steps=ap.steps)
        # ONE base key stream shared by every replica (index 0 on
        # purpose: rank folding differentiates replicas, the stream
        # itself must be common property)
        self._base_key = jax.random.PRNGKey(
            process_seed(opt.seed, "replica-plane", 0))
        self.round = 0
        self.members: list = []
        self.key_log: list = []      # (round, raw key bytes)
        self.fence_events = 0
        self.rejoins = 0
        self._ingest_counter = 0
        self._recorder = flight_recorder.get_recorder(
            f"replica-{replica_id}")

    # -- deterministic shared ingest ----------------------------------------

    def _synth_chunk(self, rows: int) -> Any:
        """``rows`` transitions minted from the counter-keyed shared
        stream — identical bytes on every replica at the same counter."""
        from pytorch_distributed_tpu.utils.experience import Transition

        ap = self.opt.agent_params
        rng = np_rng(self.opt.seed, "replica-ingest",
                     self._ingest_counter)
        self._ingest_counter += 1
        shape = (rows,) + tuple(self.spec.state_shape)
        sdt = np.dtype(self.opt.memory_params.state_dtype)
        if sdt.kind == "u":
            s0 = rng.integers(0, 256, size=shape).astype(sdt)
            s1 = rng.integers(0, 256, size=shape).astype(sdt)
        else:
            s0 = rng.standard_normal(shape).astype(sdt)
            s1 = rng.standard_normal(shape).astype(sdt)
        if self.spec.discrete:
            action = rng.integers(
                0, max(1, self.spec.num_actions),
                size=(rows,)).astype(np.int32)
        else:
            action = rng.standard_normal(
                (rows, self.spec.action_dim)).astype(np.float32)
        return Transition(
            state0=s0,
            action=action,
            reward=rng.standard_normal(rows).astype(np.float32),
            gamma_n=np.full(rows, ap.gamma ** ap.nstep, np.float32),
            state1=s1,
            terminal1=(rng.random(rows) < 0.05).astype(np.float32),
        )

    def prefill(self, rows: int) -> None:
        self.replay.feed_chunk(self._synth_chunk(rows))

    # -- state capture / restore (the oracle + the rejoin leg) ---------------

    def snapshot(self) -> dict:
        import jax

        return {
            "state": jax.device_get(self.state),
            "ring": jax.device_get(self.replay.state),
            "round": self.round,
            "ingest_counter": self._ingest_counter,
        }

    def load_snapshot(self, snap: dict) -> None:
        import jax

        self.state = jax.device_put(snap["state"])
        self.replay.state = jax.device_put(snap["ring"])
        self.round = snap["round"]
        self._ingest_counter = snap["ingest_counter"]

    @property
    def lstep(self) -> int:
        import jax

        return int(jax.device_get(self.state.step))

    def _commit_epoch(self) -> int:
        extras = dict(
            learner_step=self.lstep,
            replica_round=self.round,
            replica_ingest_counter=self._ingest_counter,
        )
        ckpt.save_epoch(
            self.opt.model_name, state=self.state, memory=self.replay,
            extras=extras, retain=self.opt.agent_params.checkpoint_retain)
        return self.lstep

    # -- the round loop ------------------------------------------------------

    def _rank(self) -> int:
        if not self.members:
            return 0
        try:
            return sorted(self.members).index(self.replica)
        except ValueError:
            return 0

    def run_rounds(self, until_round: int, *, stop=None, faults=None,
                   capture=None, on_round=None, rejoin: bool = False,
                   stats_every: int = 0) -> None:
        """Drive rounds ``[self.round, until_round)``.  ``capture(r,
        driver)`` fires after round ``r`` is fully applied (state,
        ring, key log current).  ``rejoin=True`` turns a fence into the
        epoch-barrier rejoin path instead of an exception."""
        import jax

        from pytorch_distributed_tpu.parallel.dcn import (
            RSTAT_OK, ReplicaFenced,
        )
        from pytorch_distributed_tpu.parallel.learner import (
            ReplicaExchange,
        )

        inj = faults if faults is not None \
            else FaultInjector.from_env("replica")
        exchange = ReplicaExchange(self.channel)
        t_win = time.monotonic()
        r_win = self.round
        while self.round < until_round:
            if stop is not None and stop.is_set():
                return
            r = self.round
            # the production fault plane: kill@N / hang@N / crash@N /
            # delay@N:S fire HERE, once per round
            inj.frame(b"")
            if self.ingest_rows_per_round > 0:
                self.prefill(self.ingest_rows_per_round)
            rank = self._rank()
            key = jax.random.fold_in(
                jax.random.fold_in(self._base_key, r), rank)
            self.key_log.append((r, _key_data(key)))
            beta = self.replay.beta(self.lstep)
            batch = self.replay.sample(
                self.opt.agent_params.batch_size, key, beta=beta)
            grads, ok, _metrics, td_abs = self._grad(self.state, batch)
            pidx = np.asarray(jax.device_get(batch.index), np.int32)
            ptd = np.abs(np.asarray(jax.device_get(td_abs), np.float32))
            try:
                reply, reduced = exchange.exchange(
                    r, grads, ok=bool(float(jax.device_get(ok)) > 0),
                    pidx=pidx, ptd=ptd)
            except (ConnectionError, OSError) as e:
                raise ReplicaFenced(
                    f"replica {self.replica} lost the registry: {e}")
            if reply["status"] != RSTAT_OK:
                self.fence_events += 1
                self._recorder.record("replica-fenced", round=r,
                                      status=reply["status"])
                if rejoin:
                    self.rejoin()
                    continue
                raise ReplicaFenced(
                    f"replica {self.replica} fenced at round {r} "
                    f"(status {reply['status']})")
            self.members = list(reply["members"])
            if reduced is not None:
                self.state = self._apply(self.state, reduced,
                                         np.float32(1.0))
            # merged |TD| write-backs, applied in the reply's
            # deterministic order on EVERY replica — one logical
            # priority plane across N rings
            # (memory/device_per.per_apply_writeback_groups)
            from pytorch_distributed_tpu.memory.device_per import (
                per_apply_writeback_groups,
            )

            self.replay.state = per_apply_writeback_groups(
                self.replay.state,
                [(w[1], w[2]) for w in reply["writebacks"]],
                alpha=self.replay.alpha)
            self.round = r + 1
            if reply.get("epoch_due") and self._rank() == 0:
                step = self._commit_epoch()
                self.channel.note_epoch(r, step)
            if capture is not None:
                capture(r, self)
            if on_round is not None:
                on_round(r, reply)
            if stats_every and (r + 1) % stats_every == 0 \
                    and self.writer is not None:
                now = time.monotonic()
                self.writer.scalar(
                    "learner/updates_per_s",
                    (self.round - r_win) / max(now - t_win, 1e-9),
                    step=self.lstep)
                self.writer.scalar("replica/round", float(self.round),
                                   step=self.lstep)
                self.writer.flush()
                t_win, r_win = now, self.round

    # -- elastic rejoin ------------------------------------------------------

    def rejoin(self, timeout: float = 60.0) -> None:
        """Rejoin at a NEW generation: re-lease, wait for the join
        barrier's committed epoch, load that exact state (params, opt
        state, ring, counters), fast-forward to the join round, and
        activate — the survivors held the entry round for us."""
        from pytorch_distributed_tpu.parallel.dcn import ReplicaFenced

        reply = self.channel.acquire()
        self.rejoins += 1
        self.members = list(reply.get("members", []))
        self.channel.start_renewer()
        barrier = reply.get("epoch_barrier")
        self._recorder.record("rejoin", generation=reply["generation"],
                              barrier=barrier)
        if barrier is None:
            # no live peers = a fresh plane OR a whole-fleet restart
            # behind a fresh registry.  "Rejoin = fetch the latest
            # committed epoch": restore it exactly as the solo learner
            # would (resume="never" opts out, same contract), so a
            # supervisor-restarted replicated fleet never silently
            # retrains from seed-initialised params
            self.round = int(reply.get("round", 0))
            if self.opt.resume != "never":
                info = ckpt.resolve_epoch(self.opt.model_name)
                if info is not None:
                    import jax

                    self.state = jax.device_put(ckpt.load_epoch_state(
                        info, jax.device_get(self.state)))
                    if info.has_replay:
                        ckpt.load_epoch_replay(info, self.replay)
                    self.round = max(self.round, int(
                        info.extras.get("replica_round", 0)))
                    self._ingest_counter = int(info.extras.get(
                        "replica_ingest_counter",
                        self._ingest_counter))
                    print(f"[replica] {self.replica} resumed epoch "
                          f"{info.epoch} (step {info.learner_step}, "
                          f"round {self.round})", flush=True)
            return
        deadline = time.monotonic() + timeout
        epoch_step = None
        while time.monotonic() < deadline:
            j = self.channel.poll_join()
            if j is None:
                # join cancelled (timeout server-side): fenced again
                raise ReplicaFenced(
                    f"replica {self.replica} join cancelled")
            if j.get("epoch_step") is not None:
                epoch_step = int(j["epoch_step"])
                break
            time.sleep(0.05)
        if epoch_step is None:
            raise ReplicaFenced(
                f"replica {self.replica} barrier epoch never committed")
        info = ckpt.await_epoch(self.opt.model_name, epoch_step,
                                timeout=max(5.0, deadline
                                            - time.monotonic()))
        if info is None:
            raise ReplicaFenced(
                f"replica {self.replica} could not resolve the barrier "
                f"epoch (step >= {epoch_step})")
        import jax

        self.state = jax.device_put(ckpt.load_epoch_state(
            info, jax.device_get(self.state)))
        if info.has_replay:
            ckpt.load_epoch_replay(info, self.replay)
        self.round = int(reply.get("round",
                                   info.extras.get("replica_round", 0)))
        self._ingest_counter = int(info.extras.get(
            "replica_ingest_counter", self._ingest_counter))
        act = self.channel.activate(epoch_step)
        self.members = list(act.get("members", self.members))
        print(f"[replica] {self.replica} rejoined at generation "
              f"{self.channel.generation}, round {self.round} "
              f"(epoch step {epoch_step})", flush=True)


def run_replica_learner(opt: Options, spec: EnvSpec, process_ind: int,
                        memory: Any, param_store: ParamStore,
                        clock: GlobalClock, stats: LearnerStats,
                        replica_id: Optional[int] = None) -> None:
    """Production wrapper around ``ReplicaLearnerDriver``: the learner
    role of a replicated fleet.  Replica 0 is the LEAD — it runs in the
    gateway's own process and joins through a LocalReplicaChannel
    against the in-process registry (fleet.FleetTopology wires it);
    replicas >= 1 run on other hosts (``fleet.py --role
    learner-replica``) and dial ``replica_params.coordinator``.  The
    ``memory`` handle of the solo learner is not consumed — the replica
    plane's experience is the deterministic shared stream (driver
    docstring); a loud note says so once."""
    from pytorch_distributed_tpu.parallel import dcn as dcn_mod

    rp = dcn_mod.resolve_replica(opt.replica_params)
    rid = int(replica_id if replica_id is not None else process_ind)
    registry = dcn_mod.local_registry()
    if registry is not None:
        channel = dcn_mod.LocalReplicaChannel(registry, rid)
    else:
        host, _, port = rp.coordinator.rpartition(":")
        channel = dcn_mod.ReplicaClient((host, int(port)), rid,
                                        params=rp)
    ap = opt.agent_params
    timing_writer = MetricsWriter(opt.log_dir, enable_tensorboard=False,
                                  role="learner", run_id=opt.refs)
    driver = ReplicaLearnerDriver(opt, spec, rid, channel,
                                  writer=timing_writer,
                                  ingest_rows_per_round=0)
    if memory is not None:
        print(f"[replica] {rid}: the replica plane trains from the "
              f"deterministic shared stream; the local ingest queue is "
              f"drained but not consumed (sharded gateway ingest is the "
              f"next ROADMAP step)", flush=True)
    # the initial lease goes through the REJOIN path: a fresh plane
    # grants round 0 and falls through; a replacement process entering
    # mid-training gets the join barrier and syncs from the committed
    # epoch instead of bouncing a stale round 0 off the registry
    driver.rejoin(timeout=max(60.0, 4.0 * rp.join_timeout_s))
    channel.wait_members(rp.replicas,
                         timeout=4.0 * max(rp.lease_s, 0.5))
    driver.members = channel.members()
    if driver.round == 0:
        driver.prefill(min(max(ap.learn_start, ap.batch_size),
                           opt.memory_params.memory_size))

    import jax
    from jax.flatten_util import ravel_pytree

    from pytorch_distributed_tpu.factory import published_params

    def _publish() -> None:
        flat, _ = ravel_pytree(jax.device_get(
            published_params(opt, driver.state)))
        param_store.publish(np.asarray(flat, dtype=np.float32))

    _publish()

    def _on_round(r: int, reply: dict) -> None:
        clock.bump_progress("learner")
        clock.set_learner_step(driver.lstep)
        if ap.param_publish_freq and \
                (r + 1) % ap.param_publish_freq == 0:
            _publish()
        if ap.checkpoint_freq and (r + 1) % ap.checkpoint_freq == 0 \
                and driver._rank() == 0:
            driver._commit_epoch()
        if memory is not None and hasattr(memory, "drain"):
            # keep a hybrid topology's ingest queue from backing up
            # while the replica plane trains from the shared stream
            memory.drain()

    try:
        driver.run_rounds(ap.steps, stop=clock.stop,
                          on_round=_on_round, rejoin=(rid != 0),
                          stats_every=max(1, ap.learner_freq))
    finally:
        _publish()
        if driver._rank() == 0 and driver.round > 0:
            driver._commit_epoch()
        channel.release()
        channel.close()
        timing_writer.close()
