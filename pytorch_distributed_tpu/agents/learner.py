"""Learner process: the compute-critical update loop.

Re-design of reference core/single_processes/dqn_learner.py:50-95 /
ddpg_learner.py:50-106.  Same cadence contract — gate on
``memory.size > learn_start`` with a sleep spin (reference dqn_learner.py:
51,102-103), one sampled minibatch per step, target-net update folded into
the step, global learner clock increment (reference :94-95), loss stats on
the ``learner_freq`` cadence (reference :99-101) — but the update itself is
one pure jitted XLA program (ops/losses.py) dispatched through
``ShardedLearner``: batch dp-sharded over the mesh, gradients all-reduced
over ICI, params/opt-state donated so the TrainState updates in place in
HBM.  Where the reference's Adam writes become instantly visible through
shared CUDA storage (reference :87), here the learner explicitly publishes
versioned parameter snapshots every ``param_publish_freq`` steps.

A single learner process drives the whole mesh; the reference's
``num_learners > 1`` hogwild hook (unsynchronized racing Adam steps,
SURVEY.md "known quirks") maps to widening the mesh's dp axis instead.

PER additions (the reference's TODO): queue-fed single-owner buffer
(memory/feeder.py) drained each step, |TD| priority write-back after every
update.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from pytorch_distributed_tpu.config import Options
from pytorch_distributed_tpu.factory import (
    EnvSpec, build_model, build_train_state_and_step, init_params,
    published_params,
)
from pytorch_distributed_tpu.agents.clocks import GlobalClock, LearnerStats
from pytorch_distributed_tpu.agents.param_store import (
    ParamStore, make_flattener,
)
from pytorch_distributed_tpu.memory.device_replay import DeviceReplayIngest
from pytorch_distributed_tpu.memory.feeder import QueueOwner
from pytorch_distributed_tpu.utils import checkpoint as ckpt
from pytorch_distributed_tpu.utils.rngs import np_rng


def run_learner(opt: Options, spec: EnvSpec, process_ind: int, memory: Any,
                param_store: ParamStore, clock: GlobalClock,
                stats: LearnerStats) -> None:
    import jax
    from jax.flatten_util import ravel_pytree

    from pytorch_distributed_tpu.parallel.learner import ShardedLearner
    from pytorch_distributed_tpu.parallel.mesh import make_mesh

    ap = opt.agent_params
    pp = opt.parallel_params

    # ---- model + train state (reference dqn_learner.py:21-39) ----
    model = build_model(opt, spec)
    params = init_params(opt, spec, model, seed=opt.seed)
    if opt.model_file:
        # finetune-from-file (reference main.py:45)
        path = ckpt.params_path(opt.model_file) \
            if not opt.model_file.endswith(".msgpack") else opt.model_file
        params = ckpt.load_params(path, params)
    state, step_fn = build_train_state_and_step(opt, spec, model, params)

    mesh = None
    if len(jax.devices()) > 1:
        mesh = make_mesh(pp.dp_size, pp.mp_size)
    learner = ShardedLearner(step_fn, mesh, donate=pp.donate)
    state = learner.place(state)

    # resume full state if a prior run left one (the resume tier the
    # reference lacks, utils/checkpoint.py docstring)
    restored = ckpt.restore_train_state(opt.model_name, jax.device_get(state))
    if restored is not None:
        state = learner.place(restored)

    # ---- initial publication: actors block on version 1 ----
    def _publish(st) -> None:
        flat, _ = ravel_pytree(jax.device_get(published_params(opt, st)))
        param_store.publish(np.asarray(flat, dtype=np.float32))

    _publish(state)

    is_per = isinstance(memory, QueueOwner)
    is_device = isinstance(memory, DeviceReplayIngest)
    if is_device:
        # attach the HBM ring on the learner's mesh and fuse sampling into
        # the train step: one XLA program does gather-from-ring + forward +
        # backward + Adam + target update, so the hot loop never touches the
        # host (memory/device_replay.py docstring)
        from pytorch_distributed_tpu.memory.device_replay import sample_rows

        memory.attach(mesh=mesh)
        fused_step = jax.jit(
            lambda ts, rs, key: step_fn(
                ts, sample_rows(rs, key, ap.batch_size)),
            donate_argnums=(0,) if pp.donate else ())
        device_key = jax.random.PRNGKey(
            np_rng(opt.seed, "learner", process_ind).integers(2 ** 31))
        # the CPU backend's collective rendezvous needs per-step blocking
        # (see ShardedLearner.step)
        block_each_step = (mesh is not None
                           and mesh.devices.flat[0].platform == "cpu")

    rng = np_rng(opt.seed, "learner", process_ind)
    lstep = int(jax.device_get(state.step))
    clock.set_learner_step(lstep)

    # ---- gate until the replay warms up (reference dqn_learner.py:51) ----
    # clamped to capacity: a learn_start >= memory_size would otherwise spin
    # forever since a full ring's size never exceeds its capacity
    learn_start = min(ap.learn_start, opt.memory_params.memory_size - 1)
    while not clock.done(ap.steps) and memory_size(memory) <= learn_start:
        time.sleep(0.05)

    # metric refs are collected per step without forcing a device sync and
    # converted to floats only on the learner_freq cadence
    pending_metrics = []
    t_cadence = time.monotonic()

    while lstep < ap.steps and not clock.stop.is_set():
        if is_device:
            memory.drain()
            device_key, sub = jax.random.split(device_key)
            state, metrics, td_abs = fused_step(state, memory.replay.state,
                                                sub)
            if block_each_step:
                jax.block_until_ready(state.params)
        else:
            if is_per:
                memory.drain()
            batch = memory.sample(ap.batch_size, rng)
            state, metrics, td_abs = learner.step(state, batch)
            if is_per:
                memory.update_priorities(np.asarray(batch.index),
                                         np.asarray(td_abs))
        lstep += 1
        clock.set_learner_step(lstep)  # reference dqn_learner.py:94-95
        pending_metrics.append(metrics)

        if lstep % ap.param_publish_freq == 0:
            _publish(state)
        if ap.checkpoint_freq and lstep % ap.checkpoint_freq == 0:
            ckpt.save_train_state(opt.model_name, state)

        if lstep % ap.learner_freq == 0:  # reference dqn_learner.py:99-101
            now = time.monotonic()
            vals = {k: float(np.mean([float(m[k]) for m in pending_metrics]))
                    for k in pending_metrics[-1]}
            pending_metrics = []
            stats.add(
                counter=1,
                critic_loss=vals.get("learner/critic_loss", 0.0),
                actor_loss=vals.get("learner/actor_loss", 0.0),
                q_mean=vals.get("learner/q_mean", 0.0),
                grad_norm=vals.get("learner/grad_norm", 0.0),
                steps_per_sec=ap.learner_freq / max(now - t_cadence, 1e-9),
            )
            t_cadence = now

    # final publication + full-state checkpoint so a next run can resume
    _publish(state)
    ckpt.save_train_state(opt.model_name, state)


def memory_size(memory: Any) -> int:
    if hasattr(memory, "drain"):
        memory.drain()
    return memory.size
