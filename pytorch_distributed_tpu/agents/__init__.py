"""Worker-process layer.

TPU-native re-design of the reference's ``core/single_processes/`` package:
the same five process roles per agent family — actor, learner, evaluator,
tester, logger (reference utils/factory.py:22-31) — but communicating by
explicit message passing (versioned parameter publication + shared/queued
replay feeds + counter structs) instead of implicitly shared CUDA storage
(SURVEY.md §2 "distributed communication backend").
"""

from pytorch_distributed_tpu.agents.clocks import (
    ActorStats, EvaluatorStats, GlobalClock, LearnerStats,
)
from pytorch_distributed_tpu.agents.param_store import ParamStore

__all__ = [
    "GlobalClock", "ActorStats", "LearnerStats", "EvaluatorStats",
    "ParamStore",
]
