"""Recurrent (R2D2) rollout workers.

Same Ape-X topology as agents/actor.py — vectorized envs, per-slot epsilon
schedule, versioned weight pulls, stat cadences — but the policy carries an
LSTM state across steps and experience leaves as overlapping episode
SEGMENTS (memory/sequence_replay.py SegmentBuilder), not n-step
transitions.  The carry recorded with each step is the state BEFORE acting,
which is what the stored-state burn-in strategy replays from
(ops/sequence_losses.py docstring).

The hot loop rides the shared scheduler (agents/actor._drive_actor_loop),
so the recurrent family gets the same inline/pipelined split as the flat
ones (ISSUE 4).  Pipelining a recurrent policy adds one wrinkle: the
carry.  It stays DEVICE-RESIDENT across ticks inside the engine — no
host->device upload per tick — and episode resets ride into the NEXT
tick's fused act as a per-row boolean mask
(models/policies.build_recurrent_packed_act), which zeroes exactly the
rows the serial loop used to zero host-side between ticks.  The host
keeps a copy of each tick's post-act carry for segment storage; its
terminal rows are zeroed by ``advance`` (as before), so the host copy and
the device carry agree on every episode boundary.  ``actor_backend=
batched`` is NOT served for this family — per-env recurrent state on a
shared server is a different design — and downgrades to ``pipelined``
(factory.resolve_actor_backend).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from pytorch_distributed_tpu.config import Options
from pytorch_distributed_tpu.factory import (
    EnvSpec, resolve_actor_backend, sequence_pack_frames,
)
from pytorch_distributed_tpu.agents.actor import (
    _ActorHarness, _drive_actor_loop,
)
from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.memory.sequence_replay import SegmentBuilder
from pytorch_distributed_tpu.utils.experience import make_prov
from pytorch_distributed_tpu.utils.helpers import pin_to_cpu
from pytorch_distributed_tpu.utils.rngs import process_key


class _RecurrentHarness(_ActorHarness):
    """Actor harness with the n-step assemblers swapped for per-env
    SegmentBuilders."""

    def __init__(self, opt: Options, spec: EnvSpec, process_ind: int,
                 memory: Any, param_store: ParamStore, clock: GlobalClock,
                 stats: ActorStats, backend: str = "pipelined"):
        super().__init__(opt, spec, process_ind, memory, param_store, clock,
                         stats, backend=backend)
        ap = self.ap
        state_dtype = (np.uint8 if opt.memory_params.state_dtype == "uint8"
                       else np.float32)
        self.builders = [
            SegmentBuilder(ap.seq_len, ap.seq_overlap,
                           state_dtype=state_dtype,
                           pack_frames=sequence_pack_frames(opt))
            for _ in range(self.num_envs)]
        # initial-carry rows precomputed host-side once so per-episode
        # resets never allocate on the accelerator
        self._init_carry = tuple(np.asarray(c)
                                 for c in self.model.zero_carry(1))

    # segments replace transitions: override the per-env feed
    def advance(self, actions, next_obs, rewards, terminals, infos,
                carry_before=None, carry_after=None) -> None:
        state_for_segment = getattr(self.model, "state_for_segment", None)
        for j in range(self.num_envs):
            true_next = infos[j].get("final_obs", next_obs[j])
            truncated = bool(infos[j].get("truncated", False))
            # stored state for the segment: the LSTM carry row, unless the
            # model substitutes its own (transformers store a placeholder)
            per_env_carry = (state_for_segment(carry_before, j)
                             if state_for_segment is not None
                             else (carry_before[0][j], carry_before[1][j]))
            for seg in self.builders[j].push(
                    self._obs[j], int(actions[j]), float(rewards[j]),
                    # time-limit truncation ends the segment but must
                    # bootstrap through (not a death) — same distinction
                    # the n-step assembler draws for feed()
                    bool(terminals[j]) and not truncated, true_next,
                    per_env_carry, episode_end=bool(terminals[j]),
                    prov=make_prov(self.process_ind, j,
                                   self._feed_version, self._birth_step)):
                self.memory.feed(seg, None)
            self.episode_steps[j] += 1
            self.episode_reward[j] += float(rewards[j])
            if terminals[j]:
                self._record_episode(j, infos[j])
                # fresh episode: zero the HOST copy's rows (the engine's
                # carry_before for the next tick); the DEVICE carry rows
                # are zeroed by the reset mask inside the next fused act
                for c_row, c_init in zip(carry_after, self._init_carry):
                    c_row[j] = c_init[0]
                self.builders[j].reset()
        self._obs = next_obs
        self._flush_cadence()

    # shutdown: the base _ActorHarness.shutdown is used as-is (its
    # pending-holds loop is a no-op here — segments carry no deferred
    # priorities) — a copied override once missed the QueueFeeder.close
    # fix and hung the config-14 probe's join for 240 s.


class _RecurrentEngine:
    """Fused recurrent act with a device-resident carry.

    ``submit`` advances the device carry (resetting masked rows
    on-device) and returns (action, carry') handles without blocking;
    ``collect`` syncs the action plus a mutable host copy of the
    post-act carry — ``carry_after`` for segment storage — and rotates
    it into ``carry_before`` for the next tick.  ``advance`` zeroes the
    host copy's terminal rows in place, mirroring the device-side mask
    reset, so the two stay equal at every episode boundary."""

    def __init__(self, h: _RecurrentHarness, base_key, eps):
        import jax.numpy as jnp

        from pytorch_distributed_tpu.models.policies import (
            build_recurrent_packed_act,
        )

        self._h = h
        self._act = build_recurrent_packed_act(h.model.apply,
                                               h.model.zero_carry(1))
        self._key = pin_to_cpu(base_key)
        self._eps = pin_to_cpu(jnp.asarray(eps, jnp.float32))
        # distinct leaf buffers, explicitly: zero_carry may alias its
        # leaves (DrqnMlpModel returns (z, z)), and the fused act DONATES
        # the carry — the same buffer donated twice is an XLA error
        self._dev_carry = pin_to_cpu(tuple(
            jnp.array(c, copy=True) for c in h.model.zero_carry(h.num_envs)))
        self._host_carry = tuple(np.asarray(c)
                                 for c in h.model.zero_carry(h.num_envs))

    def submit(self, obs, tick, reset_mask):
        action, carry = self._act(self._h.params, obs, self._dev_carry,
                                  np.ascontiguousarray(reset_mask),
                                  self._key, tick, self._eps)
        self._dev_carry = carry
        action.copy_to_host_async()
        return action, carry

    def collect(self, pending):
        action, carry = pending
        # np.array (copy): zero-copy views of jax buffers are read-only,
        # and advance() writes per-env reset rows in place
        carry_after = tuple(np.array(c) for c in carry)
        extras = dict(carry_before=self._host_carry,
                      carry_after=carry_after)
        self._host_carry = carry_after
        return np.asarray(action).astype(np.int64), extras

    def jit_cache_size(self) -> Optional[int]:
        return self._act._cache_size()

    def close(self) -> None:
        pass


def run_r2d2_actor(opt: Options, spec: EnvSpec, process_ind: int,
                   memory: Any, param_store: ParamStore, clock: GlobalClock,
                   stats: ActorStats, inference: Any = None):
    """eps-greedy recurrent rollout worker, batched over the env vector."""
    from pytorch_distributed_tpu.models.policies import apex_epsilons

    backend = resolve_actor_backend(opt, inference)
    h = _RecurrentHarness(opt, spec, process_ind, memory, param_store,
                          clock, stats, backend=backend)
    eps = apex_epsilons(process_ind, opt.num_actors, h.num_envs,
                        h.ap.eps, h.ap.eps_alpha)
    engine = _RecurrentEngine(
        h, process_key(opt.seed, "actor", process_ind), eps)
    return _drive_actor_loop(h, engine, clock,
                             pipelined=(backend != "inline"))
