"""Recurrent (R2D2) rollout workers.

Same Ape-X topology as agents/actor.py — vectorized envs, per-slot epsilon
schedule, versioned weight pulls, stat cadences — but the policy carries an
LSTM state across steps and experience leaves as overlapping episode
SEGMENTS (memory/sequence_replay.py SegmentBuilder), not n-step
transitions.  The carry recorded with each step is the state BEFORE acting,
which is what the stored-state burn-in strategy replays from
(ops/sequence_losses.py docstring).

Episode boundaries reset both the env slot's carry (to the model's zero
state) and its segment stream.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from pytorch_distributed_tpu.config import Options
from pytorch_distributed_tpu.factory import (
    EnvSpec, build_env_vector, build_model, init_params,
    sequence_pack_frames,
)
from pytorch_distributed_tpu.agents.actor import _ActorHarness
from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.memory.sequence_replay import SegmentBuilder
from pytorch_distributed_tpu.utils.rngs import process_key


class _RecurrentHarness(_ActorHarness):
    """Actor harness with the n-step assemblers swapped for per-env
    SegmentBuilders and a persistent LSTM carry per env slot."""

    def __init__(self, opt: Options, spec: EnvSpec, process_ind: int,
                 memory: Any, param_store: ParamStore, clock: GlobalClock,
                 stats: ActorStats):
        super().__init__(opt, spec, process_ind, memory, param_store, clock,
                         stats)
        ap = self.ap
        state_dtype = (np.uint8 if opt.memory_params.state_dtype == "uint8"
                       else np.float32)
        self.builders = [
            SegmentBuilder(ap.seq_len, ap.seq_overlap,
                           state_dtype=state_dtype,
                           pack_frames=sequence_pack_frames(opt))
            for _ in range(self.num_envs)]
        # one batched carry; per-env rows reset at episode ends.  The
        # initial-carry rows are precomputed host-side once so per-episode
        # resets never allocate on the accelerator
        self.carry = tuple(np.asarray(c) for c in
                           self.model.zero_carry(self.num_envs))
        self._init_carry = tuple(np.asarray(c)
                                 for c in self.model.zero_carry(1))

    # segments replace transitions: override the per-env feed
    def advance(self, actions, next_obs, rewards, terminals, infos,
                carry_before=None, carry_after=None) -> None:
        state_for_segment = getattr(self.model, "state_for_segment", None)
        for j in range(self.num_envs):
            true_next = infos[j].get("final_obs", next_obs[j])
            truncated = bool(infos[j].get("truncated", False))
            # stored state for the segment: the LSTM carry row, unless the
            # model substitutes its own (transformers store a placeholder)
            per_env_carry = (state_for_segment(carry_before, j)
                             if state_for_segment is not None
                             else (carry_before[0][j], carry_before[1][j]))
            for seg in self.builders[j].push(
                    self._obs[j], int(actions[j]), float(rewards[j]),
                    # time-limit truncation ends the segment but must
                    # bootstrap through (not a death) — same distinction
                    # the n-step assembler draws for feed()
                    bool(terminals[j]) and not truncated, true_next,
                    per_env_carry, episode_end=bool(terminals[j])):
                self.memory.feed(seg, None)
            self.episode_steps[j] += 1
            self.episode_reward[j] += float(rewards[j])
            if terminals[j]:
                self._record_episode(j, infos[j])
                # fresh episode: model-defined initial carry + fresh
                # segment stream (host-side copy of the precomputed rows)
                for c_row, c_init in zip(carry_after, self._init_carry):
                    c_row[j] = c_init[0]
                self.builders[j].reset()
        self._obs = next_obs
        self.carry = carry_after
        self._run_cadences()

    # shutdown: the base _ActorHarness.shutdown is used as-is (its
    # pending-holds loop is a no-op here — segments carry no deferred
    # priorities) — a copied override once missed the QueueFeeder.close
    # fix and hung the config-14 probe's join for 240 s.


def run_r2d2_actor(opt: Options, spec: EnvSpec, process_ind: int,
                   memory: Any, param_store: ParamStore, clock: GlobalClock,
                   stats: ActorStats) -> None:
    """eps-greedy recurrent rollout worker, batched over the env vector."""
    import jax

    from pytorch_distributed_tpu.models.policies import (
        apex_epsilons, build_recurrent_epsilon_greedy_act,
    )

    h = _RecurrentHarness(opt, spec, process_ind, memory, param_store,
                          clock, stats)
    act = build_recurrent_epsilon_greedy_act(h.model.apply)
    eps = apex_epsilons(process_ind, opt.num_actors, h.num_envs,
                        h.ap.eps, h.ap.eps_alpha)
    from pytorch_distributed_tpu.utils.helpers import pin_to_cpu

    key = pin_to_cpu(process_key(opt.seed, "actor", process_ind))

    h.start()
    while not clock.done(h.ap.steps):
        key, sub = jax.random.split(key)
        carry_before = h.carry
        with h.timer.phase("act"):
            a, carry_after = act(h.params, h._obs, carry_before, sub, eps)
            actions = np.asarray(a)
            # np.array (copy): zero-copy views of jax buffers are
            # read-only, and episode resets write per-env rows in place.
            # Stays a tuple: flipping the carry's pytree container type
            # would retrace the jitted act on the second tick.
            carry_after = tuple(np.array(c) for c in carry_after)
        with h.timer.phase("env"):
            next_obs, rewards, terminals, infos = h.env.step(actions)
        with h.timer.phase("advance"):
            h.advance(actions, next_obs, rewards, terminals, infos,
                      carry_before=carry_before, carry_after=carry_after)
    h.shutdown()
