"""Evaluator process: periodic greedy evaluation + checkpointing.

Re-design of reference core/single_processes/evaluators.py (shared by both
agent families, reference utils/factory.py:28-29): every
``evaluator_freq`` seconds pull the freshest published weights, run
``evaluator_nepisodes`` greedy episodes in ``env.eval()`` mode, hand the
stats to the logger through the EvaluatorStats flag handshake (reference
:90-95), and write the params-only checkpoint — the reference's only
checkpoint writer (reference :97-100).

CAPTURE is decoupled from EVALUATION (no reference equivalent; the
reference's single loop is also its cadence).  A background thread
snapshots (weights, learner_step, wall) on the ``evaluator_freq`` cadence
— a cheap shared-memory copy that holds its schedule even when this
process is starved of CPU (``evaluator_nice`` on a 1-core host stretched
the old eval-inline cadence from ~60 s to ~10 min and made a north-star
run's +18 crossing timestamp a sampling artifact, RESULTS.md round 3) —
while the expensive greedy episodes drain the snapshot backlog in order
and publish each result against its CAPTURE step and wall time.  Under
sustained starvation the backlog drops its oldest pending snapshots
(bounded lag), but every published point still carries the step/time the
policy actually existed, so learning-curve crossings are exact regardless
of how slowly the episodes themselves got scheduled.
"""

from __future__ import annotations

import time
from typing import Any, Tuple

import numpy as np

from pytorch_distributed_tpu.config import Options
from pytorch_distributed_tpu.factory import (
    EnvSpec, build_env, build_model, init_params,
)
from pytorch_distributed_tpu.agents.clocks import EvaluatorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import (
    ParamStore, make_flattener,
)
from pytorch_distributed_tpu.utils import checkpoint as ckpt
from pytorch_distributed_tpu.utils.helpers import unravel_on_cpu
from pytorch_distributed_tpu.utils.rngs import process_seed


def greedy_episodes(opt: Options, spec: EnvSpec, model, params, env,
                    nepisodes: int) -> Tuple[float, float, int]:
    """Run n greedy episodes; returns (avg_steps, avg_reward, solved).
    Greedy = eps 0 for DQN (reference evaluators.py:56-86), noiseless policy
    forward for DDPG, zero-carry recurrent greedy for R2D2."""
    from pytorch_distributed_tpu.utils.helpers import pin_to_cpu

    # greedy eval is host-side inference: pin params (and any carry) to the
    # CPU device so batch-1 forwards never round-trip the learner's chip
    params = pin_to_cpu(params)
    on_reset = lambda: None  # recurrent policies re-bind this per episode
    if opt.agent_type == "dqn":
        from pytorch_distributed_tpu.models.policies import build_greedy_act

        act = build_greedy_act(model.apply)

        def pick(obs):
            a, _ = act(params, obs[None])
            return int(a[0])
    elif opt.agent_type == "r2d2":
        from pytorch_distributed_tpu.models.policies import (
            build_recurrent_greedy_act,
        )

        ract = build_recurrent_greedy_act(model.apply)
        carry_box = [pin_to_cpu(model.zero_carry(1))]

        def pick(obs):
            a, carry_box[0] = ract(params, obs[None], carry_box[0])
            return int(a[0])

        def _reset_carry():
            carry_box[0] = pin_to_cpu(model.zero_carry(1))
        on_reset = _reset_carry
    else:
        from pytorch_distributed_tpu.models.policies import build_ddpg_act

        dact = build_ddpg_act(
            lambda p, o: model.apply(p, o, method=model.forward_actor))

        def pick(obs):
            return np.asarray(dact(params, obs[None]))[0]

    total_steps, total_reward, solved = 0, 0.0, 0
    for _ in range(nepisodes):
        on_reset()
        obs = env.reset()
        env.render()  # no-op unless a FrameDumper is attached
        ep_reward, ep_steps, terminal, info = 0.0, 0, False, {}
        while not terminal:
            obs, r, terminal, info = env.step(pick(obs))
            env.render()
            ep_reward += float(r)
            ep_steps += 1
        total_steps += ep_steps
        total_reward += ep_reward
        solved += int(bool(info.get("solved", ep_reward > 0)))
    return total_steps / nepisodes, total_reward / nepisodes, solved


def run_evaluator(opt: Options, spec: EnvSpec, process_ind: int, memory: Any,
                  param_store: ParamStore, clock: GlobalClock,
                  stats: EvaluatorStats) -> None:
    ap = opt.agent_params
    # seed slot past the whole actor fleet (actors hold slots
    # 0 .. num_actors*num_envs_per_actor - 1)
    fleet = opt.num_actors * max(1, opt.env_params.num_envs_per_actor)
    env = build_env(opt, process_ind=fleet + 1)
    env.eval()  # standard episode boundaries (reference evaluators.py:19)
    if opt.env_params.render:
        from pytorch_distributed_tpu.utils.render import attach_frame_dumper

        attach_frame_dumper(env, opt.log_dir, "evaluator")
    model = build_model(opt, spec)
    params0 = init_params(opt, spec, model, seed=process_seed(
        opt.seed, "evaluator"))
    _, unravel = make_flattener(params0)

    # best-so-far lives on the shared clock, not a process-local: the
    # learner binds it into every checkpoint epoch and restores it before
    # its first publication (agents/learner.py), so a resumed run's dips
    # can never overwrite <refs>_best.msgpack with a worse policy than
    # the pre-crash best (the reference has no best tier at all)
    if clock.best_eval_reward.value > float("-inf"):
        print(f"[evaluator] best-so-far restored: "
              f"{clock.best_eval_reward.value:g}")

    # ---- capture thread: cadence-true weight snapshots -------------------
    # (flat, learner_step, wall) tuples, oldest first.  MAX_BACKLOG bounds
    # both memory and staleness: under sustained CPU starvation the oldest
    # pending snapshots drop, so evaluated points thin to what the host
    # affords while each keeps its true capture attribution.
    import threading
    from collections import deque

    MAX_BACKLOG = 8
    snapshots: deque = deque()
    snap_lock = threading.Lock()

    def capture_loop() -> None:
        version = 0
        flat = None
        last_cap = float("-inf")  # capture immediately once weights exist
        while not clock.done(ap.steps):
            time.sleep(0.25)
            if time.monotonic() - last_cap < ap.evaluator_freq:
                continue
            got = param_store.fetch(version)
            if got is not None:
                flat, version = got
            if flat is None:
                continue  # learner hasn't published yet
            last_cap = time.monotonic()
            with snap_lock:
                if len(snapshots) >= MAX_BACKLOG:
                    snapshots.popleft()
                # re-capturing an unchanged flat at a new step is still a
                # new curve point (the policy existed unchanged there)
                snapshots.append((flat, clock.learner_step.value,
                                  time.time()))

    cap_thread = threading.Thread(target=capture_loop, name="eval-capture",
                                  daemon=True)
    cap_thread.start()

    def evaluate(flat: np.ndarray, at_step: int, at_wall: float) -> None:
        # host-side inference: unravel straight onto the CPU device
        # (actors do the same; see utils/helpers.py pin_to_cpu)
        params = unravel_on_cpu(unravel, flat)
        avg_steps, avg_reward, solved = greedy_episodes(
            opt, spec, model, params, env, ap.evaluator_nepisodes)
        # the logger's handshake slot holds ONE result; when a drained
        # backlog produces evals faster than its 0.2 s poll, wait for the
        # slot instead of overwriting an unconsumed point
        waited = time.monotonic() + 10.0
        while stats.flag.value and time.monotonic() < waited \
                and not clock.stop.is_set():
            time.sleep(0.05)
        stats.publish(
            at_step,
            wall=at_wall,
            avg_steps=avg_steps,
            avg_reward=avg_reward,
            nepisodes=float(ap.evaluator_nepisodes),
            nepisodes_solved=float(solved),
        )
        # the params-only checkpoint (reference evaluators.py:97-100);
        # snapshots evaluate oldest-first, so the last write is newest
        ckpt.save_params(ckpt.params_path(opt.model_name), params)
        # best-so-far tier (no reference equivalent): value curves dip —
        # DQN evals can transiently collapse right after a peak — and the
        # latest-params tier alone would let a run that ends mid-dip
        # overwrite its own best policy.  <refs>_best.msgpack always
        # holds the weights of the highest eval so far — ACROSS resumes,
        # via the clock-shared score the checkpoint epochs persist.
        with clock.best_eval_reward.get_lock():
            is_best = avg_reward > clock.best_eval_reward.value
            if is_best:
                clock.best_eval_reward.value = avg_reward
        if is_best:
            # sidecar BEFORE the weights: a crash between the two writes
            # then leaves the score ahead of the file — a conservative
            # threshold that can only delay the next best-write, never
            # let a worse policy overwrite a better one (the reverse
            # order would; checkpoint.py save_best_score docstring)
            ckpt.save_best_score(opt.model_name, avg_reward, step=at_step)
            ckpt.save_params(
                ckpt.params_path(opt.model_name + "_best"), params)

    def pop_snapshot():
        with snap_lock:
            return snapshots.popleft() if snapshots else None

    # hang-watchdog liveness mark (utils/supervision.ProgressBoard):
    # bumped on every poll and after every eval, so a stuck episode —
    # not a merely starved evaluator — is what goes stale
    bump = getattr(clock, "bump_progress", lambda label: None)
    try:
        while not clock.done(ap.steps):
            bump("evaluator-0")
            snap = pop_snapshot()
            if snap is None:
                time.sleep(0.1)
                continue
            evaluate(*snap)
            bump("evaluator-0")
        # final eval of the FINISHED weights (short runs may never have hit
        # the cadence; the run's acceptance signal must still be written):
        # always fetch fresh — a pending backlog snapshot can be up to
        # evaluator_freq stale, and the final <refs>.msgpack is what
        # mode-2/resume loads.  Backlog only as a fallback when the fetch
        # has nothing (learner died before its final publication).
        cap_thread.join(timeout=2.0)
        got = param_store.fetch(0)
        if got is not None:
            snap = (got[0], clock.learner_step.value, time.time())
        else:
            with snap_lock:
                snap = snapshots.pop() if snapshots else None
        if snap is not None:
            evaluate(*snap)
    finally:
        stats.done.value = 1
