"""Evaluator process: periodic greedy evaluation + checkpointing.

Re-design of reference core/single_processes/evaluators.py (shared by both
agent families, reference utils/factory.py:28-29): wake on a short poll,
every ``evaluator_freq`` seconds pull the freshest published weights, run
``evaluator_nepisodes`` greedy episodes in ``env.eval()`` mode, hand the
stats to the logger through the EvaluatorStats flag handshake (reference
:90-95), and write the params-only checkpoint — the reference's only
checkpoint writer (reference :97-100).
"""

from __future__ import annotations

import time
from typing import Any, Tuple

import numpy as np

from pytorch_distributed_tpu.config import Options
from pytorch_distributed_tpu.factory import (
    EnvSpec, build_env, build_model, init_params,
)
from pytorch_distributed_tpu.agents.clocks import EvaluatorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import (
    ParamStore, make_flattener,
)
from pytorch_distributed_tpu.utils import checkpoint as ckpt
from pytorch_distributed_tpu.utils.helpers import unravel_on_cpu
from pytorch_distributed_tpu.utils.rngs import process_seed


def greedy_episodes(opt: Options, spec: EnvSpec, model, params, env,
                    nepisodes: int) -> Tuple[float, float, int]:
    """Run n greedy episodes; returns (avg_steps, avg_reward, solved).
    Greedy = eps 0 for DQN (reference evaluators.py:56-86), noiseless policy
    forward for DDPG, zero-carry recurrent greedy for R2D2."""
    from pytorch_distributed_tpu.utils.helpers import pin_to_cpu

    # greedy eval is host-side inference: pin params (and any carry) to the
    # CPU device so batch-1 forwards never round-trip the learner's chip
    params = pin_to_cpu(params)
    on_reset = lambda: None  # recurrent policies re-bind this per episode
    if opt.agent_type == "dqn":
        from pytorch_distributed_tpu.models.policies import build_greedy_act

        act = build_greedy_act(model.apply)

        def pick(obs):
            a, _ = act(params, obs[None])
            return int(a[0])
    elif opt.agent_type == "r2d2":
        from pytorch_distributed_tpu.models.policies import (
            build_recurrent_greedy_act,
        )

        ract = build_recurrent_greedy_act(model.apply)
        carry_box = [pin_to_cpu(model.zero_carry(1))]

        def pick(obs):
            a, carry_box[0] = ract(params, obs[None], carry_box[0])
            return int(a[0])

        def _reset_carry():
            carry_box[0] = pin_to_cpu(model.zero_carry(1))
        on_reset = _reset_carry
    else:
        from pytorch_distributed_tpu.models.policies import build_ddpg_act

        dact = build_ddpg_act(
            lambda p, o: model.apply(p, o, method=model.forward_actor))

        def pick(obs):
            return np.asarray(dact(params, obs[None]))[0]

    total_steps, total_reward, solved = 0, 0.0, 0
    for _ in range(nepisodes):
        on_reset()
        obs = env.reset()
        env.render()  # no-op unless a FrameDumper is attached
        ep_reward, ep_steps, terminal, info = 0.0, 0, False, {}
        while not terminal:
            obs, r, terminal, info = env.step(pick(obs))
            env.render()
            ep_reward += float(r)
            ep_steps += 1
        total_steps += ep_steps
        total_reward += ep_reward
        solved += int(bool(info.get("solved", ep_reward > 0)))
    return total_steps / nepisodes, total_reward / nepisodes, solved


def run_evaluator(opt: Options, spec: EnvSpec, process_ind: int, memory: Any,
                  param_store: ParamStore, clock: GlobalClock,
                  stats: EvaluatorStats) -> None:
    ap = opt.agent_params
    # seed slot past the whole actor fleet (actors hold slots
    # 0 .. num_actors*num_envs_per_actor - 1)
    fleet = opt.num_actors * max(1, opt.env_params.num_envs_per_actor)
    env = build_env(opt, process_ind=fleet + 1)
    env.eval()  # standard episode boundaries (reference evaluators.py:19)
    if opt.env_params.render:
        from pytorch_distributed_tpu.utils.render import attach_frame_dumper

        attach_frame_dumper(env, opt.log_dir, "evaluator")
    model = build_model(opt, spec)
    params0 = init_params(opt, spec, model, seed=process_seed(
        opt.seed, "evaluator"))
    _, unravel = make_flattener(params0)

    version = 0
    params = None
    best_reward = float("-inf")

    def evaluate() -> None:
        nonlocal version, params, best_reward
        got = param_store.fetch(version)
        if got is not None:
            flat, version = got
            # host-side inference: unravel straight onto the CPU device
            # (actors do the same; see utils/helpers.py pin_to_cpu)
            params = unravel_on_cpu(unravel, flat)
        if params is None:
            return  # learner hasn't published yet
        avg_steps, avg_reward, solved = greedy_episodes(
            opt, spec, model, params, env, ap.evaluator_nepisodes)
        stats.publish(
            clock.learner_step.value,
            avg_steps=avg_steps,
            avg_reward=avg_reward,
            nepisodes=float(ap.evaluator_nepisodes),
            nepisodes_solved=float(solved),
        )
        # the params-only checkpoint (reference evaluators.py:97-100)
        ckpt.save_params(ckpt.params_path(opt.model_name), params)
        # best-so-far tier (no reference equivalent): value curves dip —
        # DQN evals can transiently collapse right after a peak — and the
        # latest-params tier alone would let a run that ends mid-dip
        # overwrite its own best policy.  <refs>_best.msgpack always
        # holds the weights of the highest eval so far.
        if avg_reward > best_reward:
            best_reward = avg_reward
            ckpt.save_params(
                ckpt.params_path(opt.model_name + "_best"), params)

    try:
        last_eval = 0.0  # evaluate immediately once weights exist
        while not clock.done(ap.steps):
            time.sleep(0.25)  # reference evaluators.py wakes every 5 s
            if time.monotonic() - last_eval < ap.evaluator_freq:
                continue
            last_eval = time.monotonic()
            evaluate()
        # final eval of the finished weights (short runs may never have hit
        # the cadence; the run's acceptance signal must still be written)
        evaluate()
    finally:
        stats.done.value = 1
