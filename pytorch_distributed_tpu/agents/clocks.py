"""Cross-process clocks and stat accumulators.

Equivalent of the reference's shared log-counter structs
(reference core/single_processes/logs.py): every field is a
``multiprocessing.Value`` from the spawn context, so one instance created by
the orchestrator is addressable from every worker, whether workers are OS
processes (production) or threads (tests).  As in the reference, the
**learner step is the global clock** that terminates every loop
(reference logs.py:6, dqn_actor.py:62), and actor/learner stats are
push-accumulated by workers then drained-and-reset by the logger on its
cadence (reference dqn_logger.py:34-56).
"""

from __future__ import annotations

import multiprocessing as mp

_CTX = mp.get_context("spawn")


class GlobalClock:
    """The global step counters (reference logs.py:3-6)."""

    def __init__(self):
        self.actor_step = _CTX.Value("l", 0, lock=True)
        self.learner_step = _CTX.Value("l", 0, lock=True)
        # Best evaluator reward so far — shared so (a) the learner can bind
        # it into every checkpoint epoch (utils/checkpoint.py save_epoch
        # extras) and (b) a resumed run's evaluator can't clobber
        # ``<refs>_best.msgpack`` with a worse policy: the learner restores
        # this from the epoch before its first publication, ahead of any
        # eval (agents/evaluator.py reads it per comparison).
        self.best_eval_reward = _CTX.Value("d", float("-inf"), lock=True)
        # Cooperative shutdown — the supervision layer the reference lacks
        # (SURVEY.md §5 "failure detection: none"): a dead learner there
        # stalls the clock and every loop spins forever; here the runtime
        # sets this flag when any worker dies or the run completes.
        self.stop = _CTX.Event()
        # Health-sentinel counters (utils/health.py): written by the
        # learner, read by the T_STATUS health plane (fleet.py
        # _health_snapshot -> tools/fleet_top.py) and by drills.
        self.skipped_steps = _CTX.Value("l", 0, lock=True)
        self.rollbacks = _CTX.Value("l", 0, lock=True)
        # Hang-watchdog progress board (utils/supervision.ProgressBoard),
        # attached by the owning Topology before workers spawn; the
        # shared Values ride the clock's spawn pickle into every child.
        self.progress = None

    def bump_progress(self, label: str, n: int = 1) -> None:
        """Stamp a liveness-progress mark for ``label`` (e.g.
        ``actor-3``); no-op when no watchdog board is attached.  ``n``
        is the number of work units the mark covers (a fused device
        dispatch marks once for its K vector ticks), so mark COUNTS
        stay in vector-tick units across backends — the fleet STATUS
        per-actor frames/s derives from them."""
        if self.progress is not None:
            self.progress.bump(label, n)

    def add_skipped_steps(self, n: int) -> None:
        with self.skipped_steps.get_lock():
            self.skipped_steps.value += n

    def add_actor_steps(self, n: int = 1) -> int:
        with self.actor_step.get_lock():
            self.actor_step.value += n
            return self.actor_step.value

    def seed_actor_steps(self, n: int) -> None:
        """Additive restore of a checkpointed actor-step count: actors may
        already be stepping when the learner restores the epoch, so the
        baseline is ADDED under the lock rather than overwriting their
        early increments."""
        with self.actor_step.get_lock():
            self.actor_step.value += n

    def set_learner_step(self, value: int) -> None:
        with self.learner_step.get_lock():
            self.learner_step.value = value

    def done(self, steps: int) -> bool:
        """Termination predicate shared by every worker loop
        (reference dqn_actor.py:62 ``learner_step >= steps``)."""
        return self.stop.is_set() or self.learner_step.value >= steps


class _Accumulator:
    """A drain-and-reset float accumulator group."""

    FIELDS: tuple = ()

    def __init__(self):
        self._lock = _CTX.Lock()
        for f in self.FIELDS:
            setattr(self, f, _CTX.Value("d", 0.0, lock=False))

    def add(self, **kv: float) -> None:
        with self._lock:
            for k, v in kv.items():
                getattr(self, k).value += v

    def drain(self) -> dict:
        """Read out and zero all fields atomically
        (reference dqn_logger.py:34-55 reads then ``.value = 0``)."""
        with self._lock:
            out = {f: getattr(self, f).value for f in self.FIELDS}
            for f in self.FIELDS:
                getattr(self, f).value = 0.0
            return out


class ActorStats(_Accumulator):
    """Rollout stats accumulated by all actors (reference logs.py:8-13);
    scalar names match the reference's TensorBoard keys
    (reference dqn_logger.py:34-47)."""

    FIELDS = ("nepisodes", "nepisodes_solved", "total_steps",
              "total_reward", "total_nframes")


class LearnerStats(_Accumulator):
    """Loss accumulators (reference logs.py:15-24; DDPG adds actor_loss,
    reference ddpg_logger.py:51)."""

    FIELDS = ("counter", "critic_loss", "actor_loss", "q_mean", "grad_norm",
              "steps_per_sec", "moe_aux")


class EvaluatorStats:
    """Evaluator -> logger handshake (reference logs.py:26-33): evaluator
    writes a snapshot and raises the flag; the logger consumes and lowers it
    (reference evaluators.py:90-95, dqn_logger.py:23-33)."""

    FIELDS = ("avg_steps", "avg_reward", "nepisodes", "nepisodes_solved")

    def __init__(self):
        self._lock = _CTX.Lock()
        self.flag = _CTX.Value("b", 0, lock=False)
        self.at_step = _CTX.Value("l", 0, lock=False)
        # capture wall time: the evaluator attributes each result to the
        # moment the weights were SNAPSHOTTED, not when the (possibly
        # CPU-starved) episodes finished — curve timestamps stay exact
        # under evaluator_nice (agents/evaluator.py docstring)
        self.at_wall = _CTX.Value("d", 0.0, lock=False)
        # raised when the evaluator exits (after its final eval+checkpoint)
        # so the logger drains everything before closing the run
        self.done = _CTX.Value("b", 0, lock=False)
        for f in self.FIELDS:
            setattr(self, f, _CTX.Value("d", 0.0, lock=False))

    def publish(self, learner_step: int, wall: float = 0.0,
                **kv: float) -> None:
        with self._lock:
            for k, v in kv.items():
                getattr(self, k).value = v
            self.at_step.value = learner_step
            self.at_wall.value = wall
            self.flag.value = 1

    def consume(self):
        """Returns (learner_step, wall-or-0, stats dict) or None if
        nothing new."""
        with self._lock:
            if not self.flag.value:
                return None
            out = {f: getattr(self, f).value for f in self.FIELDS}
            step, wall = self.at_step.value, self.at_wall.value
            self.flag.value = 0
            return step, wall, out
