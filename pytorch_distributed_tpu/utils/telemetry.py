"""Mission control: fleet-wide metrics aggregation, an SLO/alert
engine, and OpenMetrics exposition (ISSUE 10 tentpole).

Until this module every number the fleet produced was either a
point-in-time ``T_STATUS`` snapshot (parallel/dcn.py) or a per-host
``scalars.jsonl`` stream that dies with its host — there was no
fleet-level *time series* view, no alerting, and no machine-readable
health verdict.  Ape-X (Horgan et al. 2018) and Podracer both operate
their fleets off continuously aggregated per-role telemetry; this is
that layer, built on the planes PRs 3/6/8 already laid down:

- **Aggregation** (``FleetMetrics``): every role's scalar stream lands
  in bounded ring-buffer time series with downsampled retention tiers
  (raw points for minutes, 10 s buckets for an hour, 60 s buckets for
  six) — ingested *locally* by tailing the run dir's ``scalars.jsonl``
  through the existing ``utils/metrics.ScalarsTail`` cursor reader, and
  *remotely* via the sessionless ``T_METRICS`` DCN verb: fleet actor
  hosts batch their scalar-window deltas on the stats cadence
  (``MetricsPusher``) and push them to the learner-host gateway,
  wall-clock-aligned with the same NTP-style reply-midpoint offset
  estimate the PR 8 ``T_CLOCK`` plane uses, so a skewed host's points
  land on the gateway's time axis, not its own.
- **SLO/alert engine** (``AlertEngine``): declarative rules
  (``config.AlertParams.rules``, a small DSL — threshold,
  absence/staleness, windowed burn-rate) evaluated on the poll cadence
  through a ``pending -> firing -> resolved`` state machine.  Every
  transition lands in the flight recorder (``kind: "alert"`` — visible
  in ``tools/timeline.py``), in the scalar stream
  (``alert/<rule>`` rows), and in the gateway STATUS ``alerts`` block
  ``fleet_top`` renders — detection, not just dashboards.
- **OpenMetrics exposition** (``OpenMetricsServer``): an opt-in
  stdlib-HTTP endpoint on the gateway host serving the aggregated
  series + alert states in Prometheus/OpenMetrics text format, so
  standard scrape tooling watches the fleet without any custom client.

``MissionControl`` composes the three and owns the poll thread; the
topology layer (runtime.py / fleet.py) starts one per run when the
plane is enabled.  Knobs live in ``config.MetricsParams`` /
``config.AlertParams``, env-overridable as ``TPU_APEX_METRICS_<FIELD>``
/ ``TPU_APEX_ALERT_<FIELD>`` (bare ``TPU_APEX_METRICS=1`` =
``enabled``) — the same spawn-inheritance contract the health/perf
planes use.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from pytorch_distributed_tpu.utils import flight_recorder
from pytorch_distributed_tpu.utils.metrics import (
    MetricsWriter, ScalarsTail, is_scalar_row,
)

# ---------------------------------------------------------------------------
# knob resolution (config.MetricsParams/AlertParams + env overrides)
# ---------------------------------------------------------------------------

_ENV_PREFIX = "TPU_APEX_METRICS_"
_ALERT_ENV_PREFIX = "TPU_APEX_ALERT_"


def _coerce(cur: Any, raw: str) -> Any:
    """One env string onto a field's type (the perf/health contract,
    plus str fields — ``AlertParams.rules`` is a string DSL)."""
    if isinstance(cur, bool):
        return raw.strip().lower() not in ("0", "false", "off", "no", "")
    if isinstance(cur, int) and not isinstance(cur, bool):
        return int(float(raw))
    if isinstance(cur, float):
        return float(raw)
    return raw


def resolve_metrics(mp=None):
    """MetricsParams + ``TPU_APEX_METRICS_<FIELD>`` env overrides, plus
    the bare ``TPU_APEX_METRICS`` shorthand for ``enabled`` — same
    override-by-env contract as perf/health.resolve.  Returns a NEW
    instance; the input is never mutated (Options rides spawn
    pickles)."""
    from pytorch_distributed_tpu.config import MetricsParams

    if mp is None:
        mp = MetricsParams()
    changes: Dict[str, Any] = {}
    raw_on = os.environ.get("TPU_APEX_METRICS")
    if raw_on is not None:
        changes["enabled"] = raw_on.strip().lower() not in (
            "0", "false", "off", "no", "")
    for f in dataclasses.fields(mp):
        raw = os.environ.get(_ENV_PREFIX + f.name.upper())
        if raw is not None:
            changes[f.name] = _coerce(getattr(mp, f.name), raw)
    return dataclasses.replace(mp, **changes) if changes else mp


def resolve_alerts(ap=None):
    """AlertParams + ``TPU_APEX_ALERT_<FIELD>`` env overrides
    (``TPU_APEX_ALERT_RULES`` replaces the whole rule set)."""
    from pytorch_distributed_tpu.config import AlertParams

    if ap is None:
        ap = AlertParams()
    changes: Dict[str, Any] = {}
    for f in dataclasses.fields(ap):
        raw = os.environ.get(_ALERT_ENV_PREFIX + f.name.upper())
        if raw is not None:
            changes[f.name] = _coerce(getattr(ap, f.name), raw)
    return dataclasses.replace(ap, **changes) if changes else ap


# ---------------------------------------------------------------------------
# bounded multi-tier time series
# ---------------------------------------------------------------------------

class SeriesRing:
    """One metric's bounded history: a raw ring of (wall, value) points
    plus coarser downsampled tiers, so a days-long run keeps minutes of
    full-resolution history and hours of bucket means in a few KB —
    memory is O(tier spans), never O(run).

    Tiers: raw points covering ``raw_span`` seconds (capped at
    ``raw_points``), then ``(interval, span)`` bucket tiers holding
    (t0, count, sum, min, max, last) per interval.  Appends out of
    wall order (merged roles; clock-aligned remote rows) are folded
    into the newest bucket — downsampled telemetry does not need exact
    bucket attribution, it needs bounded memory."""

    TIERS: Tuple[Tuple[float, float], ...] = ((10.0, 3600.0),
                                              (60.0, 21600.0))

    def __init__(self, raw_span: float = 300.0, raw_points: int = 1024,
                 tiers: Optional[Sequence[Tuple[float, float]]] = None):
        self.raw_span = float(raw_span)
        self._raw: collections.deque = collections.deque(
            maxlen=max(8, int(raw_points)))
        # [interval, span, deque of [t0, count, sum, mn, mx, last]]
        self._tiers = [[float(iv), float(span), collections.deque()]
                       for iv, span in (self.TIERS if tiers is None
                                        else tiers)]
        self.appended = 0

    def append(self, wall: float, value: float) -> None:
        wall, value = float(wall), float(value)
        self._raw.append((wall, value))
        self.appended += 1
        newest = self._raw[-1][0]
        while self._raw and newest - self._raw[0][0] > self.raw_span:
            self._raw.popleft()
        for tier in self._tiers:
            interval, span, buckets = tier
            t0 = (wall // interval) * interval
            if buckets and t0 <= buckets[-1][0]:
                b = buckets[-1]  # same or out-of-order bucket: fold
                b[1] += 1
                b[2] += value
                b[3] = min(b[3], value)
                b[4] = max(b[4], value)
                b[5] = value
            else:
                buckets.append([t0, 1, value, value, value, value])
            while buckets and buckets[-1][0] - buckets[0][0] > span:
                buckets.popleft()

    def latest(self) -> Optional[Tuple[float, float]]:
        return self._raw[-1] if self._raw else None

    def recent(self, n: int) -> List[Tuple[float, float]]:
        """Last ``n`` raw points (newest last)."""
        if n <= 0:
            return []
        return list(self._raw)[-n:]

    def window(self, seconds: float, now: Optional[float] = None
               ) -> List[Tuple[float, float]]:
        """Points within the trailing ``seconds`` window: raw where raw
        coverage reaches, extended backwards with bucket means from the
        finest tier that still covers the gap."""
        if now is None:
            now = time.time()
        cut = now - float(seconds)
        out = [(w, v) for w, v in self._raw if w >= cut]
        raw_oldest = self._raw[0][0] if self._raw else now
        if raw_oldest > cut:
            for interval, _span, buckets in self._tiers:
                # only buckets ENTIRELY before the raw coverage: a
                # bucket straddling raw_oldest holds the same points
                # the raw tier already returned
                older = [(b[0], b[2] / b[1]) for b in buckets
                         if cut <= b[0] and b[0] + interval <= raw_oldest]
                if older:
                    out = older + out
                    break
        return out


# ---------------------------------------------------------------------------
# the fleet aggregator
# ---------------------------------------------------------------------------

class FleetMetrics:
    """Tag-keyed fleet time-series store.  Series are kept per
    ``(tag, role)`` so two actors emitting the same tag never interleave
    into one jagged curve; fleet-level reads (``latest``/``window``)
    merge across roles.  Bounded: at most ``max_series`` distinct
    series — overflow is COUNTED (``series_dropped``), never silent."""

    def __init__(self, params=None):
        p = resolve_metrics(params)
        self.params = p
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, str], SeriesRing] = {}
        self.ingested_rows = 0
        self.remote_batches = 0
        self.series_dropped = 0
        self._warned_cap = False

    def _ring(self, tag: str, role: str) -> Optional[SeriesRing]:
        key = (tag, role)
        ring = self._series.get(key)
        if ring is None:
            if len(self._series) >= self.params.max_series:
                self.series_dropped += 1
                if not self._warned_cap:
                    self._warned_cap = True
                    print(f"[telemetry] series cap "
                          f"({self.params.max_series}) reached; new tag "
                          f"{tag!r} dropped (counted, not silent)",
                          flush=True)
                return None
            ring = self._series[key] = SeriesRing(
                raw_span=self.params.raw_span_s,
                raw_points=self.params.raw_points)
        return ring

    def ingest(self, rows: Sequence[dict], offset: float = 0.0,
               source: str = "local") -> int:
        """Absorb scalar rows (MetricsWriter schema: tag/value/wall/role;
        histogram/span/bucket rows are skipped — they summarize at the
        writer already).  ``offset`` is ADDED to each row's wall so a
        remote host's points land on this host's clock (the T_METRICS
        alignment leg).  Returns rows absorbed."""
        n = 0
        with self._lock:
            for r in rows:
                if not is_scalar_row(r):
                    continue
                try:
                    wall = float(r.get("wall", 0.0)) + offset
                    value = float(r["value"])
                    tag = str(r["tag"])
                except (TypeError, ValueError, KeyError):
                    continue
                ring = self._ring(tag, str(r.get("role", source)))
                if ring is None:
                    continue
                ring.append(wall, value)
                n += 1
            self.ingested_rows += n
        return n

    # -- fleet-level reads ---------------------------------------------------

    def tags(self) -> List[str]:
        with self._lock:
            return sorted({t for t, _r in self._series})

    def latest(self, tag: str) -> Optional[Tuple[float, float]]:
        """Newest (wall, value) across every role emitting ``tag``."""
        best: Optional[Tuple[float, float]] = None
        with self._lock:
            for (t, _role), ring in self._series.items():
                if t != tag:
                    continue
                pt = ring.latest()
                if pt is not None and (best is None or pt[0] > best[0]):
                    best = pt
        return best

    def window(self, tag: str, seconds: float,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """Trailing-window points merged across roles, wall-ordered."""
        out: List[Tuple[float, float]] = []
        with self._lock:
            for (t, _role), ring in self._series.items():
                if t == tag:
                    out.extend(ring.window(seconds, now=now))
        out.sort(key=lambda p: p[0])
        return out

    def series_block(self, tags: Optional[Sequence[str]] = None,
                     points: Optional[int] = None) -> Dict[str, dict]:
        """The STATUS ``series`` block: recent points + latest value per
        tag (roles merged, newest ``points`` kept) — what fleet_top's
        sparklines and ``--json`` consumers read without re-tailing the
        metrics stream themselves."""
        if points is None:
            points = self.params.series_points
        want = set(tags) if tags is not None else None
        merged: Dict[str, List[Tuple[float, float]]] = {}
        with self._lock:
            for (tag, _role), ring in self._series.items():
                if want is not None and tag not in want:
                    continue
                merged.setdefault(tag, []).extend(ring.recent(points))
        out: Dict[str, dict] = {}
        for tag, pts in merged.items():
            pts.sort(key=lambda p: p[0])
            pts = pts[-points:]
            out[tag] = {
                "points": [[round(w, 3), v] for w, v in pts],
                "latest": pts[-1][1] if pts else None,
            }
        return out


# ---------------------------------------------------------------------------
# alert rules: a small declarative DSL
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative SLO rule over an aggregated series.

    kinds:
      - ``threshold``  — latest value violates ``op value``
        continuously for ``for_s`` seconds;
      - ``absence``    — no sample for ``window_s`` seconds (staleness;
        a series that has NEVER reported is absent by configuration,
        not stale — it does not fire);
      - ``burn_rate``  — over the trailing ``window_s`` window, at
        least ``frac`` of samples violate ``op value`` (the windowed
        error-budget burn read)."""

    name: str
    tag: str
    kind: str                      # threshold | absence | burn_rate
    op: str = ">"
    value: float = 0.0
    for_s: float = 0.0
    window_s: float = 0.0
    frac: float = 0.5


def _dur(text: str) -> float:
    m = re.fullmatch(r"\s*([0-9.]+)\s*(ms|s|m|h)?\s*", text)
    if not m:
        raise ValueError(f"bad duration {text!r}")
    mult = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0,
            None: 1.0}[m.group(2)]
    return float(m.group(1)) * mult


# a real float literal (optional sign, optional exponent with its own
# sign): the lazy [0-9.eE+]+ class both rejected valid "2e-2"
# thresholds and admitted garbage like "+e+." that only failed later
_FLOAT = r"[-+]?(?:\d+\.?\d*|\.\d+)(?:[eE][-+]?\d+)?"

_RULE_RE = re.compile(
    r"^\s*(?:(?P<name>[\w.-]+)\s*:)?\s*(?P<tag>[\w./-]+)\s+"
    r"(?:(?P<absent>absent)\s+(?P<age>[\w.]+)"
    rf"|(?P<op><=|>=|<|>)\s*(?P<value>{_FLOAT})"
    r"(?:\s+frac\s+(?P<frac>[0-9.]+)\s+over\s+(?P<burn>[\w.]+)"
    r"|\s+for\s+(?P<dwell>[\w.]+))?)\s*$")


def parse_rule(spec: str) -> AlertRule:
    """One rule from its DSL line.  Grammar::

        [name:] TAG absent DUR
        [name:] TAG OP VALUE [for DUR]
        [name:] TAG OP VALUE frac FRAC over DUR

    ``OP`` in ``< > <= >=``; ``DUR`` like ``30s``/``5m``/``1h`` (bare
    numbers are seconds).  An omitted name derives from the tag."""
    m = _RULE_RE.match(spec)
    if not m:
        raise ValueError(f"unparseable alert rule {spec!r} (grammar: "
                         f"'[name:] tag absent 30s' | "
                         f"'[name:] tag > 5 for 60s' | "
                         f"'[name:] tag > 5 frac 0.5 over 300s')")
    name = m.group("name") or re.sub(r"[^\w]+", "_", m.group("tag"))
    tag = m.group("tag")
    if m.group("absent"):
        return AlertRule(name=name, tag=tag, kind="absence",
                         window_s=_dur(m.group("age")))
    op, value = m.group("op"), float(m.group("value"))
    if m.group("burn"):
        frac = float(m.group("frac"))
        if not 0.0 < frac <= 1.0:
            raise ValueError(f"burn-rate frac must be in (0, 1] "
                             f"(got {frac} in {spec!r})")
        return AlertRule(name=name, tag=tag, kind="burn_rate", op=op,
                         value=value, frac=frac,
                         window_s=_dur(m.group("burn")))
    dwell = _dur(m.group("dwell")) if m.group("dwell") else 0.0
    return AlertRule(name=name, tag=tag, kind="threshold", op=op,
                     value=value, for_s=dwell)


def parse_rules(specs) -> List[AlertRule]:
    """Rules from a sequence of DSL lines or one ``;``-separated string
    (the env-override form: ``TPU_APEX_ALERT_RULES='a: x absent 30s; b:
    y > 5 for 10s'``).  Duplicate names are a config error — two rules
    writing the same ``alert/<name>`` series would shadow each other."""
    if isinstance(specs, str):
        specs = [s for s in specs.split(";") if s.strip()]
    rules = [parse_rule(s) for s in specs]
    seen: Dict[str, str] = {}
    for r in rules:
        if r.name in seen:
            raise ValueError(f"duplicate alert rule name {r.name!r}")
        seen[r.name] = r.tag
    return rules


# The rule set a bare ``TPU_APEX_METRICS=1`` fleet runs (AlertParams.
# rules = ""): the series the ROADMAP's scale-out items are operated
# by.  Sized for production cadences — drills override.
# ``overload_shed`` watches the ISSUE-11 flow plane: sustained
# shedding (state code 2 on ``flow/overload_state``, written by the
# overload governor on its transitions) pages — throttling is normal
# degradation, minutes of shedding means the fleet is sized wrong.
DEFAULT_RULES = (
    "learner_stall: learner/updates_per_s absent 120s",
    "staleness_burn: data/staleness_p50 > 100 frac 0.5 over 300s",
    "priority_collapse: replay/priority_ess_frac < 0.02 for 120s",
    "overload_shed: flow/overload_state >= 2 for 120s",
    # anakin duty cycle (ISSUE 12): a co-located loop whose rollout
    # share collapses is starving the replay of fresh experience (the
    # learner re-chews a frozen ring) — threshold-with-dwell so one
    # checkpoint-heavy window never pages; non-anakin runs never
    # report the tag, so the rule stays silently inert there
    "rollout_starvation: anakin/duty_cycle < 0.02 for 120s",
    # replica plane (ISSUE 15): membership-size ABSENCE — the registry
    # emits ``replica/members`` on every lease event and renew, so the
    # tag going silent means the whole replica plane (or the lead
    # gateway's registry) stopped, which no threshold on a dead series
    # could catch; non-replicated runs never report the tag, so the
    # rule stays silently inert there (absence-never-seen-never-fires)
    "replica_membership: replica/members absent 120s",
    # generation churn: lease-consuming events (expiries + double-lease
    # fences) per rolling minute.  Sustained churn means replicas are
    # crash-looping through lease/rejoin cycles — each individual cycle
    # "recovers", so only the rate exposes the loop
    "replica_churn: replica/generation_churn > 3 for 120s",
    # gateway HA plane (ISSUE 16): the warm standby reports
    # ``gateway/sync_stale`` on its sync cadence — 0 while the primary
    # answers T_SYNC, 1 while it doesn't.  Sustained staleness means
    # the primary is gone and a failover is in progress; the rule
    # RESOLVES once the promoted standby keeps reporting 0 as the new
    # primary.  Non-HA fleets never report the tag, so the rule stays
    # silently inert there (threshold rules never fire on a series
    # that was never written)
    "gateway_failover: gateway/sync_stale >= 1 for 60s",
    # sharded replay plane (ISSUE 20): the shard registry writes
    # ``replay/shard_degraded`` as an explicit 0/1 on every lease event
    # and renew — 1 whenever live membership is below the configured
    # shard count.  Threshold-with-dwell so one lease-window blip never
    # pages, and the rule RESOLVES once a rejoin restores membership
    # (the registry keeps reporting 0).  Unsharded fleets never
    # construct a registry, so the tag is never written and the rule
    # stays silently inert there.
    "shard_membership: replay/shard_degraded >= 1 for 60s",
)


_OPS: Dict[str, Callable[[float, float], bool]] = {
    "<": lambda a, b: a < b,
    ">": lambda a, b: a > b,
    "<=": lambda a, b: a <= b,
    ">=": lambda a, b: a >= b,
}

# state -> scalar/OpenMetrics code (resolved collapses back to ok's 0:
# the scalar stream's step function returns to baseline on recovery;
# the distinct "resolved" transition event lives in the blackbox ring)
STATE_CODE = {"ok": 0.0, "pending": 1.0, "firing": 2.0, "resolved": 0.0}


class AlertEngine:
    """The pending→firing→resolved state machine over a FleetMetrics.

    ``evaluate(now)`` runs every rule once; state transitions are
    returned AND recorded — to the flight recorder (``kind: "alert"``,
    the tools/timeline.py leg), and to the scalar stream as
    ``alert/<rule>`` rows (0 ok, 1 pending, 2 firing) when a writer is
    wired.  ``resolved`` is a one-evaluation state that relaxes back to
    ``ok`` on the next pass, so snapshots show the recovery edge."""

    def __init__(self, rules: Sequence[AlertRule], metrics: FleetMetrics,
                 resolve_s: float = 0.0, recorder=None,
                 writer: Optional[MetricsWriter] = None,
                 clock: Callable[[], float] = time.time):
        self.rules = list(rules)
        self.metrics = metrics
        self.resolve_s = float(resolve_s)
        self._recorder = recorder
        self.writer = writer
        self._clock = clock
        self._lock = threading.Lock()
        self._st: Dict[str, dict] = {
            r.name: {"state": "ok", "since": self._clock(),
                     "pending_since": None, "clear_since": None,
                     "value": None, "detail": "", "fired_total": 0,
                     "resolved_total": 0}
            for r in self.rules}
        self.evaluations = 0

    # -- rule checks ---------------------------------------------------------

    def _check(self, rule: AlertRule, now: float
               ) -> Tuple[bool, Optional[float], str]:
        """(violating, observed value, detail) for one rule."""
        if rule.kind == "absence":
            latest = self.metrics.latest(rule.tag)
            if latest is None:
                # never reported: absent by configuration, not stale
                return False, None, "no samples yet"
            age = now - latest[0]
            return (age > rule.window_s, latest[1],
                    f"last sample {age:.1f}s ago "
                    f"(limit {rule.window_s:g}s)")
        if rule.kind == "threshold":
            latest = self.metrics.latest(rule.tag)
            if latest is None:
                return False, None, "no samples yet"
            bad = _OPS[rule.op](latest[1], rule.value)
            return (bad, latest[1],
                    f"latest {latest[1]:g} {rule.op} {rule.value:g}")
        # burn_rate
        pts = self.metrics.window(rule.tag, rule.window_s, now=now)
        if len(pts) < 3:
            return False, None, f"{len(pts)} sample(s) in window"
        bad = sum(1 for _w, v in pts if _OPS[rule.op](v, rule.value))
        frac = bad / len(pts)
        return (frac >= rule.frac, frac,
                f"{frac:.0%} of {len(pts)} samples {rule.op} "
                f"{rule.value:g} over {rule.window_s:g}s "
                f"(budget {rule.frac:.0%})")

    # -- the state machine ---------------------------------------------------

    def _transition(self, rule: AlertRule, st: dict, state: str,
                    now: float) -> dict:
        st["state"] = state
        st["since"] = now
        # "rule_kind", not "kind": the flight-recorder event's own kind
        # is "alert" (what tools/timeline.py keys its loud lines on)
        evt = {"rule": rule.name, "tag": rule.tag, "state": state,
               "rule_kind": rule.kind, "value": st["value"],
               "detail": st["detail"], "wall": now}
        if self._recorder is not None:
            self._recorder.record("alert", **{k: v for k, v in evt.items()
                                              if k != "wall"})
        if self.writer is not None:
            self.writer.scalar(f"alert/{rule.name}", STATE_CODE[state],
                               step=st["fired_total"], wall=now)
            self.writer.flush()
        return evt

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One pass over every rule; returns the transitions it made."""
        if now is None:
            now = self._clock()
        transitions: List[dict] = []
        with self._lock:
            self.evaluations += 1
            for rule in self.rules:
                st = self._st[rule.name]
                violating, value, detail = self._check(rule, now)
                st["value"], st["detail"] = value, detail
                if violating:
                    st["clear_since"] = None
                    if st["state"] in ("ok", "resolved"):
                        st["pending_since"] = now
                        transitions.append(
                            self._transition(rule, st, "pending", now))
                    if (st["state"] == "pending"
                            and now - st["pending_since"] >= rule.for_s):
                        st["fired_total"] += 1
                        transitions.append(
                            self._transition(rule, st, "firing", now))
                else:
                    if st["state"] == "pending":
                        # never fired: relax quietly (recorded, but no
                        # "resolved" — there was nothing to resolve)
                        transitions.append(
                            self._transition(rule, st, "ok", now))
                    elif st["state"] == "firing":
                        if st["clear_since"] is None:
                            st["clear_since"] = now
                        if now - st["clear_since"] >= self.resolve_s:
                            st["resolved_total"] += 1
                            transitions.append(self._transition(
                                rule, st, "resolved", now))
                    elif st["state"] == "resolved":
                        st["state"] = "ok"
                        st["since"] = now
        return transitions

    def snapshot(self) -> List[dict]:
        """Per-rule state for the STATUS ``alerts`` block (and the
        OpenMetrics alert gauges).  Plain JSON-able dicts."""
        now = self._clock()
        with self._lock:
            out = []
            for rule in self.rules:
                st = self._st[rule.name]
                out.append({
                    "rule": rule.name, "tag": rule.tag,
                    "kind": rule.kind, "state": st["state"],
                    "age": round(now - st["since"], 3),
                    "value": st["value"], "detail": st["detail"],
                    "fired_total": st["fired_total"],
                    "resolved_total": st["resolved_total"],
                })
            return out

    def firing(self) -> List[str]:
        with self._lock:
            return [r.name for r in self.rules
                    if self._st[r.name]["state"] == "firing"]


# ---------------------------------------------------------------------------
# OpenMetrics / Prometheus text exposition
# ---------------------------------------------------------------------------

_METRIC_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(tag: str) -> str:
    name = _METRIC_NAME_RE.sub("_", tag)
    if name and name[0].isdigit():
        name = "_" + name
    return f"tpu_apex_{name}"


def _label(value: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline):
    role/host labels come off the wire from pushers — one misbehaving
    value must not make the whole /metrics page unparseable."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def openmetrics_text(metrics: FleetMetrics,
                     engine: Optional[AlertEngine] = None) -> str:
    """The aggregated fleet state in Prometheus text format (0.0.4 —
    the dialect every scraper speaks; terminated with the OpenMetrics
    ``# EOF`` marker, which classic parsers read as a comment).  One
    gauge per tag with the role as a label, millisecond timestamps from
    the CAPTURE wall (not scrape time), plus per-rule alert-state
    gauges and the aggregator's own ingest counters."""
    lines: List[str] = []
    with metrics._lock:
        items = sorted(metrics._series.items())
        per_tag: Dict[str, List[Tuple[str, Tuple[float, float]]]] = {}
        for (tag, role), ring in items:
            pt = ring.latest()
            if pt is not None:
                per_tag.setdefault(tag, []).append((role, pt))
    for tag, rows in per_tag.items():
        name = _metric_name(tag)
        lines.append(f"# HELP {name} fleet series {tag}")
        lines.append(f"# TYPE {name} gauge")
        for role, (wall, value) in rows:
            lines.append(f'{name}{{role="{_label(role)}"}} {value:g} '
                         f"{int(wall * 1000)}")
    if engine is not None:
        lines.append("# HELP tpu_apex_alert_state alert rule state "
                     "(0 ok, 1 pending, 2 firing)")
        lines.append("# TYPE tpu_apex_alert_state gauge")
        snap = engine.snapshot()
        for a in snap:
            lines.append(
                f'tpu_apex_alert_state{{rule="{_label(a["rule"])}",'
                f'tag="{_label(a["tag"])}"}} '
                f"{STATE_CODE.get(a['state'], 0.0):g}")
        lines.append("# TYPE tpu_apex_alerts_firing gauge")
        lines.append(f"tpu_apex_alerts_firing "
                     f"{sum(1 for a in snap if a['state'] == 'firing')}")
    lines.append("# TYPE tpu_apex_telemetry_rows_ingested counter")
    lines.append(f"tpu_apex_telemetry_rows_ingested "
                 f"{metrics.ingested_rows}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class OpenMetricsServer:
    """Opt-in stdlib-HTTP scrape endpoint (``GET /metrics``) — standard
    Prometheus tooling watches the fleet with zero custom client code.
    Daemon-threaded; ``port=0`` binds an ephemeral port (tests), the
    production default lives in ``MetricsParams.openmetrics_port``."""

    def __init__(self, text_fn: Callable[[], str],
                 host: str = "0.0.0.0", port: int = 9108):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib API name
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                try:
                    body = text_fn().encode()
                except Exception as e:  # noqa: BLE001 - scrape never kills
                    self.send_error(500, repr(e)[:200])
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; "
                                 "charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                server.scrapes += 1

            def log_message(self, *args):  # noqa: D102
                pass  # scrape chatter must not pollute the run's stdout

        self.scrapes = 0
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="openmetrics", daemon=True)
        self._thread.start()

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
        self._thread.join(2.0)


# ---------------------------------------------------------------------------
# remote push (the T_METRICS client side)
# ---------------------------------------------------------------------------

class MetricsPusher:
    """Fleet-host leg of the aggregator: tails THIS host's
    ``scalars.jsonl`` (the same ``ScalarsTail`` cursor the local ingest
    uses) and pushes each poll's scalar deltas to the learner-host
    gateway over the sessionless ``T_METRICS`` verb on the
    ``push_s`` cadence.

    Wall-clock alignment: the T_METRICS reply carries the gateway's
    wall clock; the pusher estimates its offset to it NTP-style off the
    RPC midpoint (EWMA-smoothed — the same estimator DcnClient uses for
    ``clock_sync``) and ships the estimate with every batch, so the
    gateway-side aggregator lands this host's points on the learner
    host's time axis.  The FIRST push is an empty offset-estimation
    handshake: rows only travel once an offset estimate exists, so a
    badly skewed host never pollutes the fleet series with unaligned
    points.  Push failures are counted and retried next cadence — the
    telemetry plane must never backpressure the host it watches."""

    # rows buffered across failed pushes before the OLDEST are shed
    # (counted as ``dropped_rows``, never silent): an actor host whose
    # coordinator is down for days must not hoard its whole metrics
    # backlog in memory — telemetry is a lossy-tolerable plane, the
    # host it watches is not
    MAX_PENDING = 10_000

    def __init__(self, address: Tuple[str, int], log_dir: str,
                 params=None, host: Optional[str] = None,
                 clock: Callable[[], float] = time.time):
        self.address = address
        self.params = resolve_metrics(params)
        self._tail = ScalarsTail(log_dir, max_bytes=1 << 20)
        self._host = host or os.uname().nodename
        self._clock = clock
        self.offset: Optional[float] = None
        self.pushed_rows = 0
        self.push_errors = 0
        self.dropped_rows = 0
        # ISSUE-11 brownout tier 1 (the telemetry rung): the gateway's
        # T_METRICS reply carries ``brownout`` while the ladder is
        # engaged; this pusher then sheds its pending rows (counted
        # here) until a reply clears it — metrics traffic yields to
        # the experience plane first, and never silently.
        self.brownout = 0
        self.brownout_shed_rows = 0
        self._warned_drop = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pending: List[dict] = []

    def _rpc(self, rows: List[dict]) -> dict:
        from pytorch_distributed_tpu.parallel.dcn import push_metrics

        t0 = self._clock()
        reply = push_metrics(self.address, rows, offset=self.offset,
                             host=self._host)
        mid = (t0 + self._clock()) / 2.0
        gw_wall = reply.get("wall")
        if isinstance(gw_wall, (int, float)):
            sample = float(gw_wall) - mid
            self.offset = (sample if self.offset is None
                           else 0.9 * self.offset + 0.1 * sample)
        try:
            self.brownout = int(reply.get("brownout", 0) or 0)
        except (TypeError, ValueError):
            self.brownout = 0
        return reply

    def push_once(self) -> int:
        """One cadence: tail new rows, (re)estimate the offset, push.
        Returns rows accepted by the gateway.  A failed push RETAINS
        its batch for the next cadence (re-prepended, order kept) up
        to ``MAX_PENDING`` rows; beyond that the oldest are shed and
        counted."""
        self._pending.extend(r for r in self._tail.poll()
                             if is_scalar_row(r))
        if self.brownout >= 1 and self._pending:
            # the telemetry rung of the brownout ladder: shed this
            # cadence's rows (counted), then ping with an empty batch
            # so recovery — a reply without ``brownout`` — is observed
            self.brownout_shed_rows += len(self._pending)
            self._pending = []
            try:
                self._rpc([])
            except (ConnectionError, OSError):
                self.push_errors += 1
            return 0
        if len(self._pending) > self.MAX_PENDING:
            shed = len(self._pending) - self.MAX_PENDING
            del self._pending[:shed]
            self.dropped_rows += shed
            if not self._warned_drop:
                self._warned_drop = True
                print(f"[telemetry] pusher backlog over "
                      f"{self.MAX_PENDING} rows (gateway unreachable?);"
                      f" shedding oldest (counted, not silent)",
                      flush=True)
        batch: List[dict] = []
        try:
            if self.offset is None:
                self._rpc([])  # offset handshake before any row travels
            if not self._pending:
                return 0
            batch, self._pending = self._pending, []
            reply = self._rpc(batch)
            if reply.get("error"):
                self.push_errors += 1
                self._pending = batch + self._pending
                return 0
            n = int(reply.get("accepted", 0))
            self.pushed_rows += n
            return n
        except (ConnectionError, OSError):
            # the batch survives the blip: next cadence retries it
            # ahead of newer rows (the gateway-restart soak scenario)
            self.push_errors += 1
            self._pending = batch + self._pending
            return 0

    def start(self) -> None:
        if self._thread is not None:
            return

        def _loop() -> None:
            while not self._stop.wait(self.params.push_s):
                self.push_once()
            self.push_once()  # final drain on stop

        self._thread = threading.Thread(target=_loop,
                                        name="metrics-pusher",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None


# ---------------------------------------------------------------------------
# mission control: the composed plane
# ---------------------------------------------------------------------------

class MissionControl:
    """One run's telemetry brain: aggregator + alert engine + (opt-in)
    OpenMetrics endpoint, polled by one background thread.

    - local ingest: tails ``{log_dir}/scalars.jsonl`` (every co-located
      role's writer appends there) via ScalarsTail;
    - remote ingest: ``ingest_remote`` is the gateway's T_METRICS sink
      (fleet.py wires it);
    - alert transitions land in the flight recorder (role
      ``missionctl``) and — when a log dir exists — as
      ``alert/<rule>`` rows in the same scalar stream, which is how
      tools/timeline.py shows them on the incident timeline;
    - ``status_block()`` feeds the gateway STATUS verb's ``alerts`` +
      ``series`` blocks (fleet_top's panel and ``--json``)."""

    ROLE = "missionctl"

    # tags the STATUS series block always tries to carry (the fleet's
    # vital signs); rule tags are added automatically.  The second row
    # is the reference logger's learning curve — present on EVERY run,
    # so a fleet without the perf plane still gets trend lines.
    KEY_TAGS = ("learner/updates_per_s", "learner/mfu",
                "actor/env_frames_per_s", "data/staleness_p50",
                "replay/priority_ess_frac", "flow/overload_state",
                "anakin/duty_cycle", "anakin/replay_fill",
                "replica/members", "replica/generation_churn",
                "replay/shard_members", "replay/shard_mass_skew",
                "learner/critic_loss", "evaluator/avg_reward",
                "actor/avg_reward", "learner/steps_per_sec")

    def __init__(self, log_dir: Optional[str], metrics_params=None,
                 alert_params=None, clock: Callable[[], float] = time.time):
        self.params = resolve_metrics(metrics_params)
        self.alert_params = resolve_alerts(alert_params)
        self.log_dir = log_dir
        self.metrics = FleetMetrics(self.params)
        self._tail = (ScalarsTail(log_dir, max_bytes=1 << 20)
                      if log_dir else None)
        self._writer = (MetricsWriter(log_dir, enable_tensorboard=False,
                                      role=self.ROLE)
                        if log_dir else None)
        rules: Sequence[AlertRule] = ()
        if self.alert_params.enabled:
            rules = parse_rules(self.alert_params.rules or DEFAULT_RULES)
        self.engine = AlertEngine(
            rules, self.metrics, resolve_s=self.alert_params.resolve_s,
            recorder=flight_recorder.get_recorder(self.ROLE),
            writer=self._writer, clock=clock)
        self.exporter: Optional[OpenMetricsServer] = None
        if self.params.openmetrics:
            self.exporter = OpenMetricsServer(
                self.openmetrics_text, port=self.params.openmetrics_port)
            print(f"[telemetry] OpenMetrics endpoint on "
                  f":{self.exporter.port}/metrics", flush=True)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- ingest --------------------------------------------------------------

    def poll(self, now: Optional[float] = None) -> List[dict]:
        """One cadence: tail local rows, evaluate alerts.  Returns the
        alert transitions this pass made (drills assert on them)."""
        if self._tail is not None:
            self.metrics.ingest(self._tail.poll(), source="local")
        return self.engine.evaluate(now=now)

    def ingest_remote(self, payload: dict) -> int:
        """The gateway's T_METRICS sink: one pushed batch.  ``offset``
        (the pusher's NTP-style estimate of THIS host's clock minus its
        own) aligns the rows' walls onto our time axis."""
        rows = payload.get("rows") or []
        try:
            offset = float(payload.get("offset") or 0.0)
        except (TypeError, ValueError):
            offset = 0.0
        self.metrics.remote_batches += 1
        return self.metrics.ingest(rows, offset=offset,
                                   source=str(payload.get("host",
                                                          "remote")))

    # -- reads ---------------------------------------------------------------

    def _series_tags(self) -> List[str]:
        tags = [t.strip() for t in
                self.params.series_tags.split(",") if t.strip()]
        tags.extend(self.KEY_TAGS)
        tags.extend(r.tag for r in self.engine.rules)
        have = set(self.metrics.tags())
        out, seen = [], set()
        for t in tags:
            if t in have and t not in seen:
                seen.add(t)
                out.append(t)
        return out

    def status_block(self) -> dict:
        """The gateway-STATUS contribution: ``alerts`` (per-rule state)
        and ``series`` (recent points for the vital-sign tags)."""
        return {"alerts": self.engine.snapshot(),
                "series": self.metrics.series_block(self._series_tags()),
                "telemetry": {
                    "rows": self.metrics.ingested_rows,
                    "remote_batches": self.metrics.remote_batches,
                    "series_dropped": self.metrics.series_dropped,
                }}

    def openmetrics_text(self) -> str:
        return openmetrics_text(self.metrics, self.engine)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return

        def _loop() -> None:
            while not self._stop.wait(self.params.poll_s):
                try:
                    self.poll()
                except Exception as e:  # noqa: BLE001 - watch, never kill
                    print(f"[telemetry] poll failed: {e!r}", flush=True)
        self._thread = threading.Thread(target=_loop,
                                        name="mission-control",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        try:
            self.poll()  # final tail drain + alert pass
        except Exception:  # noqa: BLE001
            pass
        if self.exporter is not None:
            self.exporter.close()
            self.exporter = None
        if self._writer is not None:
            self._writer.close()
            self._writer = None
            self.engine.writer = None
