"""Checkpointing: params-only tier, legacy single-snapshot tier, and
crash-consistent checkpoint EPOCHS.

The reference checkpoints weights only: the evaluator torch.saves a
state_dict every eval cycle (reference core/single_processes/evaluators.py:
97-100) and restores go through finetune load (reference main.py:45) and the
tester (reference testers.py:25) — optimizer state, counters, replay and RNG
are all lost on resume (SURVEY.md §5 "checkpoint/resume: minimal").

Three tiers here:

- **params-only** (reference-parity): a Flax-serialized msgpack of the param
  pytree at ``{model_name}.msgpack`` — written by the evaluator on its
  cadence, read by finetune/tester.  Restore needs a template tree of the
  same structure (``load_params(path, template)``).
- **legacy single snapshot**: Orbax checkpoint of the whole ``TrainState``
  at ``{model_name}_state/`` plus a replay ``.npz`` — kept for
  compatibility with pre-epoch runs.  ``save_train_state`` publishes via a
  fresh directory + rename (never an in-place ``force=True`` overwrite), so
  a crash mid-save cannot destroy the previous good snapshot.
- **checkpoint epochs** (the crash-consistent resume tier): versioned
  ``{model_name}_ckpt/epoch_<k>/`` directories, each holding the train
  state, the replay contents, and an ``extras.json`` of clocks/counters,
  evaluator best-score and per-role RNG states — all captured at ONE
  moment and committed together by an atomic ``MANIFEST.json`` rename.
  The manifest records the epoch number, the learner step, and a sha256
  content digest per artifact; readers (``resolve_epoch``) scan newest
  first and take the first epoch whose manifest exists and whose digests
  verify, so a SIGKILL at ANY point of a save leaves either the new epoch
  fully committed or the previous one untouched — never a torn triple of
  learner-at-step-N with replay-from-step-M.  ``gc_epochs`` keeps the
  newest ``retain`` committed epochs.  ``fsck`` (and the
  ``tools/ckpt_fsck.py`` CLI) validates a checkpoint root offline.

Fault drills: every epoch save consults a ``FaultInjector``
(utils/faults.py) built from the ``CKPT_FAULTS`` env var, counting one
frame per labelled write point (see ``_FRAME_POINTS``), so a kill-resume
drill can SIGKILL the process at an exactly reproducible position —
mid-Orbax-write, between the state and replay writes, or mid-manifest
commit.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

PyTree = Any

MANIFEST = "MANIFEST.json"
MANIFEST_FORMAT = 1
_EPOCH_PREFIX = "epoch_"
# marker dropped into a COMMITTED epoch the health sentinel rolled past
# (utils/health.py / agents/learner.py): the epoch's params are known or
# suspected diverged, so resolve_epoch must never resume from it — while
# its artifacts stay on disk, digest-intact, for post-mortems.  fsck
# reports these as ``rolled-back`` (clean), not violations.
ROLLED_BACK = "ROLLED_BACK.json"

# frame indices fired per save_epoch call, in order — CKPT_FAULTS
# schedules (e.g. ``kill@9``) target frame ``FRAMES_PER_SAVE * save_index
# + point`` to die at an exact write boundary of an exact save
_FRAME_POINTS = (
    "begin",          # 0: before the epoch dir is (re)created
    "mid_state",      # 1: Orbax save dispatched, not yet finished
    "after_state",    # 2: state durable; replay not yet written
    "mid_replay",     # 3: replay tmp written, not yet renamed in
    "pre_commit",     # 4: all artifacts written, manifest not committed
    "post_commit",    # 5: manifest committed, GC not yet run
)
FRAMES_PER_SAVE = len(_FRAME_POINTS)


class CheckpointMismatch(RuntimeError):
    """A restored snapshot does not fit the live run's configuration
    (memory geometry/dtype/family changed between save and resume).
    Raised with a field-level message instead of letting the mismatch
    surface as a cryptic broadcast error deep inside JAX."""


# ---------------------------------------------------------------------------
# fault hook (kill-resume drills)
# ---------------------------------------------------------------------------

_faults_box: list = [None]


def _faults():
    """Process-wide injector for the checkpoint plane (``CKPT_FAULTS``).
    One frame counter across all saves in the process, so a schedule can
    name "the Nth write point since start" deterministically."""
    if _faults_box[0] is None:
        from pytorch_distributed_tpu.utils.faults import FaultInjector

        _faults_box[0] = FaultInjector.from_env("ckpt")
    return _faults_box[0]


# ---------------------------------------------------------------------------
# params-only tier (reference parity)
# ---------------------------------------------------------------------------

def save_params(path: str, params: PyTree) -> str:
    """Write a params-only checkpoint (msgpack).  Returns the path."""
    import jax
    from flax import serialization

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    data = serialization.to_bytes(jax.device_get(params))
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)  # atomic: readers never see a torn file
    return path


def load_params(path: str, template: PyTree) -> PyTree:
    from flax import serialization

    with open(path, "rb") as f:
        return serialization.from_bytes(template, f.read())


def params_path(model_name: str) -> str:
    """``models/{machine}_{timestamp}.msgpack`` — the counterpart of the
    reference's ``.pth`` path (reference utils/options.py:42)."""
    return model_name + ".msgpack"


# ---------------------------------------------------------------------------
# legacy single-snapshot tier
# ---------------------------------------------------------------------------

def best_score_path(model_name: str) -> str:
    return model_name + "_best.json"


def save_best_score(model_name: str, reward: float,
                    step: Optional[int] = None) -> None:
    """Sidecar committed WITH every ``<refs>_best.msgpack`` write: the
    score that file's weights actually earned.  Checkpoint epochs also
    carry the best score, but an eval can beat the record between two
    epoch commits — a crash in that window would resume with a stale
    threshold and let a worse policy overwrite the best params.  Resume
    takes the max of both records (agents/learner.py)."""
    _write_json_atomic(best_score_path(model_name),
                       {"best_eval_reward": float(reward), "step": step})


def load_best_score(model_name: str) -> float:
    """The sidecar's score; -inf when absent or unreadable."""
    try:
        with open(best_score_path(model_name)) as f:
            return float(json.load(f)["best_eval_reward"])
    except (OSError, ValueError, KeyError):
        return float("-inf")


def state_dir(model_name: str) -> str:
    return os.path.abspath(model_name + "_state")


def save_train_state(model_name: str, state: Any) -> str:
    """Orbax save of the full TrainState — crash-safe single snapshot.

    Writes into a FRESH ``_state.new`` directory and publishes by rename:
    the previous good snapshot is parked at ``_state.old`` for the one
    instant between the two renames and deleted only after the new one is
    in place, so no point of a SIGKILL can destroy the run's only
    recovery state (the old ``force=True`` overwrite erased it first and
    rebuilt in place).  ``restore_train_state`` knows the fallbacks."""
    import jax
    import orbax.checkpoint as ocp

    path = state_dir(model_name)
    fresh = path + ".new"
    old = path + ".old"
    if not os.path.isdir(path):
        # heal a crash-window store BEFORE purging debris: with ``path``
        # absent, a complete snapshot may live only at ``.new`` (crash
        # between the publish renames — the write always completes before
        # any rename) or ``.old``; deleting it here and then dying
        # mid-save would destroy the tier's only recovery point
        for d in (fresh, old):
            if os.path.isdir(d):
                os.rename(d, path)
                break
    for d in (fresh, old):  # remaining debris from a previous crash
        if os.path.isdir(d):
            shutil.rmtree(d, ignore_errors=True)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(fresh, jax.device_get(state))
    ckptr.wait_until_finished()
    if os.path.isdir(path):
        os.rename(path, old)
    os.rename(fresh, path)
    shutil.rmtree(old, ignore_errors=True)
    return path


def restore_train_state(model_name: str, template: Any) -> Optional[Any]:
    """Restore a TrainState saved by ``save_train_state``; None if absent.
    Falls back across the publish window: ``_state`` first, then
    ``_state.new`` (with ``_state`` absent the crash was between the two
    publish renames, so ``.new`` is COMPLETE and one interval newer than
    the parked ``.old``), then ``_state.old``."""
    import orbax.checkpoint as ocp

    path = state_dir(model_name)
    ckptr = ocp.StandardCheckpointer()
    for candidate in (path, path + ".new", path + ".old"):
        if not os.path.isdir(candidate):
            continue
        try:
            return ckptr.restore(candidate, template)
        except Exception as e:  # noqa: BLE001 - torn dir: try the next tier
            print(f"[checkpoint] {candidate} unreadable ({e}); "
                  f"trying older snapshot")
    return None


def replay_path(model_name: str) -> str:
    return model_name + "_replay.npz"


def save_replay(model_name: str, memory: Any) -> Optional[str]:
    """Write the replay contents next to the train state — the resume leg
    the reference never had (SURVEY.md §5 "Not checkpointed: ... replay").
    Works for any memory exposing ``snapshot() -> dict`` (shared ring, PER
    incl. leaf priorities, HBM device rings, host/HBM segment rings; queue
    owners drain-then-delegate).  Returns the path, or None when the
    memory type has no snapshot surface."""
    data = snapshot_memory(memory)
    if data is None:
        return None
    path = replay_path(model_name)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    _write_npz_atomic(path, data)
    return path


def load_replay(model_name: str, memory: Any) -> bool:
    """Refill ``memory`` from a prior save_replay; False when absent or the
    memory type has no restore surface.  Raises ``CheckpointMismatch``
    when the snapshot's geometry no longer fits the live memory."""
    import numpy as np

    path = replay_path(model_name)
    if not hasattr(memory, "restore") or not os.path.exists(path):
        return False
    with np.load(path) as z:
        data = {k: z[k] for k in z.files}
    validate_snapshot(memory, data, source=path)
    try:
        memory.restore(data)
    except NotImplementedError:
        return False
    return True


def snapshot_memory(memory: Any) -> Optional[dict]:
    """``memory.snapshot()`` with the duck-typing every save path shares:
    None when the memory has no snapshot surface (or a queue owner wraps
    one that doesn't)."""
    if not hasattr(memory, "snapshot"):
        return None
    try:
        return memory.snapshot()
    except NotImplementedError:  # wrapper around an unsupported memory
        return None


# ---------------------------------------------------------------------------
# snapshot <-> live-memory validation (CheckpointMismatch)
# ---------------------------------------------------------------------------

def _unwrap(memory: Any) -> Any:
    """Queue owners delegate geometry to the wrapped memory; device
    ingests to the attached ring."""
    if hasattr(memory, "memory"):           # feeder.QueueOwner
        return memory.memory
    if getattr(memory, "replay", None) is not None:  # Device*Ingest
        return memory.replay
    return memory


def validate_snapshot(memory: Any, data: dict, source: str = "snapshot"
                      ) -> None:
    """Check a replay snapshot against the live memory's geometry and
    fail with a field-level ``CheckpointMismatch`` instead of a cryptic
    broadcast error deep in the restore path.

    Validated: schema family (transition vs segment rows), state/obs row
    shape, state dtype.  A different CAPACITY is legal by design — every
    restore keeps the newest rows that fit — but a shrink is reported to
    stdout since it silently drops history."""
    import numpy as np

    mem = _unwrap(memory)
    snap_is_seq = "obs" in data and "mask" in data
    mem_is_seq = hasattr(mem, "T") or hasattr(mem, "seq_len")
    name = type(mem).__name__

    def bail(msg: str) -> None:
        raise CheckpointMismatch(
            f"{source} does not fit the live {name}: {msg} "
            f"(memory/model config changed between save and resume?)")

    if snap_is_seq != mem_is_seq:
        bail("snapshot holds %s rows but the memory stores %s rows"
             % ("segment" if snap_is_seq else "transition",
                "segment" if mem_is_seq else "transition"))

    if mem_is_seq:
        obs = np.asarray(data["obs"])
        want = getattr(mem, "obs_shape", None)
        if want is None and hasattr(mem, "obs"):  # host SequenceReplay
            want = tuple(np.shape(mem.obs)[1:])
        if want is not None and len(obs) \
                and tuple(obs.shape[1:]) != tuple(want):
            bail(f"segment obs rows are {tuple(obs.shape[1:])}, "
                 f"live ring stores {tuple(want)} "
                 f"(seq_len/pack_frames/state shape changed)")
        lstm = getattr(mem, "lstm_dim", None)
        c0 = np.asarray(data.get("c0", np.zeros((0, 0))))
        if lstm is not None and len(c0) and c0.shape[1] != lstm:
            bail(f"carry width {c0.shape[1]} != live lstm_dim {lstm}")
    else:
        st = np.asarray(data["state0"])
        want = getattr(mem, "state_shape", None)
        if want is not None and len(st) \
                and tuple(st.shape[1:]) != tuple(want):
            bail(f"state rows are {tuple(st.shape[1:])}, live memory "
                 f"stores {tuple(want)}")
        want_dt = getattr(mem, "state_dtype", None)
        if want_dt is not None and len(st) \
                and np.dtype(st.dtype) != np.dtype(want_dt):
            bail(f"state dtype {st.dtype} != live {np.dtype(want_dt)}")

    cap = getattr(mem, "capacity", None)
    rows = len(np.asarray(data.get("reward", ())))
    if cap is not None and rows > cap:
        print(f"[checkpoint] note: {source} holds {rows} rows, live "
              f"{name} capacity is {cap} — restoring the newest {cap}")


# ---------------------------------------------------------------------------
# RNG state serialization (per-role, into epoch extras)
# ---------------------------------------------------------------------------

def serialize_np_rng(rng) -> dict:
    """JSON-able state of a numpy Generator."""
    return rng.bit_generator.state


def restore_np_rng(rng, state: Optional[dict]) -> bool:
    if not state:
        return False
    rng.bit_generator.state = state
    return True


def serialize_prng_key(key) -> list:
    """JSON-able words of a JAX PRNG key (typed or raw uint32)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        key = jax.random.key_data(key)
    return np.asarray(jax.device_get(key)).astype(np.uint32).tolist()


def deserialize_prng_key(data, like):
    """Rebuild a key serialized by ``serialize_prng_key``; ``like`` fixes
    typed-vs-raw so the restored key drops into the saver's slot."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    raw = jnp.asarray(np.asarray(data, np.uint32))
    if jnp.issubdtype(like.dtype, jax.dtypes.prng_key):
        return jax.random.wrap_key_data(raw)
    return raw


# ---------------------------------------------------------------------------
# checkpoint epochs
# ---------------------------------------------------------------------------

def ckpt_root(model_name: str) -> str:
    return os.path.abspath(model_name + "_ckpt")


def _epoch_dir(root: str, k: int) -> str:
    return os.path.join(root, f"{_EPOCH_PREFIX}{k}")


def _epoch_num(name: str) -> Optional[int]:
    if not name.startswith(_EPOCH_PREFIX):
        return None
    try:
        return int(name[len(_EPOCH_PREFIX):])
    except ValueError:
        return None


def _list_epochs(root: str) -> List[Tuple[int, str]]:
    """(k, path) for every epoch-shaped dir under root, newest first."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        k = _epoch_num(name)
        p = os.path.join(root, name)
        if k is not None and os.path.isdir(p):
            out.append((k, p))
    return sorted(out, reverse=True)


def _digest_file(path: str) -> Tuple[str, int]:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for blk in iter(lambda: f.read(1 << 20), b""):
            h.update(blk)
    return h.hexdigest(), os.path.getsize(path)


def _digest_tree(root: str) -> Tuple[str, int, int]:
    """Digest of a directory artifact (the Orbax state dir): sha256 over
    sorted relpaths + contents, so any torn/renamed/missing file flips
    it.  Returns (hexdigest, total_bytes, file_count)."""
    h = hashlib.sha256()
    total = nfiles = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for fn in sorted(filenames):
            p = os.path.join(dirpath, fn)
            h.update(os.path.relpath(p, root).encode() + b"\0")
            with open(p, "rb") as f:
                for blk in iter(lambda: f.read(1 << 20), b""):
                    h.update(blk)
            total += os.path.getsize(p)
            nfiles += 1
    return h.hexdigest(), total, nfiles


def _write_json_atomic(path: str, obj: dict) -> None:
    """tmp write + fsync + rename + dir fsync: the commit primitive.
    After the ``os.replace`` the file is either the complete new content
    or absent — a reader can never observe a torn manifest."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_npz_atomic(path: str, data: dict, faults=None) -> None:
    import numpy as np

    tmp = path + ".tmp.npz"
    np.savez_compressed(tmp, **data)
    if faults is not None:
        faults.frame()  # mid_replay: tmp durable, not yet published
    with open(tmp, "rb") as f:
        os.fsync(f.fileno())
    os.replace(tmp, path)


@dataclass
class EpochInfo:
    """A resolved (complete, digest-valid) checkpoint epoch."""

    path: str
    epoch: int
    learner_step: int
    manifest: dict
    extras: dict = field(default_factory=dict)

    @property
    def has_state(self) -> bool:
        return "state" in self.manifest.get("artifacts", {})

    @property
    def has_replay(self) -> bool:
        return "replay.npz" in self.manifest.get("artifacts", {})


def save_epoch(model_name: str, state: Any = None, memory: Any = None,
               extras: Optional[dict] = None, retain: int = 3) -> str:
    """Write one coordinated checkpoint epoch and commit it atomically.

    Artifacts captured at THIS call, bound into one recovery point:
    ``state/`` (Orbax TrainState), ``replay.npz`` (when ``memory`` has a
    snapshot surface), ``extras.json`` (clocks/counters/best-score/RNG —
    whatever dict the caller passes).  The epoch becomes visible to
    readers only at the final atomic MANIFEST.json rename; a crash at any
    earlier point leaves an uncommitted ``epoch_<k>`` that resolve/fsck
    skip and the next save clears.  After commit, epochs beyond
    ``retain`` are garbage-collected (newest kept)."""
    faults = _faults()
    faults.frame()  # begin
    root = ckpt_root(model_name)
    os.makedirs(root, exist_ok=True)
    committed = [k for k, p in _list_epochs(root)
                 if os.path.exists(os.path.join(p, MANIFEST))]
    k = (committed[0] + 1) if committed else 0
    ed = _epoch_dir(root, k)
    if os.path.isdir(ed):  # uncommitted debris from a crashed save
        shutil.rmtree(ed, ignore_errors=True)
    os.makedirs(ed)

    artifacts: Dict[str, dict] = {}
    learner_step = int((extras or {}).get("learner_step", -1))

    if state is not None:
        import jax
        import orbax.checkpoint as ocp

        host_state = jax.device_get(state)
        if learner_step < 0 and hasattr(host_state, "step"):
            learner_step = int(host_state.step)
        sd = os.path.join(ed, "state")
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(sd, host_state)
        faults.frame()  # mid_state: dispatched, possibly unfinished
        ckptr.wait_until_finished()
        digest, nbytes, nfiles = _digest_tree(sd)
        artifacts["state"] = {"sha256": digest, "bytes": nbytes,
                              "files": nfiles}
    else:
        faults.frame()  # keep the frame schedule position-stable

    faults.frame()  # after_state
    data = snapshot_memory(memory) if memory is not None else None
    if data is not None:
        rp = os.path.join(ed, "replay.npz")
        _write_npz_atomic(rp, data, faults=faults)
        digest, nbytes = _digest_file(rp)
        artifacts["replay.npz"] = {
            "sha256": digest, "bytes": nbytes,
            "rows": int(len(data.get("reward", ())))}
    else:
        faults.frame()  # mid_replay placeholder

    ep = os.path.join(ed, "extras.json")
    _write_json_atomic(ep, dict(extras or {}))
    digest, nbytes = _digest_file(ep)
    artifacts["extras.json"] = {"sha256": digest, "bytes": nbytes}

    faults.frame()  # pre_commit: everything durable, nothing visible
    import time as _time

    _write_json_atomic(os.path.join(ed, MANIFEST), {
        "format": MANIFEST_FORMAT,
        "epoch": k,
        "learner_step": learner_step,
        "wall": _time.time(),
        "artifacts": artifacts,
    })
    faults.frame()  # post_commit
    # bandwidth X-ray (ISSUE 18): per-epoch byte gauges off the
    # already-digested artifact sizes — zero extra I/O
    from pytorch_distributed_tpu.utils import bandwidth

    epoch_bytes = sum(int(m.get("bytes", 0)) for m in artifacts.values())
    bandwidth.set_gauge("ckpt/epoch_bytes", float(epoch_bytes))
    for name, meta in artifacts.items():
        bandwidth.set_gauge(f"ckpt/epoch_bytes/{name}",
                            float(meta.get("bytes", 0)))
        bandwidth.note("ckpt", name, int(meta.get("bytes", 0)), "tx")
    gc_epochs(root, retain=retain, in_progress=k)
    return ed


def mark_rolled_back(path: str, to_epoch: Optional[int] = None,
                     reason: str = "") -> None:
    """Fence a committed epoch off from resume (health-sentinel
    rollback): atomic marker write; idempotent."""
    import time as _time

    _write_json_atomic(os.path.join(path, ROLLED_BACK), {
        "wall": _time.time(),
        "rolled_back_to": to_epoch,
        "reason": reason,
    })


def fence_epochs_after(model_name: str, after_epoch: int,
                       reason: str = "") -> List[int]:
    """Mark every COMMITTED epoch numbered above ``after_epoch`` as
    rolled-back (idempotent) — the rollback path's fencing step, kept
    here so the committed-vs-fenced invariant (manifest = committed,
    ROLLED_BACK marker = never resumed from) lives next to the readers
    that honor it.  Returns the epoch numbers newly fenced."""
    fenced = []
    for k, path in _list_epochs(ckpt_root(model_name)):
        if k > after_epoch \
                and os.path.exists(os.path.join(path, MANIFEST)) \
                and not os.path.exists(os.path.join(path, ROLLED_BACK)):
            mark_rolled_back(path, to_epoch=after_epoch, reason=reason)
            fenced.append(k)
    return fenced


def verify_epoch(path: str) -> Tuple[str, List[str]]:
    """(status, violations) for one epoch dir.

    - ``complete``: manifest present, well-formed, every artifact's
      digest verifies, extras consistent — violations empty.
    - ``incomplete``: no manifest (a crash mid-save; expected debris,
      not a violation).
    - ``rolled-back``: committed but fenced off by the health sentinel
      (``ROLLED_BACK.json``) — its params are suspected diverged, so it
      is never resumed from; clean, not a violation.
    - ``corrupt``: manifest present but lying — torn artifacts, digest
      mismatches, inconsistent counters.  Every lie is listed.
    """
    mp = os.path.join(path, MANIFEST)
    if not os.path.exists(mp):
        return "incomplete", []
    if os.path.exists(os.path.join(path, ROLLED_BACK)):
        return "rolled-back", []
    bad: List[str] = []
    try:
        with open(mp) as f:
            man = json.load(f)
    except (OSError, ValueError) as e:
        return "corrupt", [f"{mp}: manifest unreadable ({e})"]
    arts = man.get("artifacts")
    if not isinstance(arts, dict) or "epoch" not in man:
        return "corrupt", [f"{mp}: manifest missing required keys"]
    k = _epoch_num(os.path.basename(path))
    if k is not None and man["epoch"] != k:
        bad.append(f"{mp}: manifest epoch {man['epoch']} != dir epoch {k}")
    for name, meta in arts.items():
        ap = os.path.join(path, name)
        if name == "state":
            if not os.path.isdir(ap):
                bad.append(f"{ap}: state dir missing")
                continue
            digest, nbytes, nfiles = _digest_tree(ap)
        elif not os.path.exists(ap):
            bad.append(f"{ap}: artifact missing")
            continue
        else:
            digest, nbytes = _digest_file(ap)
        if digest != meta.get("sha256"):
            bad.append(f"{ap}: content digest mismatch "
                       f"(torn or modified after commit)")
        if meta.get("bytes") is not None \
                and int(meta["bytes"]) != int(nbytes):
            bad.append(f"{ap}: size mismatch — manifest says "
                       f"{int(meta['bytes'])} bytes, on disk "
                       f"{int(nbytes)} (truncated or padded after "
                       f"commit)")
    if "extras.json" in arts and not any("extras.json" in b for b in bad):
        try:
            with open(os.path.join(path, "extras.json")) as f:
                extras = json.load(f)
        except (OSError, ValueError) as e:
            extras = None
            bad.append(f"{path}/extras.json: unreadable ({e})")
        if extras is not None:
            es = int(extras.get("learner_step", man.get("learner_step", -1)))
            if es != int(man.get("learner_step", -1)):
                bad.append(
                    f"{path}: extras learner_step {es} != manifest "
                    f"learner_step {man.get('learner_step')}")
    return ("complete" if not bad else "corrupt"), bad


def resolve_epoch(model_name: str,
                  before: Optional[int] = None) -> Optional[EpochInfo]:
    """Newest COMPLETE epoch under ``{model_name}_ckpt``, or None.

    Torn (uncommitted) and digest-mismatched epochs are skipped with a
    note — a crash mid-save or a partially synced copy must cost at most
    one epoch of progress, never the run.  Epochs fenced off by a
    health-sentinel rollback (``ROLLED_BACK.json``) are skipped the same
    way.  ``before`` restricts the search to epochs numbered strictly
    below it — the progressive-rollback ladder (each successive rollback
    targets an older restore point than the last)."""
    root = ckpt_root(model_name)
    for k, path in _list_epochs(root):
        if before is not None and k >= before:
            continue
        status, bad = verify_epoch(path)
        if status == "complete":
            with open(os.path.join(path, MANIFEST)) as f:
                man = json.load(f)
            extras = {}
            if os.path.exists(os.path.join(path, "extras.json")):
                with open(os.path.join(path, "extras.json")) as f:
                    extras = json.load(f)
            return EpochInfo(path=path, epoch=k,
                             learner_step=int(man.get("learner_step", -1)),
                             manifest=man, extras=extras)
        if status == "corrupt":
            print(f"[checkpoint] skipping corrupt epoch {path}: "
                  + "; ".join(bad))
    return None


def await_epoch(model_name: str, min_step: int, timeout: float = 30.0,
                poll: float = 0.1) -> Optional[EpochInfo]:
    """Poll ``resolve_epoch`` until a digest-valid epoch with
    ``learner_step >= min_step`` appears (or the timeout lapses).  The
    ISSUE-15 rejoin leg: a replica learner re-entering the fleet loads
    the barrier epoch the lead replica commits for it — the commit and
    the load race only through the filesystem, and the atomic manifest
    rename means this poll can never observe a torn epoch."""
    deadline = time.monotonic() + timeout
    while True:
        info = resolve_epoch(model_name)
        if info is not None and info.learner_step >= min_step:
            return info
        if time.monotonic() >= deadline:
            return None
        time.sleep(poll)


def load_epoch_state(info: EpochInfo, template: Any) -> Any:
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.join(info.path, "state"), template)


def load_epoch_replay(info: EpochInfo, memory: Any) -> int:
    """Refill ``memory`` from the epoch's replay artifact.  Returns rows
    restored (0 when the epoch has none or the memory can't restore).
    Raises ``CheckpointMismatch`` on geometry drift."""
    import numpy as np

    if not info.has_replay or not hasattr(memory, "restore"):
        return 0
    with np.load(os.path.join(info.path, "replay.npz")) as z:
        data = {k: z[k] for k in z.files}
    validate_snapshot(memory, data, source=f"epoch {info.epoch} replay")
    try:
        out = memory.restore(data)
    except NotImplementedError:
        return 0
    if isinstance(out, int):  # device/sequence restores report the truth
        return out
    # restore() without a count: saved rows capped at the live capacity
    # (every restore keeps the newest rows that fit)
    rows = int(info.manifest["artifacts"]["replay.npz"].get(
        "rows", len(data.get("reward", ()))))
    cap = getattr(_unwrap(memory), "capacity", None)
    return min(rows, cap) if cap else rows


def gc_epochs(root: str, retain: int = 3,
              in_progress: Optional[int] = None) -> List[str]:
    """Delete committed epochs beyond the newest ``retain`` plus any
    uncommitted debris (except ``in_progress``, the epoch a caller is
    mid-writing).  Returns the paths removed.

    Rollback-fenced epochs (``ROLLED_BACK.json``) never count against
    the retention budget — they are unusable for resume, so letting
    them crowd out the newest GOOD epochs would destroy the run's only
    recovery points.  They are kept (as post-mortem evidence) while
    newer than the oldest retained good epoch, collected once older."""
    removed = []
    committed = []
    rolled = []
    for k, path in _list_epochs(root):
        if os.path.exists(os.path.join(path, MANIFEST)):
            if os.path.exists(os.path.join(path, ROLLED_BACK)):
                rolled.append((k, path))
            else:
                committed.append((k, path))
        elif k != in_progress:
            shutil.rmtree(path, ignore_errors=True)
            removed.append(path)
    kept = committed[:max(retain, 1)]
    for k, path in committed[max(retain, 1):]:
        shutil.rmtree(path, ignore_errors=True)
        removed.append(path)
    if kept:
        floor = kept[-1][0]  # oldest retained good epoch
        for k, path in rolled:
            if k < floor:
                shutil.rmtree(path, ignore_errors=True)
                removed.append(path)
    return removed


def fsck(root: str) -> dict:
    """Offline validation of a checkpoint root (the ``tools/ckpt_fsck.py``
    engine).  Returns a report dict; ``violations`` non-empty means a
    COMMITTED epoch is lying about its contents — incomplete epochs are
    expected crash debris and only reported."""
    report: dict = {"root": root, "epochs": [], "violations": [],
                    "newest_complete": None, "rolled_back": 0}
    if not os.path.isdir(root):
        report["violations"].append(f"{root}: no such directory")
        return report
    complete_steps: List[Tuple[int, int]] = []  # (epoch, learner_step)
    for k, path in _list_epochs(root):
        status, bad = verify_epoch(path)
        entry = {"epoch": k, "status": status, "violations": bad}
        if status in ("complete", "rolled-back"):
            with open(os.path.join(path, MANIFEST)) as f:
                man = json.load(f)
            entry["learner_step"] = man.get("learner_step")
            # per-artifact byte sizes (bandwidth X-ray, ISSUE 18) —
            # what tools/ckpt_fsck.py prints per epoch
            entry["artifacts"] = {
                name: int(meta.get("bytes", 0))
                for name, meta in (man.get("artifacts") or {}).items()}
            entry["bytes"] = sum(entry["artifacts"].values())
        if status == "complete":
            if report["newest_complete"] is None:
                report["newest_complete"] = k
            if entry["learner_step"] is not None:
                complete_steps.append((k, int(entry["learner_step"])))
        elif status == "rolled-back":
            report["rolled_back"] += 1
        report["epochs"].append(entry)
        report["violations"].extend(bad)
    # learner_step must grow with the epoch number across RESUMABLE
    # epochs.  A regression means two epochs disagree about time — on a
    # healthy run that cannot happen, and on a run that rolled back the
    # overtaken epochs carry ROLLED_BACK markers (status above) and are
    # excluded here, so a rolled-back-mid-training root still exits
    # clean.  A regression among unmarked complete epochs is a real lie.
    for (k_new, s_new), (k_old, s_old) in zip(complete_steps,
                                              complete_steps[1:]):
        if s_new < s_old:
            report["violations"].append(
                f"{root}: epoch {k_new} learner_step {s_new} regressed "
                f"below epoch {k_old}'s {s_old} (an unmarked rollback?)")
    return report
