"""Checkpointing.

The reference checkpoints weights only: the evaluator torch.saves a
state_dict every eval cycle (reference core/single_processes/evaluators.py:
97-100) and restores go through finetune load (reference main.py:45) and the
tester (reference testers.py:25) — optimizer state, counters, replay and RNG
are all lost on resume (SURVEY.md §5 "checkpoint/resume: minimal").

Here two tiers:

- **params-only** (reference-parity): a Flax-serialized msgpack of the param
  pytree at ``{model_name}.msgpack`` — written by the evaluator on its
  cadence, read by finetune/tester.  Restore needs a template tree of the
  same structure (``load_params(path, template)``).
- **full train state** (the resume the reference lacks): Orbax checkpoint of
  the whole ``TrainState`` (params + target + optimizer state + step) at
  ``{model_name}_state/``; ``restore_train_state`` resumes the learner
  exactly, counters included.
"""

from __future__ import annotations

import os
from typing import Any, Optional

PyTree = Any


def save_params(path: str, params: PyTree) -> str:
    """Write a params-only checkpoint (msgpack).  Returns the path."""
    import jax
    from flax import serialization

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    data = serialization.to_bytes(jax.device_get(params))
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)  # atomic: readers never see a torn file
    return path


def load_params(path: str, template: PyTree) -> PyTree:
    from flax import serialization

    with open(path, "rb") as f:
        return serialization.from_bytes(template, f.read())


def params_path(model_name: str) -> str:
    """``models/{machine}_{timestamp}.msgpack`` — the counterpart of the
    reference's ``.pth`` path (reference utils/options.py:42)."""
    return model_name + ".msgpack"


def state_dir(model_name: str) -> str:
    return os.path.abspath(model_name + "_state")


def save_train_state(model_name: str, state: Any) -> str:
    """Orbax save of the full TrainState (async-safe single snapshot)."""
    import jax
    import orbax.checkpoint as ocp

    path = state_dir(model_name)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, jax.device_get(state), force=True)
    ckptr.wait_until_finished()
    return path


def replay_path(model_name: str) -> str:
    return model_name + "_replay.npz"


def save_replay(model_name: str, memory: Any) -> Optional[str]:
    """Write the replay contents next to the train state — the resume leg
    the reference never had (SURVEY.md §5 "Not checkpointed: ... replay").
    Works for any memory exposing ``snapshot() -> dict`` (shared ring, PER
    incl. leaf priorities, HBM device rings; queue owners drain-then-
    delegate).  Returns the path, or None when the memory type has no
    snapshot surface."""
    import numpy as np

    if not hasattr(memory, "snapshot"):
        return None
    try:
        data = memory.snapshot()
    except NotImplementedError:  # wrapper around an unsupported memory
        return None
    path = replay_path(model_name)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp.npz"
    np.savez_compressed(tmp, **data)
    os.replace(tmp, path)
    return path


def load_replay(model_name: str, memory: Any) -> bool:
    """Refill ``memory`` from a prior save_replay; False when absent or the
    memory type has no restore surface."""
    import numpy as np

    path = replay_path(model_name)
    if not hasattr(memory, "restore") or not os.path.exists(path):
        return False
    with np.load(path) as z:
        try:
            memory.restore({k: z[k] for k in z.files})
        except NotImplementedError:
            return False
    return True


def restore_train_state(model_name: str, template: Any) -> Optional[Any]:
    """Restore a TrainState saved by ``save_train_state``; None if absent."""
    import orbax.checkpoint as ocp

    path = state_dir(model_name)
    if not os.path.isdir(path):
        return None
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path, template)
