"""Frame preprocessing: bilinear uint8 resize without OpenCV.

The reference Atari pipeline resizes the grayscale ALE screen with
``cv2.resize(..., INTER_LINEAR)`` (reference core/envs/atari_env.py:53-58);
this image ships no cv2, so the resize is first-party: a C++ kernel
(native/image_ops.cpp) with a bit-identical vectorized numpy fallback.
Convention (both paths): pixel-center alignment — the source coordinate of
output pixel i is ``(i + 0.5) * (in/out) - 0.5`` clamped into the source —
interpolated in float64 and rounded half-up to uint8.

"Bit-identical" applies to the C++-vs-numpy pair only.  cv2's uint8 path
interpolates in 11-bit fixed point, so outputs may differ from real
cv2.INTER_LINEAR by ±1 LSB — parity tests against cv2-produced frames
must use a tolerance of 1.
"""

from __future__ import annotations

import ctypes
import functools
from typing import Optional, Tuple

import numpy as np

_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _native_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is None and not _lib_failed:
        try:
            from native.build import load_library

            lib = load_library("image_ops")
            lib.resize_bilinear_u8.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
            _lib = lib
        except Exception:  # noqa: BLE001 - no toolchain: numpy fallback
            _lib_failed = True
    return _lib


@functools.lru_cache(maxsize=8)
def _axis(n_in: int, n_out: int):
    s = np.clip((np.arange(n_out) + 0.5) * (n_in / n_out) - 0.5,
                0.0, n_in - 1.0)
    i0 = np.floor(s).astype(np.intp)
    i1 = np.minimum(i0 + 1, n_in - 1)
    return i0, i1, s - i0


def resize_bilinear_np(frames: np.ndarray, size: Tuple[int, int]
                       ) -> np.ndarray:
    """Numpy reference: (..., H, W) uint8 -> (..., oh, ow) uint8."""
    oh, ow = size
    h, w = frames.shape[-2], frames.shape[-1]
    y0, y1, fy = _axis(h, oh)
    x0, x1, fx = _axis(w, ow)
    f = frames.astype(np.float64)
    ty, tb = f[..., y0, :], f[..., y1, :]
    top = ty[..., :, x0] * (1 - fx) + ty[..., :, x1] * fx
    bot = tb[..., :, x0] * (1 - fx) + tb[..., :, x1] * fx
    out = top * (1 - fy)[:, None] + bot * fy[:, None]
    return np.floor(out + 0.5).astype(np.uint8)


def resize_bilinear(frames: np.ndarray, size: Tuple[int, int]
                    ) -> np.ndarray:
    """(..., H, W) uint8 -> (..., oh, ow) uint8 via the native kernel when
    the toolchain built it, else the numpy reference (same bits)."""
    frames = np.ascontiguousarray(frames, dtype=np.uint8)
    oh, ow = size
    lib = _native_lib()
    if lib is None:
        return resize_bilinear_np(frames, size)
    lead = frames.shape[:-2]
    h, w = frames.shape[-2], frames.shape[-1]
    n = int(np.prod(lead)) if lead else 1
    out = np.empty((*lead, oh, ow), dtype=np.uint8)
    lib.resize_bilinear_u8(
        frames.ctypes.data_as(ctypes.c_void_p), n, h, w,
        out.ctypes.data_as(ctypes.c_void_p), oh, ow)
    return out
