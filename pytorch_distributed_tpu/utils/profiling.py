"""Tracing / profiling subsystem.

The reference has none — only stdout banners and TensorBoard scalars
(SURVEY.md §5 "tracing: none").  Two first-class tools here:

- ``StepTimer``: cheap per-role wall-time accounting.  Workers wrap their
  hot-loop phases (act / env.step / feed / learn / drain / publish) and the
  accumulated per-phase seconds flow into the metrics stream on the normal
  logger cadence, so "where does the step time go" is a dashboard read, not
  a guess.
- ``trace``: a context manager around ``jax.profiler.trace`` that captures
  a real XLA trace (TensorBoard-viewable) for a bounded window, gated so it
  can be left in production code and switched on with an env var
  (``TPU_APEX_PROFILE=dir``).

Cross-role request tracing (per-hop trace ids + latency histograms) lives
in utils/tracing.py; the post-mortem event rings in
utils/flight_recorder.py.  README "Observability" documents all three
together with the env knobs.
"""

from __future__ import annotations

import contextlib
import os
import re
import threading
import time
import warnings
from typing import Dict, Iterator, Optional


class StepTimer:
    """Accumulates wall seconds per named phase; drain() returns and resets
    per-phase mean/max/call-count as flat metrics.  The max and count ride
    along because a mean averages stalls away: one 2 s drain in a window
    of 100 × 2 ms drains reads as 22 ms mean — the ``*_max_ms`` row is
    what makes the stall visible."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        self._acc: Dict[str, float] = {}
        self._max: Dict[str, float] = {}
        self._n: Dict[str, int] = {}
        self._last_wall: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float) -> None:
        """Accumulate an externally-timed duration — for callers that
        need one measurement to land under several phase names (the
        pipelined actor loop books dispatch+sync both under their own
        phases and under the serial loop's ``act`` so dashboards stay
        comparable across schedules)."""
        self._acc[name] = self._acc.get(name, 0.0) + seconds
        if seconds > self._max.get(name, 0.0):
            self._max[name] = seconds
        self._n[name] = self._n.get(name, 0) + 1
        # wall epoch of the phase's LAST occurrence this window: drained
        # rows otherwise carry only the flush wall, which lets a
        # timeline mis-order a stalled phase against blackbox events by
        # a whole cadence (ISSUE 8 satellite; tools/timeline.py reads
        # the *_last_wall row as the phase's true clock position)
        self._last_wall[name] = time.time()

    def drain(self) -> Dict[str, float]:
        out = {}
        for name, secs in self._acc.items():
            n = self._n[name]
            out[f"{self.prefix}/time_{name}_ms"] = secs / max(n, 1) * 1e3
            out[f"{self.prefix}/time_{name}_max_ms"] = \
                self._max.get(name, 0.0) * 1e3
            out[f"{self.prefix}/time_{name}_calls"] = float(n)
            # the window's TOTAL: means hide call-count asymmetry, so
            # per-phase means never sum to wall time — totals do, which
            # is what a stacked phase-share plot needs
            # (tools/plot_run.py --phase-breakdown)
            out[f"{self.prefix}/time_{name}_total_ms"] = secs * 1e3
            # schema-additive (plot_run's _total_ms regex ignores it):
            # the epoch above, exported as a plain scalar row
            out[f"{self.prefix}/time_{name}_last_wall"] = \
                self._last_wall.get(name, 0.0)
        self._acc.clear()
        self._max.clear()
        self._n.clear()
        self._last_wall.clear()
        return out


def sanitize_label(label: str) -> str:
    """A trace label safe to join into the trace path.  Labels arrive
    from callers AND from the network (the DCN ``T_PROFILE`` verb
    forwards a client-supplied label), so anything outside
    ``[A-Za-z0-9._-]`` — path separators above all — is squashed to
    ``-`` and leading dots are stripped; an emptied label falls back to
    ``trace``."""
    clean = re.sub(r"[^A-Za-z0-9._-]+", "-", str(label)).lstrip(".-")
    return clean or "trace"


# one profiler per process: jax.profiler.trace raises on a nested
# start, which used to turn an inner library trace (mfu_probe inside a
# TPU_APEX_PROFILE'd run) into a crash of the OUTER capture
_trace_lock = threading.Lock()
_trace_active = False


@contextlib.contextmanager
def trace(label: str, log_dir: Optional[str] = None
          ) -> Iterator[Optional[str]]:
    """Capture an XLA profiler trace for the enclosed block when enabled.

    Enabled by passing ``log_dir`` or by setting ``TPU_APEX_PROFILE`` to a
    directory; otherwise a no-op.  Yields the trace directory (None when
    disabled or when a trace is already active — a nested capture is a
    warning + no-op, never a profiler error: the outer window keeps
    recording and the inner caller learns from the None).  View with
    TensorBoard's profile plugin.
    """
    global _trace_active
    target = log_dir or os.environ.get("TPU_APEX_PROFILE")
    if not target:
        yield None
        return
    with _trace_lock:
        nested = _trace_active
        if not nested:
            _trace_active = True
    if nested:
        # warn + no-op OUTSIDE the lock: yielding with it held would
        # stall the outer trace's exit behind this caller's whole body
        # (and deadlock a doubly-nested same-thread capture)
        warnings.warn(
            f"profiling.trace({label!r}): a trace is already active "
            f"in this process; nested capture skipped (the outer "
            f"window keeps recording)", stacklevel=3)
        yield None
        return
    try:
        import jax

        os.makedirs(target, exist_ok=True)
        path = os.path.join(target, sanitize_label(label))
        with jax.profiler.trace(path):
            yield path
    finally:
        with _trace_lock:
            _trace_active = False
