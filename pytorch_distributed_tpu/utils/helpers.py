"""Pytree parameter-update helpers.

Functional equivalents of reference utils/helpers.py:19-25
(``update_target_model``): the reference mutates a torch module in place;
here both flavours are pure pytree→pytree functions that jit/fuse on TPU.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

PyTree = Any


def hard_update(target: PyTree, online: PyTree) -> PyTree:
    """Full copy — reference utils/helpers.py:24-25 (the every-N-steps
    branch).  Pure: returns the new target pytree."""
    return jax.tree_util.tree_map(lambda o: o, online)


def soft_update(target: PyTree, online: PyTree, tau: float) -> PyTree:
    """Polyak averaging ``t <- (1-tau) t + tau o`` — reference
    utils/helpers.py:20-23 (the tau<1 branch, used by DDPG)."""
    return jax.tree_util.tree_map(
        lambda t, o: (1.0 - tau) * t + tau * o, target, online
    )


def periodic_update(target: PyTree, online: PyTree, step: jnp.ndarray,
                    period: int) -> PyTree:
    """Hard update every ``period`` learner steps, as a jit-safe select —
    reference dqn_learner.py:91 calls update_target_model each step and the
    helper internally gates on ``step % period == 0``."""
    do = (step % period) == 0
    return jax.tree_util.tree_map(
        lambda t, o: jnp.where(do, o, t), target, online
    )


def update_target(target: PyTree, online: PyTree, step: jnp.ndarray,
                  target_model_update: float) -> PyTree:
    """Dispatch on the reference's overloaded ``target_model_update``
    scalar: <1 means soft tau-update every step, >=1 means hard update every
    N steps (reference utils/helpers.py:19-25)."""
    if target_model_update < 1:
        return soft_update(target, online, float(target_model_update))
    return periodic_update(target, online, step, int(target_model_update))


def enable_compile_cache(cache_dir: str | None = None) -> str | None:
    """Turn on JAX's persistent XLA compile cache for this process AND its
    spawned workers — TPU platform only.

    Both halves are load-bearing: ``jax.config.update`` flips the already-
    imported jax in this process (the env var alone is too late once
    sitecustomize pre-imported jax), while the env var is inherited by
    spawn children whose fresh jax import reads it.  Repeated drives on a
    tunnelled chip otherwise pay minutes of identical remote compiles per
    process.

    On the CPU backend this is a NO-OP: XLA's CPU AOT loader can
    nondeterministically SIGABRT when re-loading cached executables of
    collective-dense multi-device programs (feature-string mismatch the
    loader itself warns about; A/B-reproduced 2026-07-31 — 3/8 aborts
    with cache vs 0/22 without on the pp pipeline step).  TPU cache
    entries are TPU executables that never cross that loader."""
    import os
    import tempfile

    if jax.devices()[0].platform != "tpu":
        # make sure spawn children don't re-enable it either, AND kill it
        # in this process too — an ambient env var set before jax import
        # has already landed in the live config
        os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
        jax.config.update("jax_compilation_cache_dir", None)
        return None
    cache_dir = os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        cache_dir or os.path.join(tempfile.gettempdir(), "pdtpu_xla_cache"))
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    return cache_dir


def host_cpu_device():
    """The host CPU jax device — always present alongside any accelerator
    backend."""
    return jax.local_devices(backend="cpu")[0]


def pin_to_cpu(tree: PyTree) -> PyTree:
    """Commit a pytree to the host CPU device.  Rollout-side inference
    (actors, evaluator, tester) pins its params/keys here so batch-1
    forwards compile and run on the host instead of round-tripping a
    (possibly tunnelled) accelerator — the learner alone owns the mesh
    (SURVEY.md §7 design stance).  jit follows committed inputs, so no
    backend= plumbing is needed in the act functions."""
    return jax.device_put(tree, host_cpu_device())


def unravel_on_cpu(unravel, flat) -> PyTree:
    """unravel (ravel_pytree's inverse) onto the host CPU: the jnp concat/
    reshape ops inside it would otherwise land on the default device."""
    with jax.default_device(host_cpu_device()):
        return pin_to_cpu(unravel(flat))


def global_norm(tree: PyTree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x)) for x in leaves))


def tree_size(tree: PyTree) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across jax versions: newer releases expose it at
    top level with the ``check_vma`` kwarg; 0.4.x ships it as
    ``jax.experimental.shard_map.shard_map`` with the same knob named
    ``check_rep``.  One compat entry so the sp/pp kernels (ops/
    ring_attention.py, parallel/pipeline.py) run on either."""
    try:
        sm = jax.shard_map
    except AttributeError:  # jax 0.4.x
        from jax.experimental.shard_map import shard_map as sm_old

        return sm_old(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_vma=check_vma)
