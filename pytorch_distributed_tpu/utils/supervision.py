"""Crash-loop restart policy shared by the worker supervisors.

One policy, two call sites — the single-host runtime monitor
(runtime.py ``_monitor``) and the multi-host actor-host supervisor
(fleet.py ``run_fleet_actors``).  The reference has no supervision at all
(SURVEY.md §5: a dead actor silently reduces throughput, a dead learner
hangs the run); this is the "failure detection" subsystem it lacked.

Per slot: a restart is granted while fewer than ``max_restarts``
incarnations have crashed *young*; an incarnation that lived longer than
``grace`` seconds proves the previous crash was isolated and resets the
slot's budget, so only genuine crash loops exhaust it.
"""

from __future__ import annotations

import multiprocessing as mp
import time
from typing import Dict, Iterable, List, Optional

_CTX = mp.get_context("spawn")

# Worker exit-code vocabulary shared by the supervisors.  Distinguishing
# "crashed" from "lost its session" matters in logs: a fleet of actors
# all exiting EXIT_DISCONNECTED points at the learner host / network, not
# at the actor code (fleet.py maps DcnClient.disconnected to this code).
# EXIT_HUNG marks a worker the hang watchdog SIGKILLed for making no
# progress within its deadline (alive-but-stuck — the failure mode that
# never produces an exit code on its own).
EXIT_OK = 0
EXIT_CRASH = 1
EXIT_DISCONNECTED = 3
EXIT_HUNG = 4


def describe_exit(code: Optional[int]) -> str:
    """Human-readable worker exit for supervisor logs."""
    if code == EXIT_OK:
        return "exit 0 (run complete)"
    if code == EXIT_DISCONNECTED:
        return f"exit {code} (DCN session lost)"
    if code == EXIT_HUNG:
        return f"exit {code} (hung; watchdog killed)"
    if code is not None and code < 0:
        return f"signal {-code}"
    return f"exit {code} (crash)"


class ProgressBoard:
    """Per-worker liveness-progress marks for the hang watchdog.

    A crash produces an exit code; a *hang* produces nothing — the
    reference (and this repo before the health sentinel) would wait on a
    stuck worker forever.  Every supervised role owns a progress counter
    already (actor ticks, learner steps, eval episodes); this board
    makes those counters *observable across processes*: one
    ``mp.Value`` pair per slot label (wall-clock of the last mark + a
    mark count), created by the supervisor BEFORE spawn so the shared
    values ride the worker args' pickle.  ``bump`` is the worker-side
    hot call: two lock-free Value stores.

    ``hung(deadline, grace, now)`` returns the labels whose last mark is
    older than ``deadline`` seconds — except workers that have never
    marked, which get ``deadline + grace`` from their start stamp (the
    compile-grace window: a first jit can legitimately take minutes).
    Supervisors SIGKILL hung workers (flight-recorder dump first) and
    respawn through the normal RestartBudget with EXIT_HUNG.
    """

    def __init__(self, labels: Iterable[str]):
        self._last = {lb: _CTX.Value("d", 0.0, lock=False) for lb in labels}
        self._count = {lb: _CTX.Value("l", 0, lock=False) for lb in labels}

    @property
    def labels(self) -> List[str]:
        return list(self._last)

    def note_start(self, label: str) -> None:
        """Stamp a (re)spawn: the grace window restarts from here."""
        if label in self._last:
            self._last[label].value = time.time()
            self._count[label].value = 0

    def bump(self, label: str, n: int = 1) -> None:
        v = self._last.get(label)
        if v is None:
            return
        v.value = time.time()
        self._count[label].value += n

    def marks(self, label: str) -> int:
        c = self._count.get(label)
        return int(c.value) if c is not None else 0

    def age(self, label: str, now: Optional[float] = None) -> float:
        """Seconds since the label's last mark (inf before note_start)."""
        v = self._last.get(label)
        if v is None or v.value == 0.0:
            return float("inf")
        return (time.time() if now is None else now) - v.value

    def hung(self, deadline: float, grace: float = 0.0,
             now: Optional[float] = None,
             only: Optional[Iterable[str]] = None) -> List[str]:
        """Labels with no progress inside their deadline.  Workers that
        have never bumped (still compiling / importing) answer to
        ``deadline + grace`` instead; workers never started (no
        note_start) are skipped — the supervisor hasn't spawned them."""
        if deadline <= 0:
            return []
        now = time.time() if now is None else now
        out = []
        for lb in (self._last if only is None else only):
            v = self._last.get(lb)
            if v is None or v.value == 0.0:
                continue
            limit = deadline if self.marks(lb) > 0 else deadline + grace
            if now - v.value > limit:
                out.append(lb)
        return out


class RestartBudget:
    """``request_restart(slot)`` returns the respawn delay in seconds —
    exponential backoff capped at ``max_backoff`` when ``backoff`` is on
    (a hot respawn loop against a gateway still holding the dead worker's
    slot would burn the budget), 0.0 otherwise — or None when the slot is
    out of budget.  Call ``note_birth`` whenever a slot (re)spawns."""

    def __init__(self, max_restarts: int = 3, grace: float = 300.0,
                 backoff: bool = False, max_backoff: float = 30.0):
        self.max_restarts = max_restarts
        self.grace = grace
        self.backoff = backoff
        self.max_backoff = max_backoff
        self._restarts: Dict[int, int] = {}
        self._born: Dict[int, float] = {}

    def note_birth(self, slot: int) -> None:
        self._born[slot] = time.monotonic()

    def count(self, slot: int) -> int:
        return self._restarts.get(slot, 0)

    def remaining(self) -> Dict[int, int]:
        """Per-slot restarts left, for every slot ever born — the health
        plane's view (DCN STATUS verb / tools/fleet_top.py)."""
        return {slot: max(0, self.max_restarts - self._restarts.get(slot, 0))
                for slot in self._born}

    def request_restart(self, slot: int) -> Optional[float]:
        born = self._born.get(slot)
        # only a RECORDED incarnation that outlived the grace period
        # proves the crash isolated; a slot with no recorded birth must
        # not read as an ancient incarnation (it used to — monotonic==0
        # birth made every unborn crash "old", silently refilling the
        # budget forever for callers that skip note_birth)
        if born is not None and time.monotonic() - born > self.grace:
            self._restarts[slot] = 0  # isolated crash, not a crash loop
        n = self._restarts.get(slot, 0)
        if n >= self.max_restarts:
            return None
        self._restarts[slot] = n + 1
        if not self.backoff:
            return 0.0
        return min(2.0 * 2 ** n, self.max_backoff)
