"""Crash-loop restart policy shared by the worker supervisors.

One policy, two call sites — the single-host runtime monitor
(runtime.py ``_monitor``) and the multi-host actor-host supervisor
(fleet.py ``run_fleet_actors``).  The reference has no supervision at all
(SURVEY.md §5: a dead actor silently reduces throughput, a dead learner
hangs the run); this is the "failure detection" subsystem it lacked.

Per slot: a restart is granted while fewer than ``max_restarts``
incarnations have crashed *young*; an incarnation that lived longer than
``grace`` seconds proves the previous crash was isolated and resets the
slot's budget, so only genuine crash loops exhaust it.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

# Worker exit-code vocabulary shared by the supervisors.  Distinguishing
# "crashed" from "lost its session" matters in logs: a fleet of actors
# all exiting EXIT_DISCONNECTED points at the learner host / network, not
# at the actor code (fleet.py maps DcnClient.disconnected to this code).
EXIT_OK = 0
EXIT_CRASH = 1
EXIT_DISCONNECTED = 3


def describe_exit(code: Optional[int]) -> str:
    """Human-readable worker exit for supervisor logs."""
    if code == EXIT_OK:
        return "exit 0 (run complete)"
    if code == EXIT_DISCONNECTED:
        return f"exit {code} (DCN session lost)"
    if code is not None and code < 0:
        return f"signal {-code}"
    return f"exit {code} (crash)"


class RestartBudget:
    """``request_restart(slot)`` returns the respawn delay in seconds —
    exponential backoff capped at ``max_backoff`` when ``backoff`` is on
    (a hot respawn loop against a gateway still holding the dead worker's
    slot would burn the budget), 0.0 otherwise — or None when the slot is
    out of budget.  Call ``note_birth`` whenever a slot (re)spawns."""

    def __init__(self, max_restarts: int = 3, grace: float = 300.0,
                 backoff: bool = False, max_backoff: float = 30.0):
        self.max_restarts = max_restarts
        self.grace = grace
        self.backoff = backoff
        self.max_backoff = max_backoff
        self._restarts: Dict[int, int] = {}
        self._born: Dict[int, float] = {}

    def note_birth(self, slot: int) -> None:
        self._born[slot] = time.monotonic()

    def count(self, slot: int) -> int:
        return self._restarts.get(slot, 0)

    def remaining(self) -> Dict[int, int]:
        """Per-slot restarts left, for every slot ever born — the health
        plane's view (DCN STATUS verb / tools/fleet_top.py)."""
        return {slot: max(0, self.max_restarts - self._restarts.get(slot, 0))
                for slot in self._born}

    def request_restart(self, slot: int) -> Optional[float]:
        born = self._born.get(slot)
        # only a RECORDED incarnation that outlived the grace period
        # proves the crash isolated; a slot with no recorded birth must
        # not read as an ancient incarnation (it used to — monotonic==0
        # birth made every unborn crash "old", silently refilling the
        # budget forever for callers that skip note_birth)
        if born is not None and time.monotonic() - born > self.grace:
            self._restarts[slot] = 0  # isolated crash, not a crash loop
        n = self._restarts.get(slot, 0)
        if n >= self.max_restarts:
            return None
        self._restarts[slot] = n + 1
        if not self.backoff:
            return 0.0
        return min(2.0 * 2 ** n, self.max_backoff)
