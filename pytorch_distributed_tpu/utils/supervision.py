"""Crash-loop restart policy shared by the worker supervisors.

One policy, two call sites — the single-host runtime monitor
(runtime.py ``_monitor``) and the multi-host actor-host supervisor
(fleet.py ``run_fleet_actors``).  The reference has no supervision at all
(SURVEY.md §5: a dead actor silently reduces throughput, a dead learner
hangs the run); this is the "failure detection" subsystem it lacked.

Per slot: a restart is granted while fewer than ``max_restarts``
incarnations have crashed *young*; an incarnation that lived longer than
``grace`` seconds proves the previous crash was isolated and resets the
slot's budget, so only genuine crash loops exhaust it.
"""

from __future__ import annotations

import time
from typing import Dict, Optional


class RestartBudget:
    """``request_restart(slot)`` returns the respawn delay in seconds —
    exponential backoff capped at ``max_backoff`` when ``backoff`` is on
    (a hot respawn loop against a gateway still holding the dead worker's
    slot would burn the budget), 0.0 otherwise — or None when the slot is
    out of budget.  Call ``note_birth`` whenever a slot (re)spawns."""

    def __init__(self, max_restarts: int = 3, grace: float = 300.0,
                 backoff: bool = False, max_backoff: float = 30.0):
        self.max_restarts = max_restarts
        self.grace = grace
        self.backoff = backoff
        self.max_backoff = max_backoff
        self._restarts: Dict[int, int] = {}
        self._born: Dict[int, float] = {}

    def note_birth(self, slot: int) -> None:
        self._born[slot] = time.monotonic()

    def count(self, slot: int) -> int:
        return self._restarts.get(slot, 0)

    def request_restart(self, slot: int) -> Optional[float]:
        if time.monotonic() - self._born.get(slot, 0.0) > self.grace:
            self._restarts[slot] = 0  # isolated crash, not a crash loop
        n = self._restarts.get(slot, 0)
        if n >= self.max_restarts:
            return None
        self._restarts[slot] = n + 1
        if not self.backoff:
            return 0.0
        return min(2.0 * 2 ** n, self.max_backoff)
