"""Training health sentinel: numeric guards, anomaly detection, quarantine.

The fleet survives *process* faults — DCN reconnects, SIGKILL with
crash-consistent epochs, post-mortem blackboxes — but until this module
nothing protected the *training computation*: one NaN gradient reached
Adam and every parameter was garbage forever, one poisoned experience
chunk sat in replay getting re-sampled, and an alive-but-stuck worker
stalled the run with exit code 0 never arriving.  This module is the
detection half of the detection → containment → recovery ladder:

- **in-jit numeric guards** (``finite_guard``): wraps any learner train
  step ``(TrainState, batch) -> (TrainState, metrics, td)`` so a step
  whose loss/grad-norm/TD comes out non-finite is *skipped* — params,
  opt-state and the step counter pass through unchanged via an in-graph
  select, and the returned metrics carry ``learner/skipped`` so the PER
  write-back paths (memory/device_per.py, memory/device_sequence.py, the
  host path in agents/learner.py) suppress the priority scatter for that
  step.  The guard is pure XLA — no host syncs, no extra dispatches —
  and costs a handful of selects (<2% of a learner step; bench.py
  ``health_overhead`` proves it on whatever chip runs the bench).
- **host-side anomaly detection** (``AnomalyDetector``): rolling EWMA
  z-score on the loss, grad-norm spike ratio, |TD| explosion,
  priority-mass collapse and the skipped-step counter, evaluated on the
  learner's stats cadence.  Past ``anomaly_threshold`` consecutive
  anomalous windows the learner triggers an automatic rollback to the
  last good checkpoint epoch (agents/learner.py; bounded by
  ``max_rollbacks`` before the run fails fast).
- **ingest quarantine** (``ChunkValidator`` + ``QuarantineStore``):
  transitions are validated at the single-owner ingest boundaries —
  the learner-side queue drains (memory/feeder.py QueueOwner,
  memory/device_replay.py DeviceReplayIngest) and the DCN gateway
  (parallel/dcn.py) — and offenders are written to
  ``{log_dir}/quarantine/<source>-<n>.npz`` with their trace id instead
  of entering replay.  Per-source counters feed the T_STATUS health
  plane so ``fleet_top`` shows which actor is poisoning.

Knobs live in ``config.HealthParams``; every field is env-overridable as
``TPU_APEX_HEALTH_<FIELD>`` (the same spawn-inheritance trick the fault
planes use), so drills and fleet launchers can flip them without
plumbing.  ``TPU_APEX_QUARANTINE=0`` kills the ingest-validation plane
entirely (chunks flow unchecked, the pre-sentinel behaviour).

The hang-watchdog half of the sentinel lives in utils/supervision.py
(``ProgressBoard``) and the supervisors (runtime.py, fleet.py).
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# metrics key every consumer of the guard keys on: 1.0 for a skipped
# (non-finite) substep, 0.0 otherwise; summed — not last-sampled — over
# fused multi-step dispatches (reduce_scan_metrics)
SKIPPED_KEY = "learner/skipped"

_ENV_PREFIX = "TPU_APEX_HEALTH_"


def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("0", "false", "off", "no", "")


def resolve(hp) -> Any:
    """Apply ``TPU_APEX_HEALTH_<FIELD>`` env overrides to a HealthParams
    (config.py) — same override-by-env contract as the fault planes, so
    a drill can flip sentinel knobs on spawn children without threading
    them through every constructor.  Returns a NEW instance; the input
    is never mutated (Options rides spawn pickles)."""
    changes = {}
    for f in dataclasses.fields(hp):
        raw = os.environ.get(_ENV_PREFIX + f.name.upper())
        if raw is None:
            continue
        if f.type in ("bool", bool) or isinstance(getattr(hp, f.name), bool):
            changes[f.name] = raw.strip().lower() not in (
                "0", "false", "off", "no", "")
        elif isinstance(getattr(hp, f.name), int):
            changes[f.name] = int(float(raw))
        else:
            changes[f.name] = float(raw)
    return dataclasses.replace(hp, **changes) if changes else hp


def quarantine_active() -> bool:
    """Is the ingest-validation plane on in this process?  Default on —
    the per-transition cost is a few scalar finiteness checks (image
    states are uint8 and skip the array scan entirely)."""
    return _env_flag("TPU_APEX_QUARANTINE", True)


# ---------------------------------------------------------------------------
# in-jit numeric guards
# ---------------------------------------------------------------------------

def finite_guard(step_fn):
    """Wrap a ``(TrainState, batch) -> (TrainState, metrics, td)`` train
    step with an in-graph finite check: when any metric scalar (loss,
    grad norm, ...) or the TD/priority output is non-finite, the ENTIRE
    candidate state is discarded and the input state passes through
    unchanged (``jnp.where`` select per leaf — donation-safe, no host
    round trip), so one bad batch never reaches Adam, the target net, or
    the step counter.  ``metrics[SKIPPED_KEY]`` reports the skip; the
    raw (possibly non-finite) loss stays in the metrics so the host-side
    anomaly detector sees what actually happened.  TD output is zeroed
    on a skip so a write-back path that ignores the flag still cannot
    scatter NaN priorities."""
    import jax
    import jax.numpy as jnp

    def guarded(state, batch):
        new_state, metrics, td = step_fn(state, batch)
        ok = jnp.all(jnp.isfinite(td))
        for v in metrics.values():
            ok = ok & jnp.all(jnp.isfinite(v))
        sel = lambda n, o: jax.tree_util.tree_map(
            lambda a, b: jnp.where(ok, a, b), n, o)
        out_state = sel(new_state, state)
        out_td = jnp.where(ok, td, jnp.zeros_like(td))
        metrics = dict(metrics)
        metrics[SKIPPED_KEY] = 1.0 - ok.astype(jnp.float32)
        return out_state, metrics, out_td

    return guarded


def reduce_scan_metrics(metrics):
    """Collapse a scanned fused dispatch's stacked substep metrics to one
    row: the last substep's value per key — the sampling contract the
    learner's stats cadence already has — EXCEPT counter-like keys
    (``learner/skipped``), which sum over the scan so a dispatch reports
    how many of its K substeps were skipped, not just whether the last
    one was."""
    import jax
    import jax.numpy as jnp

    if not isinstance(metrics, dict):
        return jax.tree_util.tree_map(lambda x: x[-1], metrics)
    return {k: (jnp.sum(v, axis=0) if k == SKIPPED_KEY else v[-1])
            for k, v in metrics.items()}


def suppress_writeback(ok_flag, updated_replay, prior_replay):
    """Select between a priority-updated replay state and the untouched
    one on the guard's skip flag — the fused PER planes call this so a
    skipped step's (zeroed) TD never overwrites real priorities."""
    import jax
    import jax.numpy as jnp

    ok = ok_flag < 0.5  # SKIPPED_KEY semantics: 1.0 == skipped
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), updated_replay, prior_replay)


# ---------------------------------------------------------------------------
# priority X-ray (ISSUE 8): distribution telemetry over the PER leaves
# ---------------------------------------------------------------------------

# fixed log10 bucket grid shared with the in-jit device twin
# (memory/device_per.priority_xray_device) so fleet_top renders either
PRIORITY_XRAY_LOG10_LO = -6.0
PRIORITY_XRAY_LOG10_HI = 3.0


def provenance_stats(prov, current_version: int,
                     learner_step: int) -> Optional[Dict[str, Any]]:
    """The data-plane staleness math, shared by the learner's stats
    cadence (agents/learner.py) and the overhead bench
    (bench.bench_provenance_overhead) so the bench measures EXACTLY the
    production computation.  ``prov`` is an (n, 4) provenance matrix;
    sentinel rows (actor_id < 0) are masked out.  Returns None when no
    row carries provenance, else arrays ``staleness`` (versions),
    ``age`` (learner steps) and ``shares`` (per-actor sample
    fraction)."""
    prov = np.asarray(prov)
    known = prov[prov[:, 0] >= 0]
    if not len(known):
        return None
    _ids, cnt = np.unique(known[:, 0], return_counts=True)
    return {
        "staleness": np.maximum(current_version - known[:, 2], 0),
        "age": np.maximum(learner_step - known[:, 3], 0),
        "shares": cnt / float(len(known)),
    }


def priority_xray(leaves, bins: int = 16) -> Optional[Dict[str, Any]]:
    """Summarize a PER leaf vector (p^alpha units) into the data-plane
    X-ray: a log10-bucketed histogram over the fixed [1e-6, 1e3) decade
    grid, the effective sample size ``(sum p)^2 / sum p^2`` (how many
    rows the sampler EFFECTIVELY draws from — n means uniform, ~1 means
    one row dominates), and its fraction of the row count.  This is the
    distribution the AnomalyDetector consumes instead of a bare mass
    ratio: mass can look healthy while ESS has collapsed onto a handful
    of rows.  Returns None for an empty/all-zero leaf set."""
    p = np.asarray(leaves, dtype=np.float64)
    p = p[p > 0]
    if p.size == 0:
        return None
    s1, s2 = float(p.sum()), float((p * p).sum())
    ess = (s1 * s1 / s2) if s2 > 0 else 0.0
    logp = np.log10(np.maximum(p, 10.0 ** PRIORITY_XRAY_LOG10_LO))
    t = (logp - PRIORITY_XRAY_LOG10_LO) / (
        PRIORITY_XRAY_LOG10_HI - PRIORITY_XRAY_LOG10_LO)
    b = np.clip((t * bins).astype(np.int64), 0, bins - 1)
    counts = np.bincount(b, minlength=bins)[:bins]
    return {
        "rows": int(p.size),
        "mass": s1,
        "ess": ess,
        "ess_frac": ess / p.size,
        "counts": counts,
        "log10_lo": PRIORITY_XRAY_LOG10_LO,
        "log10_hi": PRIORITY_XRAY_LOG10_HI,
        "p_max": float(p.max()),
    }


# ---------------------------------------------------------------------------
# host-side rolling anomaly detection
# ---------------------------------------------------------------------------

class _Ewma:
    """Exponentially weighted mean/std with a warmup count."""

    def __init__(self, decay: float = 0.97):
        self.decay = decay
        self.n = 0
        self.mean = 0.0
        self.var = 0.0

    def update(self, x: float) -> None:
        self.n += 1
        if self.n == 1:
            self.mean = x
            return
        d = x - self.mean
        self.mean += (1.0 - self.decay) * d
        self.var = self.decay * (self.var + (1.0 - self.decay) * d * d)

    @property
    def std(self) -> float:
        return math.sqrt(max(self.var, 0.0))


class AnomalyDetector:
    """Rolling divergence detector fed on the learner's stats cadence.

    ``observe(...)`` returns the list of anomaly labels this window
    tripped (empty = healthy) and maintains the consecutive-anomalous-
    window streak; ``should_rollback()`` is true once the streak reaches
    ``threshold``.  Signals:

    - ``nonfinite``        — loss or grad norm is NaN/inf (a guard skip
      that still surfaced, or a guardless run diverging);
    - ``skipped``          — the in-jit guard skipped >= 1 step in the
      window;
    - ``loss_spike``       — loss z-score against its own EWMA above
      ``zmax`` (warmup: the first ``warmup`` windows never trip);
    - ``grad_spike``       — grad norm above ``grad_spike`` x its EWMA;
    - ``td_explosion``     — mean |TD| above ``grad_spike`` x its EWMA;
    - ``priority_collapse``— the PER distribution stopped doing useful
      work: total mass fell to ~0 while the buffer holds rows, or —
      with the ISSUE-8 priority X-ray wired in — the normalized
      effective sample size (``priority_ess`` = ESS / rows) fell under
      ``ess_floor``: mass can look healthy while sampling has
      concentrated onto a handful of rows.
    """

    WARMUP = 8

    def __init__(self, zmax: float = 8.0, grad_spike: float = 100.0,
                 threshold: int = 3, ess_floor: float = 0.02):
        self.zmax = zmax
        self.grad_spike = grad_spike
        self.ess_floor = ess_floor
        self.threshold = max(1, int(threshold))
        self.loss = _Ewma()
        self.grad = _Ewma()
        self.td = _Ewma()
        self.streak = 0
        self.windows = 0
        self.anomalies_total = 0

    def observe(self, loss: Optional[float] = None,
                grad_norm: Optional[float] = None,
                td_mean: Optional[float] = None,
                priority_mass: Optional[float] = None,
                replay_rows: int = 0,
                skipped: float = 0.0,
                priority_ess: Optional[float] = None) -> List[str]:
        self.windows += 1
        out: List[str] = []
        if skipped and skipped > 0:
            out.append("skipped")
        for val, ewma, spike_label in ((loss, self.loss, "loss_spike"),
                                       (grad_norm, self.grad, "grad_spike"),
                                       (td_mean, self.td, "td_explosion")):
            if val is None:
                continue
            if not math.isfinite(val):
                if "nonfinite" not in out:
                    out.append("nonfinite")
                continue  # never fold infinities into the EWMA
            warm = ewma.n >= self.WARMUP
            if warm and spike_label == "loss_spike":
                z = abs(val - ewma.mean) / max(ewma.std, 1e-12)
                if z > self.zmax:
                    out.append(spike_label)
            elif warm and abs(val) > self.grad_spike * max(
                    abs(ewma.mean), 1e-12):
                out.append(spike_label)
            if spike_label not in out:
                # anomalous readings stay OUT of the baseline: a spike
                # that shifted its own EWMA would mask the next one
                ewma.update(val)
        if replay_rows > 0 and (
                (priority_mass is not None and priority_mass <= 1e-12)
                or (priority_ess is not None
                    and priority_ess < self.ess_floor)):
            out.append("priority_collapse")
        self.streak = self.streak + 1 if out else 0
        self.anomalies_total += len(out)
        return out

    def should_rollback(self) -> bool:
        return self.streak >= self.threshold

    def reset(self) -> None:
        """Post-rollback: restart the streak AND the baselines — the
        restored epoch's loss scale may legitimately differ from the
        diverged tail's."""
        self.loss = _Ewma()
        self.grad = _Ewma()
        self.td = _Ewma()
        self.streak = 0


# ---------------------------------------------------------------------------
# ingest validation + quarantine
# ---------------------------------------------------------------------------

def poison_items(items):
    """Deterministically poison a ``[(Transition, priority), ...]`` chunk
    — the ``poison_chunk`` fault verb's payload (utils/faults.py):
    rewards go NaN, priorities go NaN (the garbage a diverged actor
    would compute), and float observations go NaN too (uint8 frames
    cannot hold NaN, so image chunks poison through the scalars).
    Preserves a TracedChunk wrapper so the quarantine file keeps the
    trace id."""
    out = []
    for t, _p in items:
        repl = {"reward": np.asarray(t.reward).dtype.type(np.nan)}
        s0 = np.asarray(t.state0)
        if s0.dtype.kind == "f":
            repl["state0"] = np.full_like(s0, np.nan)
        out.append((t._replace(**repl), float("nan")))
    from pytorch_distributed_tpu.utils import tracing

    if isinstance(items, tracing.TracedChunk):
        return tracing.TracedChunk(out, trace_id=items.trace_id,
                                   born=items.born)
    return out

def _finite_scalar(x) -> bool:
    try:
        return bool(np.isfinite(x))
    except TypeError:
        return False


class ChunkValidator:
    """Per-ingest-boundary transition validator.

    Checks, per ``(Transition, priority)`` item: non-finite
    obs/reward/gamma/terminal (float state arrays scanned; integer
    states — the uint8 Atari rows — cannot hold NaN and skip the array
    scan), non-finite or negative priority, non-finite float actions,
    discrete actions outside ``[0, num_actions)``, and shape/dtype
    drift against the expected schema.  The schema comes from the
    owning memory when it declares one (``state_shape``/``state_dtype``)
    and is otherwise latched from the first item seen — drift mid-run
    is what poisons a fixed-schema ring."""

    def __init__(self, state_shape: Optional[Tuple[int, ...]] = None,
                 state_dtype=None, num_actions: Optional[int] = None):
        self.state_shape = tuple(state_shape) if state_shape else None
        self.state_dtype = np.dtype(state_dtype) if state_dtype else None
        self.num_actions = num_actions
        self.checked = 0
        self.rejected = 0
        # Segment rows carry (T+1, *state_shape) (or frame-packed)
        # observations — latched from the first row, never compared to
        # the memory's PER-STEP state_shape
        self._seg_obs_shape: Optional[Tuple[int, ...]] = None

    @classmethod
    def for_memory(cls, memory) -> "ChunkValidator":
        return cls(state_shape=getattr(memory, "state_shape", None),
                   state_dtype=getattr(memory, "state_dtype", None))

    def _check(self, t, priority) -> Optional[str]:
        if priority is not None and (
                not _finite_scalar(priority) or float(priority) < 0.0):
            return f"invalid priority {priority!r}"
        if not hasattr(t, "state0"):
            # R2D2 Segment row (memory/sequence_replay.py): vector
            # fields per step, no six-column schema.  Until this branch
            # the validator scalar-checked t.reward — a (T,) array —
            # and crashed the learner's first drain on every sequence
            # topology with quarantine active (found driving config 13
            # under the ISSUE-9 verification pass).
            return self._check_segment(t)
        for name in ("reward", "gamma_n", "terminal1"):
            if not _finite_scalar(getattr(t, name)):
                return f"non-finite {name}"
        for name in ("state0", "state1"):
            arr = np.asarray(getattr(t, name))
            if self.state_shape is None:
                self.state_shape = arr.shape
            elif arr.shape != self.state_shape:
                return (f"{name} shape {arr.shape} != "
                        f"expected {self.state_shape}")
            if self.state_dtype is None:
                self.state_dtype = arr.dtype
            elif arr.dtype != self.state_dtype:
                return (f"{name} dtype {arr.dtype} != "
                        f"expected {self.state_dtype}")
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                return f"non-finite {name}"
        a = np.asarray(t.action)
        if a.dtype.kind == "f" and not np.isfinite(a).all():
            return "non-finite action"
        if (self.num_actions is not None and a.dtype.kind in "iu"
                and a.size and not ((a >= 0) & (a < self.num_actions)).all()):
            return f"action out of range [0, {self.num_actions})"
        return None

    def _check_segment(self, t) -> Optional[str]:
        """Segment-row validation: finiteness over the per-step vector
        fields, obs shape/dtype drift latched from the first row (a
        segment's obs is the whole window — (T+1, *state_shape), or the
        frame-packed (T+C, H, W) — so the memory's per-step
        ``state_shape`` must not be compared against it)."""
        for name in ("reward", "terminal", "mask"):
            arr = np.asarray(getattr(t, name, 0.0))
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                return f"non-finite {name}"
        obs = np.asarray(t.obs)
        if self._seg_obs_shape is None:
            self._seg_obs_shape = obs.shape
        elif obs.shape != self._seg_obs_shape:
            return (f"obs shape {obs.shape} != "
                    f"expected {self._seg_obs_shape}")
        if self.state_dtype is None:
            self.state_dtype = obs.dtype
        elif obs.dtype != self.state_dtype:
            return (f"obs dtype {obs.dtype} != "
                    f"expected {self.state_dtype}")
        if obs.dtype.kind == "f" and not np.isfinite(obs).all():
            return "non-finite obs"
        for name in ("c0", "h0"):
            arr = np.asarray(getattr(t, name, 0.0))
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                return f"non-finite {name}"
        a = np.asarray(t.action)
        if a.dtype.kind == "f" and not np.isfinite(a).all():
            return "non-finite action"
        if (self.num_actions is not None and a.dtype.kind in "iu"
                and a.size and not ((a >= 0) & (a < self.num_actions)).all()):
            return f"action out of range [0, {self.num_actions})"
        return None

    def filter(self, items) -> Tuple[list, List[Tuple[Any, Optional[float],
                                                      str]]]:
        """Split ``[(Transition, priority), ...]`` into (clean items,
        rejected ``(transition, priority, reason)`` triples).  The clean
        list preserves the input's TracedChunk identity when nothing was
        rejected (the common case costs no copy of the wrapper)."""
        self.checked += len(items)
        bad: List[Tuple[Any, Optional[float], str]] = []
        good: list = []
        for t, p in items:
            reason = self._check(t, p)
            if reason is None:
                good.append((t, p))
            else:
                bad.append((t, p, reason))
        self.rejected += len(bad)
        if not bad:
            return items, bad
        from pytorch_distributed_tpu.utils import tracing

        if isinstance(items, tracing.TracedChunk):
            good = tracing.TracedChunk(good, trace_id=items.trace_id,
                                       born=items.born)
        return good, bad


class QuarantineStore:
    """One ingest source's quarantine sink: rejected transitions land in
    ``{log_dir}/quarantine/<source>-<n>.npz`` (columns best-effort
    stacked, plus ``reason``/``trace_id`` columns) instead of replay.
    The directory rides the same per-process configuration as the
    flight recorder (``flight_recorder.configure`` / the
    ``TPU_APEX_BLACKBOX_DIR`` spawn-inheritance env), so no new
    plumbing reaches the workers.  Bounded: past ``max_files`` writes
    the store only counts — a poisoning actor must not fill the disk
    before the supervisor reacts."""

    # single-owner declaration (apexlint): quarantine diversion happens
    # at the declared ingest boundaries only — QueueOwner.drain, the
    # device ingest drains, and the DCN gateway's per-slot validator;
    # a caller elsewhere would hide data-loss from those counters
    __apex_mutators__ = ("put",)
    __apex_owner__ = ("memory.", "parallel.dcn", "utils.health")

    def __init__(self, source: str, max_files: int = 64):
        self.source = source
        self.max_files = max_files
        self.count = 0       # transitions quarantined (lifetime)
        self.files = 0       # files actually written
        self.last_path: Optional[str] = None
        self._lock = threading.Lock()

    def _dir(self) -> Optional[str]:
        from pytorch_distributed_tpu.utils import flight_recorder

        base = flight_recorder._dump_dir()
        return os.path.join(base, "quarantine") if base else None

    def put(self, rejected, trace_id: int = 0) -> Optional[str]:
        """Record ``[(transition, priority, reason), ...]``; returns the
        written path (None when no log dir is configured or the file
        budget is spent — counting continues either way)."""
        if not rejected:
            return None
        with self._lock:
            self.count += len(rejected)
            n = self.files
            if n >= self.max_files:
                return None
            self.files += 1
        target = self._dir()
        if not target:
            return None
        from pytorch_distributed_tpu.utils.experience import (
            REPLAY_FIELDS, stack_prov,
        )
        from pytorch_distributed_tpu.utils import flight_recorder
        from pytorch_distributed_tpu.utils.tracing import format_trace_id

        cols: Dict[str, np.ndarray] = {}
        # transition rows dump the six replay columns; Segment rows
        # (sequence topologies) dump their own schema — the validator
        # now rejects segments too, and put() must not assume the
        # six-column shape (it crashed on the first quarantined
        # segment before this branch)
        first = rejected[0][0]
        fields = (REPLAY_FIELDS if hasattr(first, "state0")
                  else tuple(f for f in getattr(first, "_fields", ())
                             if f != "prov"))
        for f in fields:
            vals = [np.asarray(getattr(t, f, np.zeros(0)))
                    for t, _p, _r in rejected]
            try:
                cols[f] = np.stack(vals)
            except ValueError:  # shape-drifted offenders can't stack
                cols[f] = np.array([str(v.shape) + ":" + str(v.dtype)
                                    for v in vals])
        cols["priority"] = np.array(
            [np.nan if p is None else float(p) for _t, p, _r in rejected],
            dtype=np.float64)
        cols["reason"] = np.array([r for _t, _p, r in rejected])
        cols["trace_id"] = np.array([format_trace_id(trace_id)])
        # correlation keys (ISSUE 8 satellite): per-row provenance,
        # capture wall clock and run id — tools/timeline.py joins
        # quarantine files to the incident timeline by these, never by
        # directory layout
        cols["prov"] = stack_prov([(t, p) for t, p, _r in rejected])
        cols["wall"] = np.array([time.time()], dtype=np.float64)
        rid = flight_recorder.run_id()
        if rid:
            cols["run_id"] = np.array([rid])
        safe = "".join(c if (c.isalnum() or c in "-_.") else "_"
                       for c in self.source) or "source"
        path = os.path.join(target, f"{safe}-{n:05d}.npz")
        try:
            os.makedirs(target, exist_ok=True)
            tmp = path + ".tmp.npz"
            np.savez(tmp, **cols)
            os.replace(tmp, path)  # readers never see a torn file
        except OSError:
            return None  # quarantine is best-effort; counting is not
        self.last_path = path
        if n == 0:  # first offender per source is loud; the rest are
            # counters on the health plane (a poisoning actor would
            # otherwise flood the log at chunk rate)
            print(f"[health] quarantined {len(rejected)} transition(s) "
                  f"from {self.source} ({rejected[0][2]}) -> {path}",
                  flush=True)
        return path


# per-process registry, mirroring flight_recorder's: one store per
# source, aggregated counters for the T_STATUS health plane
_q_lock = threading.Lock()
_q_stores: Dict[str, QuarantineStore] = {}


# factory → owning-class mapping for apexlint's receiver resolution:
# ``get_quarantine(...).put(...)`` is a QuarantineStore mutation
__apex_factories__ = {"get_quarantine": "QuarantineStore"}


def get_quarantine(source: str, max_files: int = 64) -> QuarantineStore:
    with _q_lock:
        st = _q_stores.get(source)
        if st is None:
            st = _q_stores[source] = QuarantineStore(source,
                                                     max_files=max_files)
        return st


def quarantine_counts() -> Dict[str, int]:
    """{source: transitions quarantined} across this process — the
    health plane's read (fleet.py _health_snapshot -> T_STATUS ->
    fleet_top)."""
    with _q_lock:
        return {s: st.count for s, st in _q_stores.items() if st.count}


def reset() -> None:
    """Test isolation: drop all quarantine stores."""
    with _q_lock:
        _q_stores.clear()
