"""Metrics writer.

The reference logs scalars through a dedicated logger process into
TensorBoard (tensorboardX SummaryWriter, reference
core/single_processes/dqn_logger.py:15) with the global learner step as the
x-axis for everything.  Here the writer is a small append-only JSONL sink
(always on — machine-readable for bench/CI) plus TensorBoard event files via
``torch.utils.tensorboard`` when available; scalar names match the reference
so existing dashboards carry over (``evaluator/avg_reward``,
``actor/total_nframes``, ``learner/critic_loss``, ... — reference
dqn_logger.py:23-55).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional


class MetricsWriter:
    def __init__(self, log_dir: str, enable_tensorboard: bool = True):
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._jsonl = open(os.path.join(log_dir, "scalars.jsonl"), "a",
                           buffering=1)
        self._tb = None
        if enable_tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=log_dir)
            except Exception:  # noqa: BLE001 - TB is best-effort
                self._tb = None

    def scalar(self, tag: str, value: float, step: int,
               wall: Optional[float] = None) -> None:
        rec = {"tag": tag, "value": float(value), "step": int(step),
               "wall": wall if wall is not None else time.time()}
        self._jsonl.write(json.dumps(rec) + "\n")
        if self._tb is not None:
            # explicit walltime: TB's wall-clock view must show the same
            # capture-true timestamps the JSONL rows carry
            self._tb.add_scalar(tag, float(value), int(step),
                                walltime=rec["wall"])

    def scalars(self, kv: dict, step: int,
                wall: Optional[float] = None) -> None:
        if wall is None:
            wall = time.time()
        for tag, value in kv.items():
            self.scalar(tag, value, step, wall)

    def flush(self) -> None:
        self._jsonl.flush()
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        self.flush()
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()


def read_scalars(log_dir: str):
    """Load all JSONL scalar records from a run dir (tests/bench use this)."""
    path = os.path.join(log_dir, "scalars.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
