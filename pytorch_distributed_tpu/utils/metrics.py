"""Metrics writer.

The reference logs scalars through a dedicated logger process into
TensorBoard (tensorboardX SummaryWriter, reference
core/single_processes/dqn_logger.py:15) with the global learner step as the
x-axis for everything.  Here the writer is a small append-only JSONL sink
(always on — machine-readable for bench/CI) plus TensorBoard event files via
``torch.utils.tensorboard`` when available; scalar names match the reference
so existing dashboards carry over (``evaluator/avg_reward``,
``actor/total_nframes``, ``learner/critic_loss``, ... — reference
dqn_logger.py:23-55).

Three row kinds share ``scalars.jsonl`` (discriminated by ``kind``,
scalars carry none for backward compatibility):

- scalar     — ``{tag, value, step, wall}``
- histogram  — ``{tag, kind: "histogram", count, mean, p50, p95, max,
  step, wall}``: a distribution summarized at the WRITER (utils/tracing.py
  span reservoirs land here); percentiles, not just means, because stalls
  live in the tail.
- span       — ``{tag, kind: "span", span, role, trace_id, value, step,
  wall}``: one sampled distributed-trace event (JSONL only — per-event
  TensorBoard points would drown the dashboards).

Every row is stamped with ``role`` and ``run_id`` when the writer knows
them, so merging the JSONL streams of a multi-role/multi-host run never
relies on directory layout.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Dict, List, Optional, Sequence


def summarize_histogram(values: Sequence[float]) -> Dict[str, float]:
    """count/mean/p50/p95/max of a sample set.  Nearest-rank percentiles
    (no interpolation): deterministic, and an observed-value answer —
    "the p95 enqueue was THIS put" — which is what latency forensics
    wants."""
    vals = sorted(float(v) for v in values)
    n = len(vals)
    if n == 0:
        raise ValueError("summarize_histogram of an empty sample")

    def pct(q: float) -> float:
        return vals[min(n - 1, max(0, math.ceil(q * n) - 1))]

    return {"count": n, "mean": sum(vals) / n,
            "p50": pct(0.50), "p95": pct(0.95), "max": vals[-1]}


class MetricsWriter:
    def __init__(self, log_dir: str, enable_tensorboard: bool = True,
                 role: Optional[str] = None, run_id: Optional[str] = None):
        self.log_dir = log_dir
        self.role = role
        self.run_id = run_id
        os.makedirs(log_dir, exist_ok=True)
        self._jsonl = open(os.path.join(log_dir, "scalars.jsonl"), "a",
                           buffering=1)
        self._tb = None
        if enable_tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=log_dir)
            except Exception:  # noqa: BLE001 - TB is best-effort
                self._tb = None

    def _write(self, rec: dict) -> None:
        # setdefault: a row carrying its own attribution (e.g. a span
        # recorded by the gateway but flushed by the learner's writer)
        # keeps it
        if self.role is not None:
            rec.setdefault("role", self.role)
        if self.run_id is not None:
            rec.setdefault("run_id", self.run_id)
        self._jsonl.write(json.dumps(rec) + "\n")

    def scalar(self, tag: str, value: float, step: int,
               wall: Optional[float] = None) -> None:
        rec = {"tag": tag, "value": float(value), "step": int(step),
               "wall": wall if wall is not None else time.time()}
        self._write(rec)
        if self._tb is not None:
            # explicit walltime: TB's wall-clock view must show the same
            # capture-true timestamps the JSONL rows carry
            self._tb.add_scalar(tag, float(value), int(step),
                                walltime=rec["wall"])

    def scalars(self, kv: dict, step: int,
                wall: Optional[float] = None) -> None:
        if wall is None:
            wall = time.time()
        for tag, value in kv.items():
            self.scalar(tag, value, step, wall)

    def histogram(self, tag: str, values: Sequence[float], step: int,
                  wall: Optional[float] = None,
                  count: Optional[int] = None) -> None:
        """One summarized-distribution row (p50/p95/max, not just the
        mean); mirrored to TensorBoard as ``<tag>/p50|p95|max`` scalars
        so tail latency is a dashboard read.  ``count`` overrides the
        reported event count when ``values`` is a bounded reservoir of a
        larger population (utils/tracing.py Tracer reservoirs)."""
        if not values:
            return
        s = summarize_histogram(values)
        rec = {"tag": tag, "kind": "histogram", "step": int(step),
               "wall": wall if wall is not None else time.time()}
        rec.update(s)
        if count is not None:
            rec["count"] = int(count)
        self._write(rec)
        if self._tb is not None:
            for k in ("p50", "p95", "max"):
                self._tb.add_scalar(f"{tag}/{k}", float(s[k]), int(step),
                                    walltime=rec["wall"])

    def bucket_histogram(self, tag: str, counts, *, log10_lo: float,
                         log10_hi: float, step: int,
                         wall: Optional[float] = None,
                         extra: Optional[dict] = None) -> None:
        """One pre-bucketed distribution row (``kind: "buckets"``) —
        for distributions summarized at the SOURCE (the ISSUE-8
        priority X-ray buckets its leaves in-jit on device so only the
        counts cross to the host; raw values never exist host-side).
        ``counts`` spans the fixed log10 grid [log10_lo, log10_hi);
        ``extra`` scalars (ess, mass, ...) ride the same row.  JSONL
        only — TB gets the companion scalar rows the caller writes."""
        rec = {"tag": tag, "kind": "buckets", "step": int(step),
               "wall": wall if wall is not None else time.time(),
               "counts": [int(c) for c in counts],
               "log10_lo": float(log10_lo), "log10_hi": float(log10_hi)}
        if extra:
            rec.update({k: (float(v) if isinstance(v, (int, float))
                            else v) for k, v in extra.items()})
        self._write(rec)

    def span(self, span: str, role: str, trace_id: str, dur_ms: float,
             step: int = 0, wall: Optional[float] = None) -> None:
        """One sampled distributed-trace event (utils/tracing.py).  JSONL
        only — per-event TB points would drown the dashboards."""
        self._write({"tag": f"trace/{role}/{span}", "kind": "span",
                     "span": span, "role": role, "trace_id": trace_id,
                     "value": float(dur_ms), "step": int(step),
                     "wall": wall if wall is not None else time.time()})

    def flush(self) -> None:
        self._jsonl.flush()
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        self.flush()
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()


class ScalarsTail:
    """Incremental reader of a run dir's ``scalars.jsonl`` for refresh
    loops (tools/fleet_top.py ``--metrics``): remembers the byte offset
    of the last fully-terminated line, so each ``poll()`` costs O(new
    rows) instead of O(run) — a long run's metrics file grows without
    bound and a full ``read_scalars`` per refresh turns the monitor
    itself into the I/O hog.

    Torn-tail handling follows read_scalars' philosophy with one
    refinement the offset makes possible: a trailing line WITHOUT a
    newline is not consumed at all (the writer may still be mid-append
    — next poll re-reads it complete), while a newline-terminated line
    that still fails to decode (a SIGKILL-torn line mid-file) is
    skipped for good.  A file that shrank (rotation, a fresh run
    reusing the dir) resets the cursor to the start.

    ``max_bytes`` bounds one poll's read (the T_METRICS push path,
    utils/telemetry.MetricsPusher): a pusher that fell far behind — or
    attached to an old, huge stream — catches up over several cadences
    instead of encoding the whole backlog into one wire frame.  A
    bounded read that lands mid-line simply resumes from the last
    complete newline next poll; a single line LONGER than the bound
    (impossible for well-formed scalar rows) is dropped rather than
    livelocking the cursor."""

    def __init__(self, log_dir: str, max_bytes: Optional[int] = None):
        self.path = os.path.join(log_dir, "scalars.jsonl")
        self._offset = 0
        self._max_bytes = max_bytes

    def poll(self) -> List[dict]:
        """All rows appended since the previous poll (up to the
        ``max_bytes`` read bound when one is set)."""
        try:
            with open(self.path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size < self._offset:
                    self._offset = 0  # truncated/rotated: start over
                f.seek(self._offset)
                data = (f.read() if self._max_bytes is None
                        else f.read(self._max_bytes))
        except OSError:
            return []
        end = data.rfind(b"\n")
        if end < 0:
            if (self._max_bytes is not None
                    and len(data) >= self._max_bytes):
                # one line wider than the whole read bound: skip it or
                # every future poll re-reads the same undecodable chunk
                self._offset += len(data)
            return []  # only an unterminated tail so far — wait
        self._offset += end + 1
        out = []
        for line in data[:end + 1].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line.decode()))
            except (ValueError, UnicodeDecodeError):
                continue  # torn mid-file line (kill); the rest is good
        return out


def is_scalar_row(rec: dict) -> bool:
    """True for plain scalar rows of the JSONL schema (module
    docstring): a ``tag`` + numeric ``value`` and no distribution
    ``kind``.  The telemetry aggregator (utils/telemetry.py) and the
    T_METRICS push path admit only these — histogram/span/bucket rows
    are already summarized at their writer."""
    return (isinstance(rec, dict) and "tag" in rec
            and isinstance(rec.get("value"), (int, float))
            and rec.get("kind") in (None, "scalar"))


def read_scalars(log_dir: str) -> List[dict]:
    """Load all JSONL records from a run dir (tests/bench/tools use this).
    A SIGKILL mid-write leaves a torn trailing line — skip undecodable
    lines instead of raising, matching the torn-artifact philosophy of
    the checkpoint tier (utils/checkpoint.py: a torn epoch is skipped,
    never fatal)."""
    path = os.path.join(log_dir, "scalars.jsonl")
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn line (kill mid-write); the rest is good
    return out
