"""Exploration noise processes.

Re-design of reference utils/random_process.py (AnnealedGaussianProcess
:10-27, OrnsteinUhlenbeckProcess :32-46).  Differences: explicit
``numpy.random.Generator`` seeding instead of the global numpy RNG (JAX-style
reproducibility across actor processes), otherwise the same stochastic
process and the same linear sigma anneal.
"""

from __future__ import annotations

import numpy as np


class AnnealedGaussianProcess:
    """sigma linearly annealed from ``sigma`` to ``sigma_min`` over
    ``n_steps_annealing`` samples (reference utils/random_process.py:10-27)."""

    def __init__(self, mu: float, sigma: float, sigma_min: float | None,
                 n_steps_annealing: int = 1000):
        self.mu = mu
        self.sigma = sigma
        self.n_steps = 0
        if sigma_min is not None:
            self.m = -(sigma - sigma_min) / float(n_steps_annealing)
            self.c = sigma
            self.sigma_min = sigma_min
        else:
            self.m = 0.0
            self.c = sigma
            self.sigma_min = sigma

    @property
    def current_sigma(self) -> float:
        return max(self.sigma_min, self.m * self.n_steps + self.c)


class OrnsteinUhlenbeckProcess(AnnealedGaussianProcess):
    """dx = theta (mu - x) dt + sigma sqrt(dt) N(0,1)
    (reference utils/random_process.py:32-46)."""

    def __init__(self, size: int = 1, theta: float = 0.15, mu: float = 0.0,
                 sigma: float = 0.3, dt: float = 1.0, x0: float | None = None,
                 sigma_min: float | None = None,
                 n_steps_annealing: int = 1000,
                 seed: int | None = None):
        super().__init__(mu=mu, sigma=sigma, sigma_min=sigma_min,
                         n_steps_annealing=n_steps_annealing)
        self.theta = theta
        self.dt = dt
        self.size = size
        self.x0 = x0 if x0 is not None else 0.0
        self.rng = np.random.default_rng(seed)
        self.reset_states()

    def reset_states(self) -> None:
        self.x_prev = np.full((self.size,), self.x0, dtype=np.float64)

    def sample(self) -> np.ndarray:
        x = (self.x_prev
             + self.theta * (self.mu - self.x_prev) * self.dt
             + self.current_sigma * np.sqrt(self.dt)
             * self.rng.standard_normal(self.size))
        self.x_prev = x
        self.n_steps += 1
        return x
