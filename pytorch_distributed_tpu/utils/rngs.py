"""Deterministic per-process randomness.

The reference seeds ad hoc (torch.manual_seed at reference main.py:16, env
seed ``seed + process_ind * num_envs_per_actor`` at reference
core/envs/atari_env.py:16).  Here every process derives its streams from one
root seed via stable folds, JAX-style.
"""

from __future__ import annotations

import jax
import numpy as np

# Process-role salts so actor 0 and learner 0 never collide.
ROLE_SALTS = {
    "main": 0,
    "actor": 1_000_000,
    "learner": 2_000_000,
    "evaluator": 3_000_000,
    "tester": 4_000_000,
    "logger": 5_000_000,
    "env": 6_000_000,
    # the ISSUE-15 multi-learner plane: ONE shared stream per fleet
    # (index 0 by convention — rank folding differentiates replicas),
    # plus the deterministic shared ingest stream (indexed by a counter
    # every replica advances identically)
    "replica-plane": 7_000_000,
    "replica-ingest": 8_000_000,
}


def process_seed(root_seed: int, role: str, index: int = 0) -> int:
    return (root_seed + ROLE_SALTS[role] + index) % (2 ** 31 - 1)


def process_key(root_seed: int, role: str, index: int = 0) -> jax.Array:
    return jax.random.fold_in(jax.random.PRNGKey(root_seed),
                              ROLE_SALTS[role] + index)


def np_rng(root_seed: int, role: str, index: int = 0) -> np.random.Generator:
    return np.random.default_rng(process_seed(root_seed, role, index))
