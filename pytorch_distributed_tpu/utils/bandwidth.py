"""Byte-exact bandwidth accounting for every wire, ring, and
checkpoint plane (ISSUE 18 tentpole).

Ape-X's defining cost is moving experience: at production actor counts
the DCN wire and replay HBM are the ceilings after compute (ROADMAP
item 4), and INES (PAPERS.md) makes the case that *where bytes flow*
decides distributed-RL scale.  Before this module the repo counted
chunks, rows, and rejects everywhere but **bytes nowhere** — the
compression campaign cannot be built, benched, or gated until
bytes/transition and bytes/round are first-class, live-queryable
series with an exact conservation story.  This module is that plane:

- **LinkAccountant** — a process-wide, lock-guarded table of
  cumulative ``(bytes, frames)`` per ``link x verb x slot x
  direction``, stamped at every transport boundary: ``_send_frame`` /
  ``_recv_frame`` in parallel/dcn.py (chunk ingest, clock acks,
  metrics pushes, replica rounds, journal T_SYNC), the spawn-queue
  mint/drain boundaries (memory/feeder.py, memory/device_replay.py),
  replay occupancy by column dtype, and per-artifact checkpoint-epoch
  sizes (utils/checkpoint.py).  The hot path is counter-only: one
  dict lookup + two integer adds under a lock that is never held
  across I/O (bench.py ``wire_overhead`` gates it under the 0.02
  absolute overhead band, directly timed per the PR-10 lesson).
- **Socket registry** — ``socket.socket`` declares ``__slots__`` so
  transport identity cannot ride the object; a WeakKeyDictionary side
  table maps live sockets to ``(link, slot)`` without pinning them.
- **Headline series** (``emit_scalars`` on the learner stats cadence,
  ``status_block`` on the gateway STATUS path): ``wire/<link>/
  bytes_per_s``, ``wire/bytes_per_transition`` (wire bytes / ingested
  rows — the number frame packing claims 4x on), ``wire/
  replica_bytes_per_round``, ``replay/hbm_bytes``, ``ckpt/
  epoch_bytes`` — flowing MetricsWriter -> FleetMetrics -> T_STATUS
  ``wire`` block -> fleet_top -> OpenMetrics -> timeline counters.
- **Byte conservation ledger** — rides the ISSUE-11 flow ledger
  verbatim: the client counts each experience payload ONCE at encode
  (``acked_bytes``, cumulative, retransmit-idempotent — a retransmit
  resends the same frame, it does not re-encode), the report rides
  every T_TICK, and the gateway legs (``ingested_bytes`` +
  ``rejected_bytes`` + ``shed_bytes``) live in flow.GatewayFlow so
  ``conservation()`` can assert ``acked_bytes <= accounted_bytes``
  live and EXACT equality at drill quiescence.  Frames that die
  mid-wire (corrupt -> decode ConnectionError -> connection dropped)
  are counted by NEITHER side: the client already counted the clean
  encode, the gateway counts only the clean retransmit it finally
  acks.  The gateway byte legs are journaled across failover exactly
  like the row legs (``_ha_ledger`` / ``_seed_records`` in
  parallel/dcn.py).

Knobs live in ``config.BandwidthParams``, env-overridable as
``TPU_APEX_WIRE_<FIELD>`` (bare ``TPU_APEX_WIRE=0`` = ``enabled``) —
the same spawn-inheritance contract the flow/perf/metrics planes use.
ON by default; disabled, every hook is a single module-flag check.

Drilled by ``tools/chaos_soak.py --flood`` (byte ledger exact under
brownout, bytes shed per rung) and ``--gateway-failover`` (journaled
byte carry), benched by ``bench.py`` ``wire`` / ``wire_overhead``,
and covered by tests/test_bandwidth.py.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import weakref
from typing import Any, Dict, Iterable, Optional, Tuple

_ENV_PREFIX = "TPU_APEX_WIRE_"

# Known link names (for reference; the accountant accepts any string):
#   client   — DcnClient RPC socket (per remote actor process)
#   gateway  — DcnGateway accepted conns (slot = actor index, after HELLO)
#   replica  — ReplicaClient lease/round sockets (slot = replica index)
#   sync     — HA standby journal-pull socket (T_SYNC)
#   probe    — sessionless RPCs (fleet_top STATUS polls, health probes)
#   spawn    — spawn-queue mint/drain (verb "mint" / "drain")
#   ckpt     — checkpoint epoch writes (verb = artifact name)


def resolve_bandwidth(bp=None):
    """BandwidthParams + ``TPU_APEX_WIRE_<FIELD>`` env overrides, plus
    the bare ``TPU_APEX_WIRE`` shorthand for ``enabled`` — same
    override-by-env contract as flow/perf/health/metrics resolve.
    Returns a NEW instance; the input is never mutated (Options rides
    spawn pickles)."""
    from pytorch_distributed_tpu.config import BandwidthParams

    if bp is None:
        bp = BandwidthParams()
    changes: Dict[str, Any] = {}
    raw_on = os.environ.get("TPU_APEX_WIRE")
    if raw_on is not None:
        changes["enabled"] = raw_on.strip().lower() not in (
            "0", "false", "off", "no", "")
    for f in dataclasses.fields(bp):
        raw = os.environ.get(_ENV_PREFIX + f.name.upper())
        if raw is None:
            continue
        cur = getattr(bp, f.name)
        if isinstance(cur, bool):
            changes[f.name] = raw.strip().lower() not in (
                "0", "false", "off", "no", "")
        elif isinstance(cur, int) and not isinstance(cur, bool):
            changes[f.name] = int(float(raw))
        elif isinstance(cur, float):
            changes[f.name] = float(raw)
        else:
            changes[f.name] = raw.strip()
    return dataclasses.replace(bp, **changes) if changes else bp


def export_env(bp) -> None:
    """Export a RESOLVED BandwidthParams into the environment so spawn
    children (actor processes stamping their own mint boundaries)
    resolve the same plane as the topology that configured it
    programmatically.  setdefault: an operator's explicit env wins."""
    if not bp.enabled:
        os.environ.setdefault("TPU_APEX_WIRE", "0")
    for f in dataclasses.fields(bp):
        val = getattr(bp, f.name)
        if val != f.default:
            os.environ.setdefault(_ENV_PREFIX + f.name.upper(),
                                  ("1" if val is True else
                                   "0" if val is False else str(val)))


# ---------------------------------------------------------------------------
# verb names — dcn registers its frame-type map at import time so this
# module never imports parallel/dcn (no circular import); unknown
# frame types account under "t<code>" rather than getting lost
# ---------------------------------------------------------------------------

_VERB_NAMES: Dict[int, str] = {}


def register_verbs(mapping: Dict[int, str]) -> None:
    _VERB_NAMES.update({int(k): str(v) for k, v in mapping.items()})


def verb_name(ftype: int) -> str:
    return _VERB_NAMES.get(ftype) or f"t{ftype}"


# ---------------------------------------------------------------------------
# byte sizing helpers — deterministic on both sides of a queue so the
# spawn plane conserves by construction
# ---------------------------------------------------------------------------

def payload_nbytes(obj, _depth: int = 0) -> int:
    """Array-payload bytes of a structured value: sum of ``.nbytes``
    over every array reachable through NamedTuples (Transition,
    ReplayState, PerReplayState), dicts, lists, and tuples — the
    dominant (and compressible) term of any pickled/savez'd frame,
    NOT the envelope: pickling a chunk twice just to weigh it would
    violate the counter-only hot path, and the same rule applied at
    mint and drain conserves exactly."""
    if obj is None or _depth > 4:
        return 0
    nb = getattr(obj, "nbytes", None)
    if nb is not None:
        return int(nb)
    if isinstance(obj, (bytes, bytearray)):
        return len(obj)
    if hasattr(obj, "_fields"):              # NamedTuple
        vals: Iterable[Any] = tuple(obj)
    elif isinstance(obj, dict):
        vals = obj.values()
    elif isinstance(obj, (list, tuple)):
        vals = obj
    else:
        return 0
    total = 0
    for v in vals:
        total += payload_nbytes(v, _depth + 1)
    return total


def chunk_nbytes(items) -> int:
    """Spawn-queue chunk bytes: a chunk is a ``[(Transition,
    priority), ...]`` list (possibly a TracedChunk)."""
    return payload_nbytes(items)


def replay_nbytes(state) -> int:
    """HBM/host occupancy of a replay state (ReplayState /
    PerReplayState NamedTuples, dicts of arrays, sidecar lists)."""
    return payload_nbytes(state)


# ---------------------------------------------------------------------------
# the accountant
# ---------------------------------------------------------------------------

class LinkAccountant:
    """Process-wide cumulative ``(bytes, frames)`` per ``link x verb x
    slot x direction``.  Counter-only hot path: ``note`` is one dict
    get + two int adds under a lock never held across I/O."""

    def __init__(self, params=None) -> None:
        self.params = params if params is not None else resolve_bandwidth()
        self._lock = threading.Lock()
        # (link, verb, slot, dir) -> [bytes, frames]
        self._counts: Dict[Tuple[str, str, Optional[int], str],
                           list] = {}
        # live socket -> (link, slot); socket.socket has __slots__, so
        # identity rides a weak side table, never the object
        self._socks: "weakref.WeakKeyDictionary[Any, Tuple[str, Optional[int]]]" \
            = weakref.WeakKeyDictionary()
        self._gauges: Dict[str, float] = {}
        self.transitions = 0       # rows ingested by the gateway
        self.rounds = 0            # replica rounds completed
        # rate state for emit_scalars: link -> (mono, cum_bytes)
        self._rate: Dict[str, Tuple[float, int]] = {}

    # -- socket identity ----------------------------------------------------

    def register_socket(self, sock, link: str,
                        slot: Optional[int] = None) -> None:
        """Tag a live socket with its link name (and slot once known —
        the gateway re-registers an accepted conn when HELLO reveals
        the actor index).  Weak: no socket is ever pinned."""
        try:
            with self._lock:
                self._socks[sock] = (link, slot)
        except TypeError:  # unweakrefable test double — account as anon
            pass

    def link_of(self, sock) -> Tuple[str, Optional[int]]:
        try:
            return self._socks.get(sock) or ("anon", None)
        except TypeError:
            return ("anon", None)

    # -- the hot path -------------------------------------------------------

    def note(self, link: str, verb: str, nbytes: int, direction: str,
             slot: Optional[int] = None, frames: int = 1) -> None:
        key = (link, verb, slot, direction)
        with self._lock:
            c = self._counts.get(key)
            if c is None:
                c = self._counts[key] = [0, 0]
            c[0] += int(nbytes)
            c[1] += int(frames)

    def note_frame(self, sock, ftype: int, nbytes: int,
                   direction: str) -> None:
        link, slot = self.link_of(sock)
        self.note(link, verb_name(ftype), nbytes, direction, slot=slot)

    def note_transitions(self, rows: int) -> None:
        with self._lock:
            self.transitions += int(rows)

    def note_round(self) -> None:
        with self._lock:
            self.rounds += 1

    def set_gauge(self, tag: str, value: float) -> None:
        with self._lock:
            self._gauges[str(tag)] = float(value)

    # -- queries ------------------------------------------------------------

    def totals(self, link: Optional[str] = None,
               verb: Optional[str] = None,
               direction: Optional[str] = None) -> Tuple[int, int]:
        """Cumulative ``(bytes, frames)`` over every key matching the
        given filters (None = any)."""
        b = f = 0
        with self._lock:
            for (lk, vb, _slot, dr), (cb, cf) in self._counts.items():
                if link is not None and lk != link:
                    continue
                if verb is not None and vb != verb:
                    continue
                if direction is not None and dr != direction:
                    continue
                b += cb
                f += cf
        return b, f

    def snapshot(self) -> Dict[str, Any]:
        """Full counter table, JSON-shaped: ``{link: {verb: {dir:
        [bytes, frames]}}}`` (slots folded — per-slot detail stays
        queryable via totals/status for the drills that need it)."""
        out: Dict[str, Any] = {}
        with self._lock:
            items = list(self._counts.items())
        for (lk, vb, _slot, dr), (cb, cf) in items:
            d = out.setdefault(lk, {}).setdefault(vb, {})
            cur = d.get(dr)
            if cur is None:
                d[dr] = [cb, cf]
            else:
                cur[0] += cb
                cur[1] += cf
        return out

    def bytes_per_transition(self) -> float:
        """Wire bytes per ingested row: experience-verb bytes RECEIVED
        on the gateway link / rows the gateway ingested.  rx-side only
        so a loopback topology (client and gateway in one process, as
        every test runs) never double-counts."""
        with self._lock:
            rows = self.transitions
        if rows <= 0:
            return 0.0
        nb, _ = self.totals(link="gateway", verb="exp", direction="rx")
        return nb / rows

    def replica_bytes_per_round(self) -> float:
        """Replica-plane bytes (lease + round + prio verbs, both
        directions, gateway side) per completed round."""
        with self._lock:
            rounds = self.rounds
        if rounds <= 0:
            return 0.0
        nb = 0
        for verb in ("rlease", "rgrad", "rprio"):
            b, _ = self.totals(link="gateway", verb=verb)
            nb += b
        return nb / rounds

    # -- export -------------------------------------------------------------

    def emit_scalars(self, now: Optional[float] = None) -> Dict[str, float]:
        """The headline series, shaped for ``MetricsWriter.scalars``.
        Rates come from deltas against the previous emit (first call
        primes the baseline and emits totals-only)."""
        now = time.monotonic() if now is None else now
        out: Dict[str, float] = {}
        per_link: Dict[str, int] = {}
        with self._lock:
            for (lk, _vb, _slot, _dr), (cb, _cf) in self._counts.items():
                per_link[lk] = per_link.get(lk, 0) + cb
            gauges = dict(self._gauges)
        for lk, cum in per_link.items():
            prev = self._rate.get(lk)
            self._rate[lk] = (now, cum)
            if prev is not None:
                dt = now - prev[0]
                if dt >= max(1e-3, float(self.params.rate_floor_s)):
                    out[f"wire/{lk}/bytes_per_s"] = (cum - prev[1]) / dt
        bpt = self.bytes_per_transition()
        if bpt > 0:
            out["wire/bytes_per_transition"] = bpt
        bpr = self.replica_bytes_per_round()
        if bpr > 0:
            out["wire/replica_bytes_per_round"] = bpr
        out.update(gauges)          # replay/hbm_bytes, ckpt/epoch_bytes
        return out

    def status_block(self) -> Dict[str, Any]:
        """The T_STATUS ``wire`` block (fleet_top's panel source):
        per-link cumulative totals + the headline ratios + gauges.
        The byte-conservation verdict rides the ``flow`` block's
        ``conservation`` (flow.GatewayFlow owns the gateway byte
        legs); fleet_top joins the two."""
        per_link: Dict[str, Dict[str, int]] = {}
        with self._lock:
            items = list(self._counts.items())
            transitions = self.transitions
            rounds = self.rounds
            gauges = dict(self._gauges)
        for (lk, _vb, _slot, dr), (cb, cf) in items:
            d = per_link.setdefault(lk, {"bytes": 0, "frames": 0,
                                         "tx_bytes": 0, "rx_bytes": 0})
            d["bytes"] += cb
            d["frames"] += cf
            d["tx_bytes" if dr == "tx" else "rx_bytes"] += cb
        return {
            "links": per_link,
            "transitions": transitions,
            "rounds": rounds,
            "bytes_per_transition": round(self.bytes_per_transition(), 2),
            "replica_bytes_per_round": round(
                self.replica_bytes_per_round(), 2),
            "gauges": gauges,
        }


# ---------------------------------------------------------------------------
# the process-wide plane (spawn-safe: each process resolves its own)
# ---------------------------------------------------------------------------

_acct_lock = threading.Lock()
_ACCT: Optional[LinkAccountant] = None
_RESOLVED = False
_ENABLED = True     # module-level fast flag: the only cost when off


def get_accountant() -> Optional[LinkAccountant]:
    """The process accountant, or None when the plane is disabled
    (``TPU_APEX_WIRE=0``).  Lazily resolved once per process."""
    global _ACCT, _RESOLVED, _ENABLED
    if _RESOLVED:
        return _ACCT
    with _acct_lock:
        if not _RESOLVED:
            params = resolve_bandwidth()
            _ENABLED = bool(params.enabled)
            _ACCT = LinkAccountant(params) if params.enabled else None
            _RESOLVED = True
    return _ACCT


def enabled() -> bool:
    if not _RESOLVED:
        get_accountant()
    return _ENABLED


def reset_for_tests() -> None:
    """Drop the process accountant so the next hook re-resolves from
    the (possibly monkeypatched) environment.  Tests/bench only."""
    global _ACCT, _RESOLVED, _ENABLED
    with _acct_lock:
        _ACCT = None
        _RESOLVED = False
        _ENABLED = True


# -- module-level hooks: what the transports actually call (each is a
#    flag check + delegate, so instrumented code never branches on
#    plane state itself) -----------------------------------------------------

def register_socket(sock, link: str, slot: Optional[int] = None) -> None:
    acct = get_accountant()
    if acct is not None:
        acct.register_socket(sock, link, slot)


def note_frame(sock, ftype: int, nbytes: int, direction: str) -> None:
    acct = get_accountant()
    if acct is not None:
        acct.note_frame(sock, ftype, nbytes, direction)


def note(link: str, verb: str, nbytes: int, direction: str,
         slot: Optional[int] = None, frames: int = 1) -> None:
    acct = get_accountant()
    if acct is not None:
        acct.note(link, verb, nbytes, direction, slot=slot, frames=frames)


def note_spawn(verb: str, items, frames: int = 1) -> None:
    """Spawn-queue boundary accounting (QueueFeeder mint / QueueOwner +
    DeviceReplayIngest drain): array-payload bytes of the chunk, gated
    on ``BandwidthParams.spawn`` (sizing is linear in rows — flush
    cadence, never per-frame)."""
    acct = get_accountant()
    if acct is not None and acct.params.spawn and frames > 0:
        acct.note("spawn", verb, chunk_nbytes(items),
                  "tx" if verb == "mint" else "rx", frames=frames)


_REPLAY_COLUMNS = ("state0", "action", "reward", "gamma_n", "state1",
                   "terminal1", "prov")


def note_device_replay(*states) -> None:
    """Gauge the attached HBM ring(s): ``replay/hbm_bytes`` total plus
    per-column ``replay/hbm_bytes/<field>`` occupancy by dtype.  One
    shot at attach — ring geometry is fixed for the run."""
    acct = get_accountant()
    if acct is None:
        return
    total = 0
    fields: Dict[str, int] = {}
    for st in states:
        if st is None:
            continue
        if hasattr(st, "_fields"):
            for name, v in zip(st._fields, tuple(st)):
                nb = payload_nbytes(v)
                fields[name] = fields.get(name, 0) + nb
                total += nb
        else:
            total += payload_nbytes(st)
    acct.set_gauge("replay/hbm_bytes", float(total))
    for name, nb in fields.items():
        acct.set_gauge(f"replay/hbm_bytes/{name}", float(nb))


def note_host_replay(mem) -> None:
    """Gauge a host-side replay's column arrays (+ the ISSUE-8 prov
    sidecar): ``replay/host_bytes`` total plus per-column detail.  One
    shot at construction — host columns are preallocated."""
    acct = get_accountant()
    if acct is None:
        return
    total = 0
    for name in _REPLAY_COLUMNS:
        nb = payload_nbytes(getattr(mem, name, None))
        if nb:
            acct.set_gauge(f"replay/host_bytes/{name}", float(nb))
            total += nb
    acct.set_gauge("replay/host_bytes", float(total))


def note_transitions(rows: int) -> None:
    acct = get_accountant()
    if acct is not None:
        acct.note_transitions(rows)


def note_round() -> None:
    acct = get_accountant()
    if acct is not None:
        acct.note_round()


def set_gauge(tag: str, value: float) -> None:
    acct = get_accountant()
    if acct is not None:
        acct.set_gauge(tag, value)


def emit_scalars() -> Dict[str, float]:
    acct = get_accountant()
    return acct.emit_scalars() if acct is not None else {}


def status_block() -> Optional[Dict[str, Any]]:
    acct = get_accountant()
    return acct.status_block() if acct is not None else None
