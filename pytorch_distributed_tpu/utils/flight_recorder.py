"""Flight recorder: a bounded in-memory ring of recent structured events
per role, dumped to ``{log_dir}/blackbox/<role>.jsonl`` when something
dies.

After PR 1 (DCN reconnect/fencing) and PR 2 (crash-consistent epochs) the
fleet survives faults it could not *explain*: when a chaos drill kills a
slot or a checkpoint heals a torn epoch, the only evidence was grepping
stdout.  This is the post-mortem layer: every role appends its last N
structured events (session transitions, fault injections, supervisor
decisions, span traffic) to a ring that costs one lock + deque append,
and the ring is written out as JSONL — newest state wins, one file per
role — on the paths where a run ends abnormally:

- **crash** — runtime._child_main wraps every spawned worker; an escaping
  exception dumps before re-raising, so the supervisor's restart does not
  erase the evidence.
- **SIGTERM preemption** — runtime.py's preemption watcher and fleet.py's
  actor-host handler dump before draining.
- **DcnDisconnected** — parallel/dcn.py DcnClient dumps when it latches a
  terminal session loss (the actor is about to exit EXIT_DISCONNECTED).
- **injected faults** — utils/faults.py records every fired event and
  dumps on the fatal ones; ``kill@N`` dumps *before* the SIGKILL, which
  is the only reason a SIGKILL drill leaves an artifact at all (nothing
  can run after the signal).

The dump directory is set once per process via ``configure(log_dir)``;
the orchestrator also exports ``TPU_APEX_BLACKBOX_DIR`` so spawn children
inherit it without plumbing (the same trick utils/faults.py uses for
fault schedules).  Unconfigured processes never write anything — library
users don't get surprise ``blackbox/`` litter.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional

_ENV_DIR = "TPU_APEX_BLACKBOX_DIR"
_ENV_RUN = "TPU_APEX_RUN_ID"

DEFAULT_CAPACITY = 512


class FlightRecorder:
    """One role's bounded event ring.  ``record`` is the hot-path call:
    one lock + deque append (the deque's maxlen discards the oldest)."""

    def __init__(self, role: str, capacity: int = DEFAULT_CAPACITY):
        self.role = role
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self.recorded = 0  # lifetime count (ring only keeps the tail)

    def record(self, kind: str, **fields) -> None:
        evt = {"t": time.time(), "kind": kind}
        evt.update(fields)
        with self._lock:
            self._ring.append(evt)
            self.recorded += 1

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def dump(self, log_dir: Optional[str] = None,
             reason: str = "") -> Optional[str]:
        """Write the ring to ``{log_dir}/blackbox/{role}.jsonl``; returns
        the path, or None when no dump dir is known.  Truncate-write: a
        later dump supersedes an earlier one — the post-mortem wants the
        final state, and each file is one role's whole story."""
        target = log_dir or _dump_dir()
        if not target:
            return None
        events = self.snapshot()
        blackbox = os.path.join(target, "blackbox")
        path = os.path.join(blackbox, f"{_safe_name(self.role)}.jsonl")
        try:
            os.makedirs(blackbox, exist_ok=True)
            with open(path, "w") as f:
                f.write(json.dumps({
                    "t": time.time(), "kind": "dump", "role": self.role,
                    "reason": reason, "pid": os.getpid(),
                    # run attribution (ISSUE 8): timeline correlation
                    # must not depend on directory layout
                    "run_id": run_id(),
                    "events": len(events),
                    "recorded_total": self.recorded,
                }) + "\n")
                for evt in events:
                    f.write(json.dumps(evt) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            # dumping is last-rites best-effort: a full disk must not
            # turn a clean SIGTERM drain into a crash
            return None
        return path


# ---------------------------------------------------------------------------
# per-process registry + dump plumbing
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_recorders: Dict[str, FlightRecorder] = {}
_configured_dir: Optional[str] = None


def _safe_name(role: str) -> str:
    return "".join(c if (c.isalnum() or c in "-_.") else "_"
                   for c in role) or "role"


def _dump_dir() -> Optional[str]:
    return _configured_dir or os.environ.get(_ENV_DIR) or None


_configured_run_id: Optional[str] = None


def run_id() -> Optional[str]:
    """This process's run id (configure(), else the spawn-inherited
    ``TPU_APEX_RUN_ID``) — stamped into blackbox dump headers and
    quarantine files so tools/timeline.py correlates artifacts by id,
    not directory layout."""
    return _configured_run_id or os.environ.get(_ENV_RUN) or None


def configure(log_dir: str, export_env: bool = False,
              run_id: Optional[str] = None) -> None:
    """Set this process's dump directory (and optionally the run id).
    ``export_env=True`` also exports both so spawn children inherit
    (orchestrators only — a child must not clobber what its parent
    exported)."""
    global _configured_dir, _configured_run_id
    _configured_dir = log_dir
    if run_id:
        _configured_run_id = str(run_id)
    if export_env:
        os.environ[_ENV_DIR] = log_dir
        if run_id:
            os.environ[_ENV_RUN] = str(run_id)


def get_recorder(role: str,
                 capacity: int = DEFAULT_CAPACITY) -> FlightRecorder:
    with _lock:
        rec = _recorders.get(role)
        if rec is None:
            rec = _recorders[role] = FlightRecorder(role, capacity)
        return rec


def dump_all(reason: str = "",
             log_dir: Optional[str] = None) -> List[str]:
    """Dump every recorder this process holds; returns written paths.
    Safe on any path — including signal-adjacent ones — because it only
    appends files under an existing log dir and swallows I/O errors."""
    with _lock:
        recs = list(_recorders.values())
    paths = []
    for rec in recs:
        p = rec.dump(log_dir=log_dir, reason=reason)
        if p:
            paths.append(p)
    return paths


def reset() -> None:
    """Drop all recorders and the configured dir (test isolation)."""
    global _configured_dir, _configured_run_id
    with _lock:
        _recorders.clear()
    _configured_dir = None
    _configured_run_id = None
