"""Transition/experience schema.

Equivalent of the reference ``Experience`` namedtuple
(reference utils/helpers.py:8-16), extended with the per-sample effective
discount ``gamma_n`` that the reference threads separately through its
shared-memory arrays (reference core/memories/shared_memory.py:27,
core/single_processes/dqn_actor.py:118-122): an n-step transition is

    (s_t, a_t, R_t, gamma_n, s_{t+m}, terminal_{t+m})

with ``R_t = sum_{k<m} gamma^k r_{t+k}`` and ``gamma_n = gamma^m`` where
``m <= nstep`` shrinks near episode ends.  The learner target is then
``R_t + gamma_n * bootstrap(s_{t+m}) * (1 - terminal)`` (reference
dqn_learner.py:73-74).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np


class Experience(NamedTuple):
    """One env interaction as seen by the env wrapper
    (reference utils/helpers.py:8: state0, action, reward, state1, terminal1).
    """

    state0: Optional[np.ndarray]
    action: Optional[np.ndarray]
    reward: Optional[float]
    state1: Optional[np.ndarray]
    terminal1: Optional[bool]


def reset_experience() -> Experience:
    # reference utils/helpers.py:10-16
    return Experience(None, None, None, None, None)


class Transition(NamedTuple):
    """One n-step replay row — the six-array schema of the reference's
    shared memory (reference core/memories/shared_memory.py:19-28), plus
    an OPTIONAL provenance sidecar (ISSUE 8): ``prov`` is a ``(4,)``
    int64 vector ``(actor_id, env_slot, param_version, birth_step)``
    minted at action time, or None for legacy/synthetic rows.  Replay
    backends that keep provenance store it in sidecar arrays/columns
    (never inside the six-array schema), so every pre-existing consumer
    of the replay fields — wire codecs, checkpoints, jitted feeds —
    keeps its shape contract; iterate ``REPLAY_FIELDS``, not
    ``Transition._fields``, when you mean the six replay columns."""

    state0: np.ndarray     # (*state_shape,) uint8 or float32
    action: np.ndarray     # () int32 for discrete, (action_dim,) f32 for continuous
    reward: np.ndarray     # () float32 — discounted n-step reward sum
    gamma_n: np.ndarray    # () float32 — gamma**m effective bootstrap discount
    state1: np.ndarray     # (*state_shape,)
    terminal1: np.ndarray  # () float32 in {0,1}
    prov: Optional[np.ndarray] = None  # (4,) int64 provenance, or None


# the six replay columns proper — what every storage/wire schema means by
# "the transition fields" (Transition._fields now also carries ``prov``)
REPLAY_FIELDS = ("state0", "action", "reward", "gamma_n", "state1",
                 "terminal1")

# provenance vector layout (utils/experience.make_prov): who acted, from
# which env slot, under which published param version, and the global
# learner step the actor observed at action time (so sample age is a
# learner-step subtraction with no clock translation)
PROV_FIELDS = ("actor_id", "env_slot", "param_version", "birth_step")
PROV_DTYPE = np.int64
PROV_NONE = np.full(len(PROV_FIELDS), -1, dtype=PROV_DTYPE)


def make_prov(actor_id: int, env_slot: int, param_version: int,
              birth_step: int) -> np.ndarray:
    """One provenance vector, minted at action time."""
    return np.array([actor_id, env_slot, param_version, birth_step],
                    dtype=PROV_DTYPE)


def stack_prov(items) -> np.ndarray:
    """Stack the provenance of ``[(Transition, priority), ...]`` (or any
    iterable of objects with a ``prov`` attribute — bare Transitions
    included) into an ``(n, 4)`` int64 column; rows without provenance
    become ``(-1, -1, -1, -1)`` (the explicit "unknown" sentinel every
    consumer masks on)."""
    rows = []
    for it in items:
        # Transition IS a tuple (NamedTuple): only unwrap PLAIN
        # (item, priority) pairs, or it[0] would be the state array and
        # every stamped row would silently read as the -1 sentinel
        t = (it[0] if isinstance(it, tuple)
             and not hasattr(it, "_fields") else it)
        p = getattr(t, "prov", None)
        rows.append(PROV_NONE if p is None
                    else np.asarray(p, dtype=PROV_DTYPE))
    return (np.stack(rows) if rows
            else np.zeros((0, len(PROV_FIELDS)), dtype=PROV_DTYPE))


def transition_dtypes(state_dtype, action_dtype) -> dict:
    """Per-field storage dtypes of the six-array transition schema, shared
    by every replay backend."""
    return dict(state0=state_dtype, action=action_dtype,
                reward=np.float32, gamma_n=np.float32,
                state1=state_dtype, terminal1=np.float32)


class Batch(NamedTuple):
    """A sampled minibatch (leading batch dim on every field), as handed to
    the jitted learner update."""

    state0: np.ndarray
    action: np.ndarray
    reward: np.ndarray
    gamma_n: np.ndarray
    state1: np.ndarray
    terminal1: np.ndarray
    # PER extras; all-ones / arange for uniform replay.
    weight: np.ndarray     # importance-sampling weights
    index: np.ndarray      # buffer slots, for priority write-back
