"""Transition/experience schema.

Equivalent of the reference ``Experience`` namedtuple
(reference utils/helpers.py:8-16), extended with the per-sample effective
discount ``gamma_n`` that the reference threads separately through its
shared-memory arrays (reference core/memories/shared_memory.py:27,
core/single_processes/dqn_actor.py:118-122): an n-step transition is

    (s_t, a_t, R_t, gamma_n, s_{t+m}, terminal_{t+m})

with ``R_t = sum_{k<m} gamma^k r_{t+k}`` and ``gamma_n = gamma^m`` where
``m <= nstep`` shrinks near episode ends.  The learner target is then
``R_t + gamma_n * bootstrap(s_{t+m}) * (1 - terminal)`` (reference
dqn_learner.py:73-74).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np


class Experience(NamedTuple):
    """One env interaction as seen by the env wrapper
    (reference utils/helpers.py:8: state0, action, reward, state1, terminal1).
    """

    state0: Optional[np.ndarray]
    action: Optional[np.ndarray]
    reward: Optional[float]
    state1: Optional[np.ndarray]
    terminal1: Optional[bool]


def reset_experience() -> Experience:
    # reference utils/helpers.py:10-16
    return Experience(None, None, None, None, None)


class Transition(NamedTuple):
    """One n-step replay row — the six-array schema of the reference's
    shared memory (reference core/memories/shared_memory.py:19-28)."""

    state0: np.ndarray     # (*state_shape,) uint8 or float32
    action: np.ndarray     # () int32 for discrete, (action_dim,) f32 for continuous
    reward: np.ndarray     # () float32 — discounted n-step reward sum
    gamma_n: np.ndarray    # () float32 — gamma**m effective bootstrap discount
    state1: np.ndarray     # (*state_shape,)
    terminal1: np.ndarray  # () float32 in {0,1}


def transition_dtypes(state_dtype, action_dtype) -> dict:
    """Per-field storage dtypes of the six-array transition schema, shared
    by every replay backend."""
    return dict(state0=state_dtype, action=action_dtype,
                reward=np.float32, gamma_n=np.float32,
                state1=state_dtype, terminal1=np.float32)


class Batch(NamedTuple):
    """A sampled minibatch (leading batch dim on every field), as handed to
    the jitted learner update."""

    state0: np.ndarray
    action: np.ndarray
    reward: np.ndarray
    gamma_n: np.ndarray
    state1: np.ndarray
    terminal1: np.ndarray
    # PER extras; all-ones / arange for uniform replay.
    weight: np.ndarray     # importance-sampling weights
    index: np.ndarray      # buffer slots, for priority write-back
