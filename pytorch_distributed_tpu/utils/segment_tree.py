"""Segment trees for prioritized experience replay.

The reference ships a dead, import-crashing sum-tree sketch
(reference utils/segment_tree.py — top-level usage code above the class,
never imported; PER is a TODO at reference utils/options.py:82).  This module
is the finished version: a flat-array binary sum tree with vectorized batch
operations (set/sample-many at once, numpy), plus a min tree for computing
max importance-sampling weights.  The device-side (JAX) prioritized sampler
for the HBM-resident replay lives in ``ops/pallas_sampling.py`` (used by
``memory/device_per.py``).
"""

from __future__ import annotations

import numpy as np


class SumTree:
    """Fixed-capacity binary sum tree over ``capacity`` leaf priorities.

    Layout: ``tree[1]`` is the root; leaves occupy
    ``tree[capacity : 2*capacity]`` (capacity rounded up to a power of two),
    so parent/child index math is pure bit shifts and batch updates
    vectorize.
    """

    def __init__(self, capacity: int):
        assert capacity > 0
        self.capacity = capacity
        self._size = 1
        while self._size < capacity:
            self._size *= 2
        self.tree = np.zeros(2 * self._size, dtype=np.float64)

    # -- updates ------------------------------------------------------------

    def set(self, indices: np.ndarray, priorities: np.ndarray) -> None:
        """Set leaf priorities at ``indices`` (vectorized, duplicates allowed
        — last write wins per numpy fancy-assignment semantics, then the
        whole affected path set is re-aggregated)."""
        indices = np.asarray(indices, dtype=np.int64)
        priorities = np.asarray(priorities, dtype=np.float64)
        if indices.ndim == 0:
            indices = indices[None]
            priorities = priorities[None]
        if indices.size == 0:
            return
        assert np.all((indices >= 0) & (indices < self.capacity))
        assert np.all(priorities >= 0)
        nodes = indices + self._size
        self.tree[nodes] = priorities
        # Walk all touched paths up level by level, recomputing from children
        # (duplicate-safe: recompute instead of add-delta).
        nodes = np.unique(nodes) >> 1
        while nodes[0] >= 1:
            self.tree[nodes] = self.tree[2 * nodes] + self.tree[2 * nodes + 1]
            nodes = np.unique(nodes >> 1)
            if nodes[-1] < 1:
                break

    # -- queries ------------------------------------------------------------

    @property
    def total(self) -> float:
        """Root sum (reference utils/segment_tree.py:68 ``total_sum``)."""
        return float(self.tree[1])

    def get(self, indices: np.ndarray) -> np.ndarray:
        indices = np.asarray(indices, dtype=np.int64)
        return self.tree[indices + self._size]

    def find(self, values: np.ndarray) -> np.ndarray:
        """Batch prefix-sum descent: for each ``v in [0, total)`` return the
        leaf index i such that cumsum(priorities)[i-1] <= v <
        cumsum(priorities)[i] (the reference's recursive ``_retrieve``,
        utils/segment_tree.py:50-63, vectorized and iterative)."""
        values = np.asarray(values, dtype=np.float64).copy()
        if values.ndim == 0:
            values = values[None]
        if values.size == 0:
            return values.astype(np.int64)
        nodes = np.ones_like(values, dtype=np.int64)
        while nodes[0] < self._size:  # all nodes are on the same level
            left = 2 * nodes
            left_sum = self.tree[left]
            go_right = values >= left_sum
            values = np.where(go_right, values - left_sum, values)
            nodes = np.where(go_right, left + 1, left)
        leaf = nodes - self._size
        # Guard the v == total edge and zero-priority tail slots.
        return np.minimum(leaf, self.capacity - 1)

    def sample(self, batch_size: int, rng: np.random.Generator,
               stratified: bool = True) -> np.ndarray:
        """Draw ``batch_size`` leaf indices with probability proportional to
        priority.  Stratified sampling (one uniform draw per equal-mass
        stratum) matches the Ape-X/Rainbow samplers and lowers variance."""
        total = self.total
        assert total > 0, "cannot sample from an empty sum tree"
        if stratified:
            bounds = np.linspace(0.0, total, batch_size + 1)
            values = rng.uniform(bounds[:-1], bounds[1:])
        else:
            values = rng.uniform(0.0, total, size=batch_size)
        return self.find(values)


class MinTree:
    """Fixed-capacity min tree — tracks the minimum priority for the max
    importance-sampling weight normalisation in PER."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._size = 1
        while self._size < capacity:
            self._size *= 2
        self.tree = np.full(2 * self._size, np.inf, dtype=np.float64)

    def set(self, indices: np.ndarray, priorities: np.ndarray) -> None:
        indices = np.asarray(indices, dtype=np.int64)
        priorities = np.asarray(priorities, dtype=np.float64)
        if indices.ndim == 0:
            indices = indices[None]
            priorities = priorities[None]
        if indices.size == 0:
            return
        assert np.all((indices >= 0) & (indices < self.capacity))
        nodes = indices + self._size
        self.tree[nodes] = priorities
        nodes = np.unique(nodes) >> 1
        while nodes[0] >= 1:
            self.tree[nodes] = np.minimum(self.tree[2 * nodes],
                                          self.tree[2 * nodes + 1])
            nodes = np.unique(nodes >> 1)
            if nodes[-1] < 1:
                break

    @property
    def min(self) -> float:
        return float(self.tree[1])
