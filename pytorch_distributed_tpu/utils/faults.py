"""Deterministic fault injection for the DCN session layer.

The fault-tolerance claims of the cross-host plane (parallel/dcn.py:
transparent reconnect, slot fencing, heartbeat liveness) are only worth
what the failure drills behind them prove.  This module is that drill
harness: a seed-driven injector that the DCN endpoints consult once per
frame operation, so a test (tests/test_chaos.py) or a soak run
(tools/chaos_soak.py) can sever, delay, blackhole, or corrupt the wire —
or kill a process outright — at an exactly reproducible point in the
frame stream.

Frame indices are endpoint-local: a ``DcnClient`` counts the frames it
*sends* (HELLO is frame 0), a ``DcnGateway`` counts the frames it
*receives* across all connections.  Scripted specs name those indices
directly; the random mode draws the schedule from a seeded Generator so
a soak failure replays from its seed alone.

Actions (``action@frame`` or ``action@frame:arg``):

- ``sever@N``          — raise ``InjectedDisconnect`` at frame N (the
  connection "dies"; the client's reconnect path must recover).
- ``delay@N:S``        — sleep S seconds before frame N (slow network /
  GC pause; must NOT trip any liveness deadline shorter than S).
- ``blackhole@N:S``    — partition: stall S seconds, then sever.  Models
  partition-then-heal — the reconnect after the sever lands on a healed
  network.
- ``corrupt@N``        — flip a byte of frame N's payload (wire
  corruption; the peer must reject the frame and drop the connection,
  never decode garbage into the replay plane).
- ``crash@N``          — raise ``InjectedCrash`` at frame N.  Uncaught by
  design: an actor process dies nonzero (its RestartBudget engages), a
  gateway serve thread dies and frees its slot.
- ``kill@N``           — SIGKILL the whole process at frame N.  Nothing
  can catch or clean up after it — exactly a host OOM-kill or TPU
  preemption hard-stop.  The checkpoint kill-resume drills
  (utils/checkpoint.py save_epoch write points, ``CKPT_FAULTS`` env)
  use it to die MID-write and prove the epoch commit protocol.

Health-sentinel verbs (tests/test_health.py drills the ladder):

- ``poison_chunk@N``   — data-plane: the actor-side feeder
  (memory/feeder.py, ``FEEDER_FAULTS``, one frame per flush) poisons
  flush N's chunk — NaN rewards, garbage priority, NaN obs when the
  state dtype is float — which the ingest quarantine must catch.
- ``poison_grad@N``    — data-plane: the learner (agents/learner.py,
  ``LEARNER_FAULTS``, one frame per update step) injects a non-finite
  loss into update N by NaN-ing the sampled batch's rewards — the
  in-jit finite guard must skip the step with params unchanged.
- ``hang@N[:S]``       — the worker stops progressing WITHOUT exiting
  (infinite sleep, or S seconds when given): no exception, no exit
  code — the hang watchdog (utils/supervision.ProgressBoard) must
  detect, SIGKILL and respawn it.  Plane-agnostic: schedule it on any
  instrumented endpoint (``ACTOR_FAULTS`` counts actor vector ticks).

Injectors are wired through env vars so fault schedules reach spawn
children without plumbing: ``DCN_FAULTS_CLIENT`` / ``DCN_FAULTS_GATEWAY``
(wire roles) and ``{ROLE}_FAULTS`` for the other planes — ``CKPT_FAULTS``
(checkpoint writer), ``FEEDER_FAULTS`` (actor-side chunk flushes),
``LEARNER_FAULTS`` (update steps), ``ACTOR_FAULTS`` (vector ticks),
``INGEST_FAULTS`` (the learner-side ingest drain, one frame per drained
chunk — ``delay@N:S`` there is the slow-learner-ingest overload lever
the ISSUE-11 flow-control drills pull, tools/chaos_soak.py
``--slow-learner-ingest``) — hold either a scripted spec or
``random:SEED`` (see ``FaultInjector.from_env``); fleet.py exposes the
DCN pair as ``--faults-client`` / ``--faults-gateway`` CLI knobs.  No
spec = a null injector whose per-frame cost is one lock + dict probe.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

FaultEvent = Tuple[int, str, float]  # (frame index, action, arg)

# ``poison_chunk`` / ``poison_grad`` are DATA-plane verbs: the injector
# cannot mutate structured data itself, so the instrumented boundary
# (memory/feeder.py QueueFeeder.flush, agents/learner.py) asks for them
# via ``data_frame(want=...)`` and applies the poison — NaN obs/reward /
# garbage priority at the feeder, a non-finite loss injected into the
# update at the learner.  ``hang`` makes the worker stop progressing
# WITHOUT exiting (an infinite sleep after a flight-recorder dump) — the
# alive-but-stuck failure mode the hang watchdog exists to catch.
_ACTIONS = ("sever", "delay", "blackhole", "corrupt", "crash", "kill",
            "poison_chunk", "poison_grad", "hang")

# default per-frame probabilities for the random mode — light enough that
# a healthy session layer rides through, frequent enough that a soak of a
# few thousand frames exercises every recovery path
_RANDOM_RATES = {"sever": 0.002, "delay": 0.003, "corrupt": 0.001}
_RANDOM_DELAY_S = 0.05


class InjectedDisconnect(ConnectionError):
    """A fault-injected connection death — handled exactly like a real
    socket error by the session layer (that equivalence is the point)."""


class InjectedCrash(RuntimeError):
    """A fault-injected process death — deliberately NOT a
    ConnectionError, so no transport-level handler swallows it; it
    propagates until the worker exits nonzero."""


def parse_faults(spec: str) -> List[FaultEvent]:
    """``"sever@5,delay@3:0.5"`` -> [(5, "sever", 0.0), (3, "delay", 0.5)].
    Raises ValueError on malformed specs — a fault drill that silently
    injects nothing proves nothing."""
    events: List[FaultEvent] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            action, rest = part.split("@", 1)
            if ":" in rest:
                at_s, arg_s = rest.split(":", 1)
                at, arg = int(at_s), float(arg_s)
            else:
                at, arg = int(rest), 0.0
        except ValueError as e:
            raise ValueError(f"bad fault event {part!r} "
                             f"(want action@frame[:arg])") from e
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r} "
                             f"(known: {_ACTIONS})")
        events.append((at, action, arg))
    return events


class FaultInjector:
    """One injector per instrumented endpoint.  ``frame(payload)`` is the
    single hook: it counts the operation, runs any events scheduled at
    that index (sleep / raise), and returns the — possibly corrupted —
    payload.  Thread-safe: a gateway shares one injector across its
    serve threads, so the frame counter is a global order over the
    gateway's receive stream."""

    def __init__(self, events: Iterable[FaultEvent] = (), name: str = ""):
        self.name = name
        self._lock = threading.Lock()
        self._n = 0
        self._by_frame: Dict[int, List[Tuple[str, float]]] = {}
        for at, action, arg in events:
            self._by_frame.setdefault(at, []).append((action, arg))
        self.injected = 0  # events fired so far (observability for soaks)

    # -- constructors --------------------------------------------------------

    @classmethod
    def scripted(cls, spec: str, name: str = "") -> "FaultInjector":
        return cls(parse_faults(spec), name=name)

    @classmethod
    def random(cls, seed: int, horizon: int = 4000,
               rates: Optional[Dict[str, float]] = None,
               name: str = "") -> "FaultInjector":
        """A reproducible random schedule over the first ``horizon``
        frames.  ``crash`` is never drawn here — random process kills
        belong to the orchestrator (tools/chaos_soak.py), which owns the
        restart story; the wire injector only breaks the wire."""
        import numpy as np

        rng = np.random.default_rng(seed)
        events: List[FaultEvent] = []
        for action, p in (rates if rates is not None
                          else _RANDOM_RATES).items():
            hits = np.nonzero(rng.random(horizon) < p)[0]
            arg = _RANDOM_DELAY_S if action in ("delay", "blackhole") else 0.0
            events.extend((int(at), action, arg) for at in hits)
        return cls(events, name=name)

    @classmethod
    def from_env(cls, role: str) -> "FaultInjector":
        """``DCN_FAULTS_CLIENT`` / ``DCN_FAULTS_GATEWAY`` (wire roles) or
        ``{ROLE}_FAULTS`` (other planes, e.g. ``CKPT_FAULTS`` for the
        checkpoint writer): a scripted spec, or ``random:SEED[:HORIZON]``.
        Unset/empty -> null injector.  Per-process (spawn children
        inherit the env), which is what a kill-at-step-N drill needs."""
        var = (f"DCN_FAULTS_{role.upper()}" if role in ("client", "gateway")
               else f"{role.upper()}_FAULTS")
        spec = os.environ.get(var, "").strip()
        if not spec:
            return cls(name=role)
        if spec.startswith("random:"):
            parts = spec.split(":")
            seed = int(parts[1])
            horizon = int(parts[2]) if len(parts) > 2 else 4000
            return cls.random(seed, horizon=horizon, name=role)
        return cls.scripted(spec, name=role)

    # -- the hook ------------------------------------------------------------

    def _note(self, action: str, frame: int, fatal: bool) -> None:
        """Leave the drill's fingerprint in the flight recorder
        (utils/flight_recorder.py) — and for FATAL actions (crash, kill)
        dump every ring this process holds NOW: nothing runs after a
        SIGKILL, so the pre-signal dump is the only reason a kill drill
        leaves a ``blackbox/`` post-mortem at all.  Transparent faults
        (sever/delay/corrupt) only record: the session layer is expected
        to ride through them, and a dump per routine sever would churn
        the blackbox files of a healthy soak."""
        try:
            from pytorch_distributed_tpu.utils import flight_recorder

            flight_recorder.get_recorder(
                f"faults-{self.name or 'anon'}").record(
                "fault", action=action, frame=frame)
            if fatal:
                flight_recorder.dump_all(
                    f"injected {action} at frame {frame} "
                    f"(faults:{self.name})")
        except Exception:  # noqa: BLE001 - the drill must fire regardless
            pass

    def frame(self, payload: bytes = b"") -> bytes:
        """Account one frame operation; fire its scheduled events."""
        payload, _ = self._step(payload, ())
        return payload

    def data_frame(self, want: Tuple[str, ...] = ()
                   ) -> List[Tuple[str, float]]:
        """Account one DATA-plane operation (a feeder flush, a learner
        step): fires the side-effectful events exactly like ``frame``
        and returns the fired ``want`` events — the poison verbs the
        caller must apply itself (it owns the structured data the
        injector cannot mutate)."""
        _, hits = self._step(b"", tuple(want))
        return hits

    def _step(self, payload: bytes, want: Tuple[str, ...]
              ) -> Tuple[bytes, List[Tuple[str, float]]]:
        with self._lock:
            n = self._n
            self._n += 1
            events = self._by_frame.get(n)
        hits: List[Tuple[str, float]] = []
        if not events:
            return payload, hits
        for action, arg in events:
            if action.startswith("poison") and action not in want:
                # a data-plane verb scheduled on a wire plane (or a
                # plane that doesn't ask for it) is inert by design —
                # record it so a mis-wired drill is diagnosable
                self._note(action, n, fatal=False)
                continue
            self.injected += 1
            self._note(action, n,
                       fatal=action in ("crash", "kill", "hang"))
            if action in want:
                hits.append((action, arg))
            elif action == "delay":
                time.sleep(arg)
            elif action == "sever":
                raise InjectedDisconnect(
                    f"[faults:{self.name}] injected sever at frame {n}")
            elif action == "blackhole":
                time.sleep(arg)
                raise InjectedDisconnect(
                    f"[faults:{self.name}] blackhole healed after {arg}s "
                    f"at frame {n}")
            elif action == "crash":
                raise InjectedCrash(
                    f"[faults:{self.name}] injected crash at frame {n}")
            elif action == "kill":
                import signal

                # stdout may never flush — that's the point of SIGKILL
                print(f"[faults:{self.name}] SIGKILL at frame {n}",
                      flush=True)
                os.kill(os.getpid(), signal.SIGKILL)
            elif action == "hang":
                # stop progressing WITHOUT exiting: no exception, no
                # exit code — exactly the failure the watchdog must
                # catch.  The blackbox dump already happened (_note
                # fatal), because nothing runs after the SIGKILL that
                # ends this.  ``arg`` (seconds) bounds the hang for
                # self-recovering drills; 0 = forever.
                print(f"[faults:{self.name}] HANG at frame {n}",
                      flush=True)
                deadline = (time.monotonic() + arg) if arg > 0 \
                    else float("inf")
                while time.monotonic() < deadline:
                    time.sleep(0.2)
            elif action == "corrupt":
                if payload:
                    mutated = bytearray(payload)
                    # flip the leading magic AND a middle byte: a flip
                    # only in the middle can land in zip member padding
                    # (savez 64-byte aligns members) and decode clean —
                    # the drill must corrupt DETERMINISTICALLY for any
                    # payload layout, so the format magic always breaks
                    for i in {0, len(mutated) // 2}:
                        mutated[i] ^= 0xFF
                    payload = bytes(mutated)
                else:
                    payload = b"\xff"  # give empty frames something to break
        return payload, hits

    @property
    def frames_seen(self) -> int:
        with self._lock:
            return self._n
