"""End-to-end flow control and graceful degradation (ISSUE 11 tentpole).

Until this module the experience plane had exactly one answer to
overload: block.  A slow learner filled the spawn queue, the queue
blocked the gateway's ``put_chunk``, the blocked serve thread stalled
the remote actor's synchronous RPC, and the whole fleet froze behind
one saturated host — the failure model in parallel/dcn.py stated it
outright ("legitimate backpressure stalls the actor").  Ape-X (Horgan
et al. 2018) assumes actors OUTRUN the learner by design, and
In-Network Experience Sampling (PAPERS.md) makes the same point at the
transport layer: under pressure the experience plane must *degrade*
(freshest-data-wins drops, every one counted), never deadlock.  This
module is that policy layer, consumed by every transport:

- **OverloadGovernor** — the gateway's explicit overload state machine
  (``healthy -> throttled -> shedding``) driven by a live pressure
  signal (ingest-queue utilization on real topologies), with dwell
  gating on escalation and a separate recover threshold + hysteresis
  window on de-escalation so the band between them never flaps.
  Sustained shedding climbs a **brownout ladder**: tier 1 sheds
  telemetry pushes, tier 2 additionally sheds trace sampling, tier 3
  additionally sheds oldest experience — the learn path is never
  *silently* corrupted; every rung is counted and every transition is
  a flight-recorder ``overload`` event (LOUD on tools/timeline.py)
  plus a ``flow/overload_state`` scalar the alert rules watch.
- **GatewayFlow** — the DcnGateway's per-slot admission plane: credit
  grants riding every T_CLOCK ack (healthy = no credit field =
  unlimited; throttled = token-bucket-metered grants; shedding = 0),
  per-slot token buckets + the tier-3 shed of non-credit-aware peers
  (one runaway actor drains its OWN bucket, not its neighbours'), and
  the conservation ledger: ``minted = ingested + dropped + quarantined
  (+ still-buffered)``, checkable live from the STATUS ``flow`` block.
- **DropOldestRing** — the bounded client/feeder buffer: overflow
  drops the OLDEST chunk (newest experience wins, Ape-X
  priority-on-arrival), every drop counted and provenance-stamped
  (per-actor row counts off the ISSUE-8 prov columns).
- **Process-local brownout hooks** (``set_brownout``/``telemetry_shed``
  /``trace_shed``) — the client side of the ladder: DcnClient latches
  the tier carried on gateway replies, RemoteStats then sheds stat
  pushes (tier >= 1) and QueueFeeder stops minting traced chunks
  (tier >= 2), each counted via ``note_shed``/``shed_counts``.

Knobs live in ``config.FlowParams``, env-overridable as
``TPU_APEX_FLOW_<FIELD>`` (bare ``TPU_APEX_FLOW=0`` = ``enabled``) —
the same spawn-inheritance contract the health/perf/metrics planes
use.  The plane defaults ON but INERT: in the healthy state no credit
field rides the wire, nothing is ever shed, and the hot-path cost is a
few dict/float ops (bench.py ``flow_overhead`` gates it under the
0.02 absolute overhead band).

Drilled by ``tools/chaos_soak.py --flood`` / ``--slow-learner-ingest``
/ ``--slow-slot`` (deadlock, unbounded memory, uncounted drops and
unexpected alerts are each violations) and tests/test_flow.py.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

_ENV_PREFIX = "TPU_APEX_FLOW_"

# overload state -> scalar code for the ``flow/overload_state`` series
# (what the DEFAULT_RULES ``overload_shed`` threshold rule watches)
STATE_CODE = {"healthy": 0.0, "throttled": 1.0, "shedding": 2.0}


def resolve_flow(fp=None):
    """FlowParams + ``TPU_APEX_FLOW_<FIELD>`` env overrides, plus the
    bare ``TPU_APEX_FLOW`` shorthand for ``enabled`` — same
    override-by-env contract as perf/health/metrics resolve.  Returns
    a NEW instance; the input is never mutated (Options rides spawn
    pickles)."""
    from pytorch_distributed_tpu.config import FlowParams

    if fp is None:
        fp = FlowParams()
    changes: Dict[str, Any] = {}
    raw_on = os.environ.get("TPU_APEX_FLOW")
    if raw_on is not None:
        changes["enabled"] = raw_on.strip().lower() not in (
            "0", "false", "off", "no", "")
    for f in dataclasses.fields(fp):
        raw = os.environ.get(_ENV_PREFIX + f.name.upper())
        if raw is None:
            continue
        cur = getattr(fp, f.name)
        if isinstance(cur, bool):
            changes[f.name] = raw.strip().lower() not in (
                "0", "false", "off", "no", "")
        elif isinstance(cur, int) and not isinstance(cur, bool):
            changes[f.name] = int(float(raw))
        elif isinstance(cur, float):
            changes[f.name] = float(raw)
        else:
            changes[f.name] = raw.strip()
    return dataclasses.replace(fp, **changes) if changes else fp


def export_env(fp) -> None:
    """Export a RESOLVED FlowParams into the environment so spawn
    children (actor processes building their own QueueFeeders) resolve
    the same plane as the topology that configured it programmatically.
    setdefault: an operator's explicit env always wins."""
    if not fp.enabled:
        os.environ.setdefault("TPU_APEX_FLOW", "0")
    for f in dataclasses.fields(fp):
        val = getattr(fp, f.name)
        if val != f.default:
            os.environ.setdefault(_ENV_PREFIX + f.name.upper(),
                                  ("1" if val is True else
                                   "0" if val is False else str(val)))


# ---------------------------------------------------------------------------
# process-local brownout state (the client side of the ladder)
# ---------------------------------------------------------------------------

_brownout_lock = threading.Lock()
_brownout_tier = 0
_shed_counts: Dict[str, int] = {}


def set_brownout(tier: int) -> None:
    """Latch the brownout tier the gateway last announced (DcnClient
    reads it off T_CLOCK replies).  Process-wide on purpose: the
    feeder/stats/tracing hooks live in the same actor process as the
    client that learns the tier."""
    global _brownout_tier
    with _brownout_lock:
        _brownout_tier = int(tier)


def brownout_tier() -> int:
    with _brownout_lock:
        return _brownout_tier


def telemetry_shed() -> bool:
    """Tier >= 1: stat/metrics pushes are shed (counted, never silent)."""
    return brownout_tier() >= 1


def trace_shed() -> bool:
    """Tier >= 2: new chunks ship untraced (span minting suppressed)."""
    return brownout_tier() >= 2


def note_shed(kind: str, n: int = 1) -> None:
    """Count one shed at a declared shed point (``shed_counts`` is the
    observability half of 'drops are counted, never silent')."""
    with _brownout_lock:
        _shed_counts[kind] = _shed_counts.get(kind, 0) + int(n)


def shed_counts() -> Dict[str, int]:
    with _brownout_lock:
        return dict(_shed_counts)


def reset_shed_state() -> None:
    """Test hook: clear the process-local tier + counters."""
    global _brownout_tier
    with _brownout_lock:
        _brownout_tier = 0
        _shed_counts.clear()


# ---------------------------------------------------------------------------
# token bucket (per-slot admission metering)
# ---------------------------------------------------------------------------

class TokenBucket:
    """Classic refill-on-read token bucket, thread-safe.  ``take``
    consumes on success; ``level`` is the credit-grant read (a grant
    may overshoot by at most the grant cap between takes — flow
    control, not accounting)."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._last = clock()

    def _refill(self, now: float) -> None:
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now

    def take(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill(self._clock())
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def level(self) -> float:
        with self._lock:
            self._refill(self._clock())
            return self._tokens


# ---------------------------------------------------------------------------
# bounded drop-oldest buffer (the client/feeder shed point)
# ---------------------------------------------------------------------------

def _prov_actor(t, owner: int) -> int:
    """Actor id off a transition's ISSUE-8 prov column (-1 sentinel and
    prov-less rows fall back to ``owner``) — the one extraction every
    counted shed point stamps drops with."""
    prov = getattr(t, "prov", None)
    if prov is not None and len(prov) and int(prov[0]) >= 0:
        return int(prov[0])
    return int(owner)


class DropOldestRing:
    """Bounded chunk buffer: ``put`` appends the newest chunk and, at
    capacity, evicts the OLDEST (newest experience wins — Ape-X
    priority-on-arrival; In-Network Experience Sampling's
    freshest-data-wins drop policy).  Every drop is counted
    (chunks + rows) and provenance-stamped: per-actor dropped-row
    tallies off the ISSUE-8 prov columns (falling back to ``owner`` for
    rows minted without provenance), so the data X-ray can name WHOSE
    experience the overload cost."""

    def __init__(self, max_chunks: int, owner: int = -1):
        self.max_chunks = max(1, int(max_chunks))
        self.owner = int(owner)
        self._lock = threading.Lock()
        self._buf: collections.deque = collections.deque()
        self.dropped_chunks = 0
        self.dropped_rows = 0
        self.buffered_high = 0  # high-water mark, chunks (bounded-memory proof)
        self.dropped_by_actor: Dict[int, int] = {}

    def _stamp(self, chunk: list) -> None:
        for row in chunk:
            t = row[0] if isinstance(row, tuple) else row
            actor = _prov_actor(t, self.owner)
            self.dropped_by_actor[actor] = (
                self.dropped_by_actor.get(actor, 0) + 1)

    def put(self, chunk: list) -> int:
        """Buffer one chunk; returns rows DROPPED to make room (0 when
        the ring had space)."""
        dropped = 0
        with self._lock:
            self._buf.append(chunk)
            self.buffered_high = max(self.buffered_high, len(self._buf))
            while len(self._buf) > self.max_chunks:
                old = self._buf.popleft()
                self.dropped_chunks += 1
                self.dropped_rows += len(old)
                dropped += len(old)
                self._stamp(old)
        return dropped

    def pop(self) -> Optional[list]:
        """Oldest buffered chunk, or None."""
        with self._lock:
            return self._buf.popleft() if self._buf else None

    def unpop(self, chunk: list) -> None:
        """Return a popped chunk to the FRONT (drain loops that hit a
        still-full sink put the in-flight chunk back without reordering
        — and without it counting as a fresh arrival)."""
        with self._lock:
            self._buf.appendleft(chunk)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    @property
    def buffered_rows(self) -> int:
        with self._lock:
            return sum(len(c) for c in self._buf)


# ---------------------------------------------------------------------------
# the overload state machine + brownout ladder
# ---------------------------------------------------------------------------

class OverloadGovernor:
    """``healthy -> throttled -> shedding`` off a 0..1 pressure signal.

    Escalation: pressure sustained >= the next state's threshold for
    ``dwell_s`` climbs ONE state per dwell (a pressure step to 1.0
    still walks healthy -> throttled -> shedding, so the timeline shows
    the ramp).  De-escalation: pressure sustained < ``recover_at`` for
    ``recover_s`` steps down one state — the hysteresis band between
    ``recover_at`` and ``throttle_at`` holds the current state.

    Inside shedding, the brownout tier climbs one rung per
    ``brownout_dwell_s`` (1 = shed telemetry, 2 = + trace sampling,
    3 = + oldest experience) and resets as the state de-escalates.

    Every state/tier transition is recorded to the flight recorder
    (``kind: "overload"`` — a LOUD tools/timeline.py kind, clock-
    aligned with the alerts it should trigger) and written as a
    ``flow/overload_state`` scalar when a writer is wired, which is
    what the DEFAULT_RULES ``overload_shed`` threshold rule watches."""

    STATES = ("healthy", "throttled", "shedding")

    def __init__(self, params=None, recorder=None, writer=None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time):
        self.params = resolve_flow(params)
        self._recorder = recorder
        self.writer = writer
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        self.state = "healthy"
        self.tier = 0
        self.transitions = 0
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        self._tier_since: Optional[float] = None
        self.last_pressure = 0.0

    def _record(self, now: float, pressure: float, why: str) -> None:
        self.transitions += 1
        if self._recorder is not None:
            self._recorder.record("overload", state=self.state,
                                  tier=self.tier,
                                  pressure=round(pressure, 4), why=why)
        if self.writer is not None:
            try:
                self.writer.scalar("flow/overload_state",
                                   STATE_CODE[self.state] + 0.0,
                                   step=self.transitions,
                                   wall=self._wall())
                self.writer.scalar("flow/brownout_tier", float(self.tier),
                                   step=self.transitions,
                                   wall=self._wall())
                self.writer.flush()
            except Exception:  # noqa: BLE001 - telemetry must not kill flow
                pass

    def update(self, pressure: float,
               now: Optional[float] = None) -> Optional[str]:
        """One evaluation; returns the new state on a transition (state
        OR tier change), else None."""
        p = self.params
        if now is None:
            now = self._clock()
        with self._lock:
            self.last_pressure = float(pressure)
            level = self.STATES.index(self.state)
            next_thresh = (p.throttle_at if level == 0 else p.shed_at)
            changed = False
            if level < 2 and pressure >= next_thresh:
                self._below_since = None
                if self._above_since is None:
                    self._above_since = now
                if now - self._above_since >= p.dwell_s:
                    level += 1
                    self.state = self.STATES[level]
                    self._above_since = now  # next rung needs its own dwell
                    if self.state == "shedding":
                        self.tier = 1
                        self._tier_since = now
                    changed = True
                    self._record(now, pressure, "escalate")
            elif pressure < p.recover_at:
                self._above_since = None
                if self._below_since is None:
                    self._below_since = now
                if level > 0 and now - self._below_since >= p.recover_s:
                    level -= 1
                    self.state = self.STATES[level]
                    self._below_since = now  # next step down re-dwells
                    self.tier = 0 if self.state != "shedding" else self.tier
                    self._tier_since = None
                    changed = True
                    self._record(now, pressure, "recover")
            else:
                # the hysteresis band: hold state, reset both dwells
                self._above_since = None
                self._below_since = None
            if (self.state == "shedding" and self.tier < 3
                    and self._tier_since is not None
                    and now - self._tier_since >= p.brownout_dwell_s):
                self.tier += 1
                self._tier_since = now
                changed = True
                self._record(now, pressure, "brownout")
            return self.state if changed else None


# ---------------------------------------------------------------------------
# the gateway's composed flow plane
# ---------------------------------------------------------------------------

class GatewayFlow:
    """Per-slot admission control + credit grants + the conservation
    ledger, owned by one DcnGateway.

    ``admit(slot, rows)`` runs on every EXP frame: it time-gates a
    governor update off the wired ``pressure`` provider, meters the
    slot's token bucket, and returns False — SHED this chunk, counted —
    only at brownout tier 3 when the slot's bucket is dry (the
    declared gateway shed point for peers that ignore credits; credit-
    aware clients never reach it, they buffer client-side at grant 0).

    ``grant(slot)`` sizes the credit field riding the slot's next ack:
    None while healthy (no field on the wire — byte-compatible with
    old peers and zero-cost for compliant ones), a bucket-metered
    integer while throttled, 0 while shedding.

    Conservation: clients report cumulative ``minted``/``dropped``/
    ``buffered`` row counters on their tick cadence (idempotent under
    retransmit — cumulative, not deltas); the gateway adds its own
    ``ingested_rows``/``shed_rows`` and the quarantine counts it
    already keeps, and ``conservation()`` checks the ledger live
    (one-sided — see its docstring; the chaos drills assert exact
    equality at quiescence)."""

    def __init__(self, params=None, pressure=None, recorder=None,
                 writer=None, clock: Callable[[], float] = time.monotonic,
                 update_every: float = 0.25):
        self.params = resolve_flow(params)
        self.pressure = pressure
        self._clock = clock
        self._update_every = float(update_every)
        self.governor = OverloadGovernor(self.params, recorder=recorder,
                                         writer=writer, clock=clock)
        self._recorder = recorder
        self._lock = threading.Lock()
        self._buckets: Dict[int, TokenBucket] = {}
        self._next_update = 0.0
        self.ingested_rows = 0
        self.shed_chunks = 0
        self.shed_rows: Dict[int, int] = {}
        self.client_reports: Dict[int, Dict[str, int]] = {}
        self._shed_logged = 0
        # byte legs of the conservation ledger (ISSUE 18): every acked
        # EXP frame's payload bytes land in exactly ONE of these —
        # rejected (schema-invalid, acked), shed (admit False, acked),
        # or ingested (everything else; quarantine refines rows, not
        # bytes).  The client's matching cumulative ``acked_bytes``
        # rides its tick report.
        self.ingested_bytes = 0
        self.rejected_bytes = 0
        self.shed_bytes = 0
        # rung attribution: brownout tier -> shed bytes (the --flood
        # drill reports bytes shed per rung)
        self.shed_bytes_by_tier: Dict[int, int] = {}

    # -- plumbing ------------------------------------------------------------

    def _bucket(self, slot: int) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(slot)
            if b is None:
                b = self._buckets[slot] = TokenBucket(
                    self.params.bucket_rate, self.params.bucket_burst,
                    clock=self._clock)
            return b

    def refresh(self, now: Optional[float] = None) -> None:
        """Time-gated governor update off the pressure provider (runs on
        the serve threads — cheap by construction, every
        ``update_every`` seconds at most)."""
        if now is None:
            now = self._clock()
        with self._lock:
            if now < self._next_update:
                return
            self._next_update = now + self._update_every
        p = 0.0
        if self.pressure is not None:
            try:
                p = float(self.pressure())
            except Exception:  # noqa: BLE001 - a failing probe reads healthy
                p = 0.0
        self.governor.update(p, now=now)

    # -- the two hot-path reads ----------------------------------------------

    def admit(self, slot: Optional[int], rows: int,
              nbytes: int = 0) -> bool:
        """Gateway-side admission for one decoded EXP chunk.  Always
        meters the slot's bucket (so fairness accounting is live before
        overload); only SHEDS — returns False — at brownout tier 3 with
        the bucket dry.  Shed chunks are counted per slot and recorded
        (throttled to the first few) as ``flow-shed`` events."""
        self.refresh()
        s = -1 if slot is None else int(slot)
        has_tokens = self._bucket(s).take(1.0)
        if self.governor.tier >= 3 and not has_tokens:
            with self._lock:
                self.shed_chunks += 1
                self.shed_rows[s] = self.shed_rows.get(s, 0) + int(rows)
                self.shed_bytes += int(nbytes)
                tier = self.governor.tier
                self.shed_bytes_by_tier[tier] = \
                    self.shed_bytes_by_tier.get(tier, 0) + int(nbytes)
                self._shed_logged += 1
                log_it = self._shed_logged <= 3
            if self._recorder is not None:
                self._recorder.record("flow-shed", slot=s, rows=int(rows),
                                      tier=self.governor.tier)
            if log_it:
                print(f"[flow] tier-3 brownout: shed {rows}-row chunk "
                      f"from slot {s} (bucket dry)", flush=True)
            return False
        return True

    def note_ingested(self, rows: int) -> None:
        """Count rows that actually entered the learn path (admitted
        AND clean of quarantine) — the ``ingested`` leg of the
        conservation ledger.  Counted separately from ``admit`` so a
        quarantined row lands in exactly one bucket."""
        with self._lock:
            self.ingested_rows += int(rows)

    def note_ingested_bytes(self, nbytes: int) -> None:
        """Count an admitted EXP frame's payload bytes (frame-granular:
        counted even when quarantine empties the chunk — its rows land
        in the quarantined bucket, its bytes stay here)."""
        with self._lock:
            self.ingested_bytes += int(nbytes)

    def note_rejected_bytes(self, nbytes: int) -> None:
        """Count a schema-rejected (but acked) EXP frame's payload
        bytes — the ``framed-reject`` leg of the byte ledger."""
        with self._lock:
            self.rejected_bytes += int(nbytes)

    def grant(self, slot: Optional[int]) -> Optional[int]:
        """Credit grant for the slot's next ack; None = no credit field
        (healthy — unlimited)."""
        self.refresh()
        state = self.governor.state
        if state == "healthy":
            return None
        if state == "shedding":
            return 0
        s = -1 if slot is None else int(slot)
        return max(0, min(self.params.credits_throttled,
                          int(self._bucket(s).level())))

    # -- reports + reads -----------------------------------------------------

    def on_client_report(self, slot: Optional[int], report: dict) -> None:
        """Absorb a client's cumulative flow counters off its T_TICK
        (idempotent: retransmitted ticks carry the same cumulative
        values, so the dedup window cannot double-count drops)."""
        if slot is None or not isinstance(report, dict):
            return
        clean: Dict[str, int] = {}
        for k in ("minted", "acked", "acked_bytes", "dropped",
                  "buffered"):
            try:
                clean[k] = int(report.get(k, 0))
            except (TypeError, ValueError):
                clean[k] = 0
        with self._lock:
            self.client_reports[int(slot)] = clean

    def conservation(self, quarantined: int = 0) -> dict:
        """The ledger: every minted row must be ingested, counted
        dropped, quarantined, or still buffered client-side.  Only
        meaningful over slots that REPORT (credit-aware clients); a
        fleet of legacy peers reports nothing and the check degrades
        to 'unknown', never to a false alarm.

        The LIVE check flags only ``minted > accounted`` — a row the
        clients minted that no counted bucket can explain (the
        uncounted-drop smell).  ``accounted`` legitimately overshoots
        ``minted`` in flight: client counters are tick-cadence stale
        while the gateway's ``ingested`` is real-time, and a legacy
        (non-reporting) peer's rows land in ``ingested`` with no
        ``minted`` to match — neither is a leak.  Quiescent drills
        (tools/chaos_soak.py) assert exact equality from final
        counters instead."""
        with self._lock:
            reports = {s: dict(r) for s, r in self.client_reports.items()}
            gw_shed = sum(self.shed_rows.values())
            ingested = self.ingested_rows
            ingested_b = self.ingested_bytes
            rejected_b = self.rejected_bytes
            shed_b = self.shed_bytes
        minted = sum(r["minted"] for r in reports.values())
        dropped = sum(r["dropped"] for r in reports.values())
        buffered = sum(r["buffered"] for r in reports.values())
        acked_b = sum(r.get("acked_bytes", 0) for r in reports.values())
        out = {
            "minted": minted,
            "ingested": ingested,
            "dropped_client": dropped,
            "shed_gateway": gw_shed,
            "quarantined": int(quarantined),
            "buffered_client": buffered,
            # the byte ledger (ISSUE 18): every acked EXP payload byte
            # is ingested, framed-rejected, or gateway-shed; unlike
            # rows there is no client-side byte bucket — ring-dropped
            # chunks are never encoded, so their bytes never exist
            "acked_bytes": acked_b,
            "ingested_bytes": ingested_b,
            "rejected_bytes": rejected_b,
            "shed_bytes": shed_b,
            "reporting_slots": sorted(reports),
        }
        if reports:
            accounted = (ingested + dropped + gw_shed
                         + int(quarantined) + buffered)
            out["accounted"] = accounted
            out["balanced"] = bool(minted <= accounted)
            # one-sided for the same reason as rows: client counters
            # are tick-cadence stale while the gateway legs are
            # real-time, and legacy peers ingest bytes with no report
            accounted_b = ingested_b + rejected_b + shed_b
            out["accounted_bytes"] = accounted_b
            out["bytes_balanced"] = bool(acked_b <= accounted_b)
        return out

    def status_block(self, quarantined: int = 0) -> dict:
        """The STATUS ``flow`` block: overload state + tier, per-slot
        credit grants and shed counts, client-reported drop counters,
        per-actor drop share (next to ``replay/actor_share`` in the
        data X-ray), and the conservation ledger."""
        with self._lock:
            slots = sorted(set(self._buckets) | set(self.shed_rows)
                           | set(self.client_reports))
            shed = {str(s): n for s, n in sorted(self.shed_rows.items())}
            reports = {str(s): dict(r)
                       for s, r in sorted(self.client_reports.items())}
        # built from the locked snapshots, so the share a slot shows is
        # consistent with the counts printed next to it in the same block
        drops = {s: (shed.get(s, 0) + reports.get(s, {}).get("dropped", 0))
                 for s in (str(x) for x in slots)}
        total_drops = sum(drops.values())
        blk = {
            "state": self.governor.state,
            "tier": self.governor.tier,
            "pressure": round(self.governor.last_pressure, 4),
            "transitions": self.governor.transitions,
            "credits": {str(s): self.grant(s) for s in slots
                        if self.governor.state != "healthy"},
            "shed_rows": shed,
            "shed_chunks": self.shed_chunks,
            "client": reports,
            "drop_share": ({s: round(n / total_drops, 4)
                            for s, n in drops.items() if n}
                           if total_drops else {}),
            "conservation": self.conservation(quarantined=quarantined),
        }
        return blk


# ---------------------------------------------------------------------------
# local-transport shed policy (spawn-queue feeder / device-replay pending)
# ---------------------------------------------------------------------------

def shed_overflow(pending: List, max_rows: int,
                  counters: Dict[str, int],
                  owner: int = -1) -> List:
    """Drop-OLDEST overflow for a pending-row list (the device-replay
    ingest's ``local_policy="shed"`` bound): returns the trimmed list,
    counts the shed into ``counters`` (``shed_rows`` + per-actor
    ``shed_by_actor:<id>`` keys stamped from prov)."""
    over = len(pending) - int(max_rows)
    if over <= 0:
        return pending
    dropped, kept = pending[:over], pending[over:]
    counters["shed_rows"] = counters.get("shed_rows", 0) + over
    for t in dropped:
        k = f"shed_by_actor:{_prov_actor(t, owner)}"
        counters[k] = counters.get(k, 0) + 1
    return kept
