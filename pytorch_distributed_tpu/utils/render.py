"""Headless rendering: dump env frames to PNG files.

The reference displays live frames with ``cv2.imshow`` during evaluation
(reference core/env.py:51-76, core/envs/atari_env.py:83); this image is
headless and ships no cv2, so the equivalent capability is a frame dump —
attach a ``FrameDumper`` to any env (``env.attach_renderer``) and each
``env.render()`` call writes the newest observation frame as a PNG under
``<dir>/ep<episode>/step<t>.png``.  Enabled by the ``--render`` CLI flag
in mode 2 (tester) and by ``env_params.render`` generally.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


def frame_image(obs: np.ndarray) -> Optional[np.ndarray]:
    """Newest displayable (H, W) or (H, W, 3) uint8 frame in an
    observation, or None for non-image observations (low-dim vectors)."""
    obs = np.asarray(obs)
    if obs.dtype != np.uint8:
        return None
    if obs.ndim == 2:
        return obs
    if obs.ndim == 3:
        if obs.shape[-1] == 3:  # already (H, W, RGB)
            return obs
        return obs[-1]  # (C, H, W) frame stack: newest frame last
    return None


def attach_frame_dumper(env, log_dir: str, role: str) -> str:
    """Wire a FrameDumper under ``<log_dir>/frames`` onto ``env`` and
    announce it — the shared attach used by the tester (mode 2) and the
    mode-1 evaluator."""
    frames_dir = os.path.join(log_dir, "frames")
    env.attach_renderer(FrameDumper(frames_dir))
    print(f"[{role}] rendering eval frames to {frames_dir}")
    return frames_dir


class FrameDumper:
    def __init__(self, root: str):
        self.root = root
        self.episode = -1
        self.t = 0
        os.makedirs(root, exist_ok=True)

    def new_episode(self) -> None:
        self.episode += 1
        self.t = 0
        os.makedirs(self._ep_dir(), exist_ok=True)

    def _ep_dir(self) -> str:
        return os.path.join(self.root, f"ep{self.episode:03d}")

    def add(self, obs: np.ndarray) -> Optional[str]:
        """Write the observation's newest frame; returns the path (None
        for non-image observations)."""
        img = frame_image(obs)
        if img is None:
            return None
        if self.episode < 0:
            self.new_episode()
        from PIL import Image

        path = os.path.join(self._ep_dir(), f"step{self.t:05d}.png")
        Image.fromarray(img).save(path)
        self.t += 1
        return path
