"""Performance observability plane: live MFU, throughput attribution,
device-memory watermarks, retrace + transfer auditing, on-demand
profiling windows.

Until this module, every performance number lived in offline artifacts —
``bench.py`` one-line JSONs and ``tools/mfu_probe.py`` blobs — so the
questions the ROADMAP's next levers hinge on ("is the learner's MFU
moving?", "is the fleet actor-bound right now?") could only be answered
by stopping the fleet and re-benching.  Podracer (Hessel et al. 2021)
treats continuous device-utilization accounting as part of the training
loop itself, and Ape-X tunes its actor/learner balance off live
throughput ratios; this module gives the fleet the same continuously
exported signals:

- **FLOPs capture** (``flops_of_compiled``): the XLA ``cost_analysis()``
  extraction previously duplicated in ``bench.py`` (micro + families)
  and ``mfu_probe.py`` lives here once.  A ``PerfMonitor`` captures the
  fused learner program's per-update FLOPs at compile time, so MFU is
  one multiplication per stats window forever after — no re-bench.
- **Live rates** (``PerfMonitor``): each role counts its work units
  (learner updates, actor env frames) with one integer add on the hot
  path; the drain on the role's normal metrics cadence turns them into
  ``learner/updates_per_s`` / ``learner/mfu`` /
  ``actor/env_frames_per_s`` scalar rows plus whatever gauges the role
  sets (replay ratio, ingest-queue utilization).
- **Memory watermarks**: device ``live``/``peak`` bytes from
  ``device.memory_stats()`` where the backend reports them (TPU), host
  RSS current/peak everywhere — an OOM that is still ten minutes away
  is a dashboard read, not a post-mortem.
- **Retrace detector** (``RetraceDetector``): registered hot-path jit
  programs are expected to compile during warmup and NEVER again; any
  cache growth after the warmup mark is counted, named, and exported —
  a recompile on the hot path is a silent throughput cliff (the
  jit-cache no-retrace smoke in tests/test_actor_pipeline.py pins one
  program at one point in time; this watches all of them, live).
- **Transfer audit** (``TransferAudit``): opt-in
  ``jax.transfer_guard``-based attribution of IMPLICIT host<->device
  transfers on paths that must be transfer-free (the fused learner
  dispatch: state, ring and keys are all device-resident).  A flagged
  call is attributed to its python call site and retried with
  transfers allowed, so the audit observes without killing the run.
- **On-demand profile windows** (``run_profile_window``): a bounded
  ``utils/profiling.trace`` capture for the DCN gateway's sessionless
  ``T_PROFILE`` verb (parallel/dcn.py), so ``fleet_top --profile``
  pulls a real XLA trace off a RUNNING fleet without restarts.

Per-process registry (``get_monitor``) mirrors utils/tracing.py: one
monitor per role name, and ``status_snapshot()`` feeds the last drained
values into the gateway's T_STATUS health plane so ``fleet_top`` shows
them live.  Knobs live in config.PerfParams, env-overridable as
``TPU_APEX_PERF_<FIELD>`` (bare ``TPU_APEX_PERF=1`` = ``enabled``).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

# ---------------------------------------------------------------------------
# peak FLOP/s + cost-analysis FLOPs extraction (shared with bench.py and
# tools/mfu_probe.py — previously three inline copies)
# ---------------------------------------------------------------------------

# Peak dense bf16 FLOP/s per chip by device_kind, for the MFU estimate.
# Public figures; unknown kinds report achieved FLOP/s with mfu omitted.
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5e": 197e12,
    "TPU v5 lite": 197e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
    "TPU v6 lite": 918e12,
}

# Peak scaling per compute dtype relative to the bf16 table above: the
# MXU runs fp32 matmuls at half the bf16 rate (two passes), so an fp32
# run scored against the bf16 peak under-reports MFU by 2x (ISSUE-13
# satellite: config.compute_dtype admits fp32, and a denominator that
# ignores it makes the fp32 lever in mfu_probe.py look like an MFU
# collapse instead of the same chip at its fp32 peak).
DTYPE_PEAK_SCALE = {
    "bfloat16": 1.0,
    "float32": 0.5,
}


def peak_flops_of(device, compute_dtype: Optional[str] = None
                  ) -> Optional[float]:
    """Peak dense FLOP/s for a jax device, None when the kind is not in
    the table (CPU, future generations).  ``compute_dtype`` scales the
    bf16 table entry to the dtype's MXU peak (fp32 = half); unknown
    dtypes keep the bf16 figure."""
    kind = getattr(device, "device_kind", "") or ""
    for name, peak in PEAK_FLOPS.items():
        if kind.lower().startswith(name.lower()):
            if compute_dtype is not None:
                peak *= DTYPE_PEAK_SCALE.get(str(compute_dtype), 1.0)
            return peak
    return None


def flops_of_compiled(compiled) -> Optional[float]:
    """Per-call FLOPs off a ``cost_analysis()``-bearing jax stage — an
    AOT-compiled executable, or a ``Lowered`` program where the
    backend supports pre-compile analysis (same figures, no XLA
    compile).  XLA counts a scan/while body ONCE (verified in bench.py
    micro across K=1/8/64), so for a fused multi-update program the
    figure is per-UPDATE, not per-dispatch.  Best-effort: backends
    without cost analysis return None."""
    try:
        cost = compiled.cost_analysis()
        c = cost[0] if isinstance(cost, (list, tuple)) else cost
        f = (c or {}).get("flops")
        if f and f > 0:
            return float(f)
    except Exception:  # noqa: BLE001 - cost analysis is best-effort
        pass
    return None


# ---------------------------------------------------------------------------
# knob resolution (config.PerfParams + TPU_APEX_PERF_* env overrides)
# ---------------------------------------------------------------------------

_ENV_PREFIX = "TPU_APEX_PERF_"


def resolve(pp=None):
    """Apply ``TPU_APEX_PERF_<FIELD>`` env overrides to a PerfParams
    (config.py), plus the bare ``TPU_APEX_PERF`` shorthand for
    ``enabled`` — same override-by-env contract as health.resolve, so a
    drive can flip the plane on without threading knobs through every
    constructor.  Returns a NEW instance; the input is never mutated
    (Options rides spawn pickles)."""
    from pytorch_distributed_tpu.config import PerfParams

    if pp is None:
        pp = PerfParams()
    changes: Dict[str, Any] = {}
    raw_on = os.environ.get("TPU_APEX_PERF")
    if raw_on is not None:
        changes["enabled"] = raw_on.strip().lower() not in (
            "0", "false", "off", "no", "")
    for f in dataclasses.fields(pp):
        raw = os.environ.get(_ENV_PREFIX + f.name.upper())
        if raw is None:
            continue
        cur = getattr(pp, f.name)
        if isinstance(cur, bool):
            changes[f.name] = raw.strip().lower() not in (
                "0", "false", "off", "no", "")
        elif isinstance(cur, int) and not isinstance(cur, bool):
            changes[f.name] = int(float(raw))
        else:
            changes[f.name] = float(raw)
    return dataclasses.replace(pp, **changes) if changes else pp


_MXU_PREFIX = "TPU_APEX_MXU_"


def resolve_mxu(lp=None):
    """Apply ``TPU_APEX_MXU_<FIELD>`` env overrides to a
    LearnerPerfParams (config.py) — the ISSUE-13 MFU-campaign knob
    family (megabatch factor, Pallas torso), same override-by-env
    contract as ``resolve``.  Returns a NEW instance; the input is
    never mutated (Options rides spawn pickles)."""
    from pytorch_distributed_tpu.config import LearnerPerfParams

    if lp is None:
        lp = LearnerPerfParams()
    changes: Dict[str, Any] = {}
    for f in dataclasses.fields(lp):
        raw = os.environ.get(_MXU_PREFIX + f.name.upper())
        if raw is None:
            continue
        cur = getattr(lp, f.name)
        if isinstance(cur, bool):
            changes[f.name] = raw.strip().lower() not in (
                "0", "false", "off", "no", "")
        else:
            changes[f.name] = int(float(raw))
    return dataclasses.replace(lp, **changes) if changes else lp


def export_env(pp) -> None:
    """Export a RESOLVED PerfParams into the environment so spawn
    children (and their children — tools forked from workers) resolve
    the same plane even when it was enabled programmatically rather
    than by env.  setdefault: an operator's explicit env always
    wins."""
    if pp.enabled:
        os.environ.setdefault("TPU_APEX_PERF", "1")
    for f in dataclasses.fields(pp):
        val = getattr(pp, f.name)
        if val != f.default:
            os.environ.setdefault(_ENV_PREFIX + f.name.upper(),
                                  ("1" if val is True else
                                   "0" if val is False else str(val)))


# ---------------------------------------------------------------------------
# retrace detector
# ---------------------------------------------------------------------------

class RetraceDetector:
    """Counts jit cache misses per registered hot-path program and flags
    growth after warmup.

    Registration takes a zero-arg callable returning the program's
    current jit cache size (``jitted._cache_size`` — the same surface
    the actor engines already expose via ``jit_cache_size``); callables
    returning None (server-side jits, plain functions) are skipped per
    check, not rejected, so callers can register unconditionally.  The
    FIRST ``check()`` is the warmup mark: everything compiled up to it
    is expected; any growth seen by a later check is a retrace — a
    shape/dtype leak paying compile latency on the hot path."""

    def __init__(self):
        self._fns: Dict[str, Callable[[], Optional[int]]] = {}
        self._warm: Dict[str, int] = {}
        self._warmed = False
        self.retraces = 0                 # post-warmup recompiles, total
        self.fired: Dict[str, int] = {}   # per-program retrace counts

    def register(self, name: str,
                 size_fn: Optional[Callable[[], Optional[int]]]) -> None:
        if size_fn is not None:
            self._fns[name] = size_fn

    def _sizes(self) -> Dict[str, int]:
        out = {}
        for name, fn in self._fns.items():
            try:
                size = fn()
            except Exception:  # noqa: BLE001 - a dead fn must not kill perf
                size = None
            if size is not None:
                out[name] = int(size)
        return out

    def mark_warm(self) -> None:
        """Snapshot current cache sizes as the expected-compile set."""
        self._warm = self._sizes()
        self._warmed = True

    def check(self) -> List[str]:
        """Names of programs that recompiled since the last check.  The
        first call marks warmup instead of firing (startup compiles are
        legitimate); each recompile is counted once (the high-water
        advances)."""
        if not self._warmed:
            self.mark_warm()
            return []
        fired = []
        for name, size in self._sizes().items():
            prev = self._warm.get(name)
            if prev is None:
                self._warm[name] = size  # late registration: new warmup
                continue
            if size > prev:
                grew = size - prev
                self.retraces += grew
                self.fired[name] = self.fired.get(name, 0) + grew
                self._warm[name] = size
                fired.append(name)
        return fired


# ---------------------------------------------------------------------------
# transfer audit
# ---------------------------------------------------------------------------

class TransferAudit:
    """Attribute IMPLICIT host<->device transfers on a supposedly
    transfer-free path to their call sites.

    ``run(fn, *args)`` executes ``fn`` under ``jax.transfer_guard
    ("disallow")`` — which trips on implicit transfers only; explicit
    ``device_put``/``device_get`` are intended by definition and pass.
    On a trip the XLA error's traceback is walked to the innermost
    frame OUTSIDE jax itself (the call site that smuggled a host array
    onto the device path), the site is counted, and the call is retried
    with transfers allowed so the run continues.  The guard raises
    while STAGING the offending argument — before the program executes
    — so the retry is the only execution of a flagged jit dispatch."""

    def __init__(self):
        self.total = 0
        self.sites: Dict[str, int] = {}
        self.last_error: Optional[str] = None

    @staticmethod
    def _is_transfer_error(e: BaseException) -> bool:
        msg = str(e).lower()
        return "transfer" in msg and "disallow" in msg

    @staticmethod
    def _frame_site(frames) -> Optional[str]:
        site = None
        for fr in frames:
            path = fr.filename.replace(os.sep, "/")
            if "/jax/" in path or "/jaxlib/" in path \
                    or path.endswith("utils/perf.py"):
                continue
            site = f"{fr.filename}:{fr.lineno} ({fr.name})"
        return site

    @classmethod
    def _attribute(cls, e: BaseException) -> str:
        """Innermost python frame outside jax/jaxlib that owns the
        stray host array: from the error's traceback when the transfer
        staged deep inside the audited callable, else from the caller
        stack (the audited callable IS the jit dispatch — the guard
        trips while staging its arguments, so the interesting frame is
        the dispatch site above us)."""
        site = cls._frame_site(traceback.extract_tb(e.__traceback__))
        if site is None:
            site = cls._frame_site(traceback.extract_stack())
        return site or "<unattributed>"

    def run(self, fn, *args, **kwargs):
        import jax

        try:
            with jax.transfer_guard("disallow"):
                return fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 - only transfer trips handled
            if not self._is_transfer_error(e):
                raise
            site = self._attribute(e)
            first = site not in self.sites
            self.total += 1
            self.sites[site] = self.sites.get(site, 0) + 1
            self.last_error = str(e).splitlines()[0][:300]
            if first:  # one warning per site, not per tick
                print(f"[perf] transfer audit: implicit transfer on an "
                      f"audited hot path at {site}: {self.last_error}",
                      flush=True)
            with jax.transfer_guard("allow"):
                return fn(*args, **kwargs)


# ---------------------------------------------------------------------------
# host/device memory watermarks
# ---------------------------------------------------------------------------

def host_rss_bytes() -> Optional[int]:
    """Current resident set size of this process (Linux /proc)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return None


def host_peak_rss_bytes() -> Optional[int]:
    """Lifetime peak RSS (getrusage; ru_maxrss is KiB on Linux)."""
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:  # noqa: BLE001 - exotic hosts
        return None


def device_memory_watermarks() -> Dict[str, float]:
    """``live``/``peak`` bytes from the first device's
    ``memory_stats()`` — present on TPU backends, None on CPU (where
    the host RSS rows carry the watermark instead)."""
    out: Dict[str, float] = {}
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 - no backend yet / no stats
        return out
    if not stats:
        return out
    live = stats.get("bytes_in_use")
    peak = stats.get("peak_bytes_in_use")
    if live is not None:
        out["device_live_bytes"] = float(live)
    if peak is not None:
        out["device_peak_bytes"] = float(peak)
    return out


# ---------------------------------------------------------------------------
# the monitor
# ---------------------------------------------------------------------------

class PerfMonitor:
    """Per-role performance accounting.

    Hot-path surface is two integer adds (``note_updates`` /
    ``note_frames``) that early-out when the plane is disabled; all
    derivation — window rates, MFU, watermarks, retrace checks — runs
    in ``drain()`` on the role's normal metrics cadence and returns a
    flat ``{tag: value}`` dict for the role's MetricsWriter.  The last
    drained dict is kept for the registry's ``status_snapshot`` so the
    T_STATUS health plane serves fresh values without re-deriving."""

    def __init__(self, name: str, params=None, prefix: Optional[str] = None):
        self.name = name
        # "actor-3" -> tag prefix "actor": tags stay fleet-comparable,
        # rows are process-attributed by the writer's role stamp
        self.prefix = prefix if prefix is not None else name.split("-")[0]
        self.params = resolve(params)
        self.enabled = self.params.enabled
        self.flops_per_update: Optional[float] = None
        self.flops_per_frame: Optional[float] = None
        # the role's matmul compute dtype, scaling the auto-resolved MFU
        # denominator (fp32 runs score against the fp32 peak, not the
        # bf16 one); set by the learner from config.compute_dtype BEFORE
        # the first drain.  An explicit peak_flops knob is never scaled
        # — the operator named the denominator.
        self.compute_dtype: Optional[str] = None
        self._peak: Optional[float] = None
        self._peak_resolved = False
        self.retraces = RetraceDetector()
        self.audit = (TransferAudit()
                      if self.enabled and self.params.transfer_audit
                      else None)
        self._updates = 0
        self._frames = 0
        self._gauges: Dict[str, float] = {}
        self._anchor: Optional[tuple] = None  # (mono, updates, frames)
        self._flops_reported = False
        self.last: Dict[str, float] = {}

    # -- compile-time capture ------------------------------------------------

    def capture_flops(self, lower_thunk: Callable[[], Any]
                      ) -> Optional[float]:
        """AOT-compile the hot program once (``lower_thunk`` returns a
        ``Lowered``) and keep its per-update FLOPs.  Best-effort: a
        backend that cannot lower/compile/cost-analyse leaves MFU off
        rather than failing the role."""
        if not self.enabled:
            return None
        try:
            self.flops_per_update = flops_of_compiled(
                lower_thunk().compile())
        except Exception as e:  # noqa: BLE001
            print(f"[perf] {self.name}: flops capture failed ({e!r}); "
                  f"mfu reporting disabled", flush=True)
            self.flops_per_update = None
        return self.flops_per_update

    def capture_frame_flops(self, lower_thunk: Callable[[], Any],
                            frames_per_call: int) -> Optional[float]:
        """Frame-denominated twin of ``capture_flops`` for the actor
        plane: keep the fused rollout's per-env-frame FLOPs, so the
        device actor's MFU rides the SAME frames counter the
        env-frames/s rate uses (ISSUE 7: the rollout program's
        utilization is a live-plane read, not a bench artifact).

        Cost analysis is read off the LOWERED program when the backend
        supports it (lowering is tracing-only — no XLA compile), so
        the rollout is not compiled twice at actor startup (once for
        flops, once for the first real dispatch); backends without
        lowered-stage analysis fall back to the AOT compile."""
        if not self.enabled:
            return None
        try:
            lowered = lower_thunk()
            total = flops_of_compiled(lowered)
            if total is None:
                total = flops_of_compiled(lowered.compile())
            self.flops_per_frame = (total / frames_per_call
                                    if total else None)
        except Exception as e:  # noqa: BLE001
            print(f"[perf] {self.name}: frame-flops capture failed "
                  f"({e!r}); rollout mfu reporting disabled", flush=True)
            self.flops_per_frame = None
        return self.flops_per_frame

    def register_jit(self, name: str,
                     size_fn: Optional[Callable[[], Optional[int]]]) -> None:
        if self.enabled and self.params.retrace_detector:
            self.retraces.register(name, size_fn)

    # -- hot path ------------------------------------------------------------

    def note_updates(self, n: int) -> None:
        if self.enabled:
            self._updates += n

    def note_frames(self, n: int) -> None:
        if self.enabled:
            self._frames += n

    def set_gauge(self, tag: str, value: float) -> None:
        if self.enabled:
            self._gauges[tag] = float(value)

    # -- cadence -------------------------------------------------------------

    def set_compute_dtype(self, dtype: Optional[str]) -> None:
        """Pin the dtype the MFU denominator scales by (idempotent
        until the first drain resolves the peak)."""
        if self.enabled and not self._peak_resolved:
            self.compute_dtype = str(dtype) if dtype is not None else None

    def _peak_flops(self) -> Optional[float]:
        if not self._peak_resolved:
            self._peak_resolved = True
            if self.params.peak_flops > 0:
                self._peak = float(self.params.peak_flops)
            else:
                try:
                    import jax

                    self._peak = peak_flops_of(jax.devices()[0],
                                               self.compute_dtype)
                except Exception:  # noqa: BLE001
                    self._peak = None
        return self._peak

    def drain(self, step: int = 0, now: Optional[float] = None
              ) -> Dict[str, float]:
        """Window rates + derived metrics since the previous drain, as
        ``{tag: value}``.  The first call anchors the window (and the
        retrace warmup) and returns only non-rate rows."""
        if not self.enabled:
            return {}
        if now is None:
            now = time.monotonic()
        out: Dict[str, float] = {}
        anchor = self._anchor
        self._anchor = (now, self._updates, self._frames)
        if anchor is not None and now > anchor[0]:
            dt = now - anchor[0]
            d_up = self._updates - anchor[1]
            d_fr = self._frames - anchor[2]
            # achieved FLOP/s SUMS the update- and frame-denominated
            # programs: a monitor carrying both (the co-located Anakin
            # loop, whose learner dispatches and rollout dispatches
            # share one chip) reports the chip's total utilization, not
            # whichever branch ran last
            achieved = 0.0
            if self._updates or d_up:
                ups = d_up / dt
                out[f"{self.prefix}/updates_per_s"] = ups
                if self.flops_per_update:
                    achieved += ups * self.flops_per_update
            if self._frames or d_fr:
                fps = d_fr / dt
                out[f"{self.prefix}/env_frames_per_s"] = fps
                if self.flops_per_frame:
                    achieved += fps * self.flops_per_frame
            if achieved:
                out[f"{self.prefix}/achieved_flops_per_s"] = achieved
                peak = self._peak_flops()
                if peak:
                    out[f"{self.prefix}/mfu"] = achieved / peak
        if self.flops_per_update and not self._flops_reported:
            self._flops_reported = True
            out[f"{self.prefix}/flops_per_update"] = self.flops_per_update
        out.update(self._gauges)
        if self.params.memory_watermarks:
            rss = host_rss_bytes()
            if rss is not None:
                out[f"perf/{self.prefix}/rss_bytes"] = float(rss)
            peak_rss = host_peak_rss_bytes()
            if peak_rss is not None:
                out[f"perf/{self.prefix}/rss_peak_bytes"] = float(peak_rss)
            for k, v in device_memory_watermarks().items():
                out[f"perf/{self.prefix}/{k}"] = v
        if self.params.retrace_detector and self.retraces._fns \
                and (self._updates or self._frames):
            # gated on work having happened: the warmup mark must land
            # AFTER the first dispatches compiled (an anchor-only drain
            # before the loop would otherwise read them as retraces)
            fired = self.retraces.check()
            if fired:
                print(f"[perf] {self.name}: post-warmup recompile of "
                      f"{', '.join(fired)} — a shape/dtype leak is "
                      f"paying compile latency on the hot path",
                      flush=True)
            out[f"perf/{self.prefix}/retraces"] = float(
                self.retraces.retraces)
        if self.audit is not None:
            out[f"perf/{self.prefix}/transfers_flagged"] = float(
                self.audit.total)
        self.last = dict(out)
        return out

    def snapshot(self) -> Dict[str, float]:
        """Last drained values plus cumulative counters — the read the
        STATUS health plane serves.  No derivation, no reset: safe from
        any thread at any rate."""
        snap = dict(self.last)
        snap["updates_total"] = float(self._updates)
        snap["frames_total"] = float(self._frames)
        if self.flops_per_update:
            snap[f"{self.prefix}/flops_per_update"] = self.flops_per_update
        if self.flops_per_frame:
            snap[f"{self.prefix}/flops_per_frame"] = self.flops_per_frame
        return snap


# ---------------------------------------------------------------------------
# per-process registry (mirrors utils/tracing.py get_tracer)
# ---------------------------------------------------------------------------

_registry_lock = threading.Lock()
_monitors: Dict[str, PerfMonitor] = {}


def get_monitor(name: str, params=None,
                prefix: Optional[str] = None) -> PerfMonitor:
    with _registry_lock:
        m = _monitors.get(name)
        if m is None:
            m = _monitors[name] = PerfMonitor(name, params=params,
                                              prefix=prefix)
        return m


def status_snapshot() -> Dict[str, Dict[str, float]]:
    """{role: snapshot} for every enabled monitor in this process that
    has seen work — the ``perf`` block of the gateway's T_STATUS."""
    with _registry_lock:
        monitors = list(_monitors.values())
    out = {}
    for m in monitors:
        if m.enabled and (m.last or m._updates or m._frames):
            out[m.name] = m.snapshot()
    return out


def reset() -> None:
    """Drop all registered monitors (test isolation)."""
    with _registry_lock:
        _monitors.clear()


# ---------------------------------------------------------------------------
# on-demand profile windows (the T_PROFILE provider)
# ---------------------------------------------------------------------------

_profile_lock = threading.Lock()
_prewarmed = False


def prewarm_profiler() -> threading.Thread:
    """Warm the XLA profiler's one-time session init on a background
    thread (a throwaway ~50 ms trace into a temp dir).

    Measured on this image: the FIRST ``jax.profiler.start_trace`` of a
    process pays ~20 s of lazy TSL/import work when idle — and over a
    MINUTE when a hot dispatch loop is starving the GIL on a small
    host; every later trace starts in milliseconds even under full
    load.  The fleet topology calls this at startup (perf plane
    enabled only), so the operator's first ``fleet_top --profile``
    answers at window speed instead of minutes into a saturated run.
    Holds the one-window lock while warming: a concurrent T_PROFILE
    gets the explicit busy error, not a nested capture."""
    def _warm() -> None:
        global _prewarmed
        import shutil
        import tempfile

        tmp = tempfile.mkdtemp(prefix="perf_profiler_warm_")
        try:
            run_profile_window(tmp, label="_warmup", seconds=0.05)
            _prewarmed = True
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    t = threading.Thread(target=_warm, name="perf-profiler-warm",
                         daemon=True)
    t.start()
    return t


def run_profile_window(trace_dir: str, label: str = "tprofile",
                       seconds: float = 3.0,
                       max_seconds: float = 30.0) -> Dict[str, Any]:
    """Capture one bounded XLA profiler window of THIS process's device
    activity into ``trace_dir`` and report where it landed.

    Blocks for the (clamped) window — the caller is a gateway serve
    thread with its own connection, so blocking is free concurrency-
    wise.  One window at a time: a second request while one is active
    gets an error reply instead of a nested capture (utils/profiling.
    trace would no-op a nested window anyway; the explicit error tells
    the operator WHY there is no trace)."""
    from pytorch_distributed_tpu.utils import profiling

    try:
        seconds = float(seconds)
    except (TypeError, ValueError):
        return {"error": f"bad seconds value {seconds!r}"}
    seconds = max(0.05, min(seconds, max_seconds))
    if not _profile_lock.acquire(blocking=False):
        return {"error": "a profile window is already active"}
    try:
        with profiling.trace(str(label), log_dir=trace_dir) as path:
            if path is None:
                return {"error": "profiler unavailable (a trace is "
                                 "already active in this process)"}
            time.sleep(seconds)
        return {"trace_dir": path, "seconds": seconds}
    except Exception as e:  # noqa: BLE001 - report, never kill the serve
        return {"error": f"profile capture failed: {e!r}"}
    finally:
        _profile_lock.release()
