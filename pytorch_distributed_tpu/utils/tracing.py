"""Distributed request tracing for the actor→learner experience pipeline.

The reference repo has no tracing at all — only stdout banners and
TensorBoard scalars (SURVEY.md §5) — so when an experience chunk takes
seconds to reach the learner there is no way to say WHERE it waited: the
actor's feed buffer, the DCN wire, the ingest queue, or the learner's
drain.  This module is the answer the Podracer/TorchBeast-style stacks
carry as a first-class feature: every chunk that leaves an actor is
stamped with a **trace id** minted at the originating role, the id rides
every hop (spawn queue pickling and the DCN wire alike — parallel/dcn.py
``encode_chunk`` carries it as a savez column, no pickle), and each role
records a **span** against it:

    enqueue  — actor-side: the feeder's put (blocking = backpressure)
    gateway  — DCN only: actor flush → gateway receipt (wire + stall)
    feed     — gateway/queue → the replay drain on the learner host
    sample   — learner: one minibatch draw
    learn    — learner: one train-step dispatch

Span durations accumulate into per-span reservoirs that the owning role
flushes to the metrics stream on its normal cadence as **histogram rows**
(p50/p95/max via utils/metrics.py ``MetricsWriter.histogram`` — stalls
live in the tail, means average them away) plus sampled per-span JSONL
rows carrying the trace id, so one end-to-end trace
(actor→gateway→feeder→learner sharing an id) is greppable from
``scalars.jsonl``.  Cross-host hops use wall clocks on both ends; the
latency is only as honest as the hosts' clock sync (same caveat every
distributed tracer carries).

Knobs (env, read at tracer construction):

- ``TPU_APEX_TRACE=0``       — disable the plane entirely: chunks ship
  as plain lists (no id mint, no wire columns) and tracers record
  nothing (the default is on: the per-event cost is one lock + a few
  dict ops).
- ``TPU_APEX_TRACE_SAMPLE``  — fraction of trace-carrying span events
  emitted as individual JSONL rows (default 1.0; histogram rows count
  every event regardless, reservoir-sampling the duration values past
  ``Tracer.MAX_SAMPLES`` per flush window so late-window stalls still
  reach the percentiles).

Spans also mirror into the role's flight recorder ring when one exists
(utils/flight_recorder.py), so a post-crash blackbox dump shows the last
traffic the role saw.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple


def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("0", "false", "off", "no", "")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def active() -> bool:
    """Is tracing on in this process?  Gates the chunk-wrap fast path
    (memory/feeder.py): with ``TPU_APEX_TRACE=0`` chunks stay plain
    lists — no id mint, no wire columns — so the kill switch removes the
    whole per-chunk cost, not just the span recording."""
    return _env_flag("TPU_APEX_TRACE", True)


def mint_trace_id() -> int:
    """A fresh 63-bit trace id.  urandom-based so ids minted on different
    hosts (remote actors) never need coordination to stay distinct."""
    tid = 0
    while not tid:
        tid = int.from_bytes(os.urandom(8), "big") >> 1
    return tid


def format_trace_id(tid: int) -> str:
    return f"{int(tid):016x}"


class TracedChunk(list):
    """A ``[(Transition, priority), ...]`` chunk carrying trace metadata
    across hops.  Subclasses list so every existing consumer —
    ``pop_chunks``'s extend, the gateway's ``put_chunk``, direct feeds —
    handles it unchanged; the spawn queue's pickling preserves the
    attributes via ``__reduce__``."""

    __slots__ = ("trace_id", "born")

    def __init__(self, items=(), trace_id: Optional[int] = None,
                 born: Optional[float] = None):
        super().__init__(items)
        self.trace_id = mint_trace_id() if trace_id is None else int(trace_id)
        self.born = time.time() if born is None else float(born)

    def __reduce__(self):
        return (TracedChunk, (list(self), self.trace_id, self.born))


# most recent trace id observed by ANY tracer in this process — the
# learner's sample/learn spans attach to it so an end-to-end trace closes
# without threading chunk identity through the jitted hot loop.  A plain
# int assignment (GIL-atomic) on purpose: this is "latest traffic", not
# an exact join, and the hot loop must not take a lock for it.
_last_trace_id = 0


def set_current_trace(tid: int) -> None:
    global _last_trace_id
    _last_trace_id = int(tid)


def current_trace() -> int:
    return _last_trace_id


class Tracer:
    """Per-role span recorder: bounded duration reservoirs (histogram
    feed) plus sampled per-event rows (trace-id feed).  Thread-safe —
    the gateway shares one across its serve threads."""

    MAX_SAMPLES = 4096   # per-span reservoir cap between flushes
    MAX_ROWS = 2048      # per-event row cap between flushes

    def __init__(self, role: str, enabled: Optional[bool] = None,
                 sample: Optional[float] = None):
        self.role = role
        self.enabled = (_env_flag("TPU_APEX_TRACE", True)
                        if enabled is None else enabled)
        self.sample = (_env_float("TPU_APEX_TRACE_SAMPLE", 1.0)
                       if sample is None else sample)
        self._lock = threading.Lock()
        self._hist: Dict[str, List[float]] = {}
        self._count: Dict[str, int] = {}
        self._rows: List[dict] = []
        self._events = 0
        self.dropped_rows = 0  # rows lost to MAX_ROWS (observability)

    # -- recording -----------------------------------------------------------

    def record(self, span: str, dur_ms: float, trace_id: int = 0,
               wall: Optional[float] = None) -> None:
        if not self.enabled:
            return
        if trace_id:
            set_current_trace(trace_id)
        wall = time.time() if wall is None else wall
        with self._lock:
            vals = self._hist.setdefault(span, [])
            n = self._count.get(span, 0) + 1
            self._count[span] = n
            if len(vals) < self.MAX_SAMPLES:
                vals.append(float(dur_ms))
            else:
                # reservoir sampling (Algorithm R): past the cap every
                # event of the window keeps an equal chance of being in
                # the sample, so a stall LATE in a busy window still
                # reaches the percentiles — first-N-kept would blind the
                # tail forensics exactly when traffic is heaviest
                j = random.randrange(n)
                if j < self.MAX_SAMPLES:
                    vals[j] = float(dur_ms)
            self._events += 1
            if trace_id and self._take_sample():
                if len(self._rows) < self.MAX_ROWS:
                    self._rows.append({
                        "span": span, "role": self.role,
                        "trace_id": format_trace_id(trace_id),
                        "dur_ms": round(float(dur_ms), 3), "wall": wall,
                    })
                else:
                    self.dropped_rows += 1

    def _take_sample(self) -> bool:
        # deterministic 1-in-N (no RNG in the hot path; reproducible)
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        period = max(1, int(round(1.0 / self.sample)))
        return self._events % period == 1 or period == 1

    def record_hop(self, span: str, born_wall: float,
                   trace_id: int = 0) -> None:
        """A cross-hop latency measured against the chunk's birth wall
        clock (clamped at 0: cross-host clock skew must not produce
        negative latencies that wreck the histogram floor)."""
        self.record(span, max(0.0, (time.time() - float(born_wall)) * 1e3),
                    trace_id=trace_id)

    @contextlib.contextmanager
    def span(self, name: str, trace_id: int = 0) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, (time.perf_counter() - t0) * 1e3,
                        trace_id=trace_id)

    # -- draining ------------------------------------------------------------

    def drain(self) -> Tuple[Dict[str, List[float]], List[dict],
                             Dict[str, int]]:
        """Return-and-reset (histogram reservoirs, per-event rows, true
        per-span event counts — the reservoirs cap at MAX_SAMPLES but the
        counts never do)."""
        with self._lock:
            hist, self._hist = self._hist, {}
            rows, self._rows = self._rows, []
            counts, self._count = self._count, {}
            return hist, rows, counts

    def flush_to(self, writer, step: int) -> None:
        """Emit everything drained into a utils/metrics.MetricsWriter:
        one histogram row per span (``trace/<role>/<span>_ms``) plus the
        sampled per-event trace rows."""
        hist, rows, counts = self.drain()
        for span, vals in hist.items():
            writer.histogram(f"trace/{self.role}/{span}_ms", vals,
                             step=step, count=counts.get(span))
        for r in rows:
            writer.span(r["span"], role=r["role"], trace_id=r["trace_id"],
                        dur_ms=r["dur_ms"], wall=r["wall"], step=step)


# ---------------------------------------------------------------------------
# per-process registry — one tracer per role name, shared by the role's
# components (e.g. the gateway's serve threads, an actor's feeder +
# harness) so their spans aggregate into one histogram set
# ---------------------------------------------------------------------------

_registry_lock = threading.Lock()
_tracers: Dict[str, Tracer] = {}


def get_tracer(role: str) -> Tracer:
    with _registry_lock:
        t = _tracers.get(role)
        if t is None:
            t = _tracers[role] = Tracer(role)
        return t


def all_tracers() -> List[Tracer]:
    with _registry_lock:
        return list(_tracers.values())


def reset() -> None:
    """Drop all registered tracers and the current-trace latch (test
    isolation; production processes never call this)."""
    global _last_trace_id
    with _registry_lock:
        _tracers.clear()
    _last_trace_id = 0
