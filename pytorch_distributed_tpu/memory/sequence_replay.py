"""Sequence replay: contiguous episode segments for recurrent learners.

The reference stores single n-step transitions only; SURVEY.md §5 flags
that the replay layout must not preclude "contiguous episode segments"
for recurrent/R2D2-style training — this module is that layout.  One row
is a fixed-length window of an episode:

    obs[T+1], action[T], reward[T], terminal[T], mask[T], (c0, h0)

where ``mask`` marks valid steps (episode tails are zero-padded) and
``(c0, h0)`` is the actor's recorded LSTM state at the segment's first
step — the "stored state" strategy of R2D2 (Kapturowski et al. 2019),
which the learner refreshes with a burn-in prefix
(ops/sequence_losses.py).

Segments overlap by ``overlap`` steps (R2D2 uses length 80, overlap 40) so
every step appears in ~T/overlap windows.  Sampling is proportional over
per-sequence priorities (eta-blended max/mean |TD|, written back by the
learner) with new rows at the running max — uniform when alpha == 0.
Single-owner like the host PER buffer: actors stream segments through a
QueueOwner (memory/feeder.py); only the learner touches the arrays.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import numpy as np


class Segment(NamedTuple):
    """One replay row (unbatched).  ``prov`` is the OPTIONAL data-plane
    provenance vector of the segment's FIRST step (ISSUE 8 —
    utils/experience.make_prov); storage keeps it in a sidecar array,
    never in the segment schema proper (iterate the replay's ``_FIELDS``
    when you mean the stored columns)."""

    obs: np.ndarray        # (T+1, *state_shape)
    action: np.ndarray     # (T,) int32
    reward: np.ndarray     # (T,) float32
    terminal: np.ndarray   # (T,) float32
    mask: np.ndarray       # (T,) float32, 1 = valid step
    c0: np.ndarray         # (lstm_dim,) float32
    h0: np.ndarray         # (lstm_dim,) float32
    prov: Optional[np.ndarray] = None  # (4,) int64 provenance, or None


class SegmentBatch(NamedTuple):
    """A sampled minibatch of segments (leading batch dim everywhere)."""

    obs: np.ndarray        # (B, T+1, *state_shape)
    action: np.ndarray
    reward: np.ndarray
    terminal: np.ndarray
    mask: np.ndarray
    c0: np.ndarray         # (B, lstm_dim)
    h0: np.ndarray
    weight: np.ndarray     # (B,) importance weights
    index: np.ndarray      # (B,) rows, for priority write-back


class _BuilderStep(NamedTuple):
    """One pushed actor step held by SegmentBuilder before emit.  Named
    fields on purpose (apexlint schema-contract): assembly used to
    positional-index raw 8-tuples, which silently misread every row the
    day the prov column landed at index 7."""

    obs: np.ndarray
    action: int
    reward: float
    terminal: bool
    next_obs: np.ndarray
    c: np.ndarray
    h: np.ndarray
    prov: Optional[np.ndarray]


class SegmentBuilder:
    """Per-env online segment assembly with overlap.

    ``push`` receives one acted step — the observation the actor saw, the
    LSTM carry it held BEFORE acting (the state to store for this step),
    and the step outcome — and returns zero or more finished Segments.
    Episode ends flush a padded+masked tail and reset the stream (overlap
    never crosses episodes).

    ``pack_frames=C`` (image obs only) stores segments FRAME-PACKED:
    consecutive C-stacked observations share C-1 frames, so a stacked
    segment ships every pixel C times.  Packed, ``obs`` is the
    de-duplicated frame sequence (T+C, H, W) — stack t is frames
    [t, t+C) — cutting the actor->learner queue bytes, host RAM, and
    the per-update host->device transfer ~C-fold; the learner
    reconstructs stacks on device (ops/sequence_losses.py
    unpack_frame_stacks).  Motivation: the R2D2 pixel learner measured
    H2D-bound at ~1 update/s with stacked 16x17-stack batches through
    the ~50 MB/s tunnel (2026-07-31)."""

    def __init__(self, seq_len: int, overlap: int,
                 state_dtype=np.float32, pack_frames: int = 0):
        assert 0 <= overlap < seq_len, (overlap, seq_len)
        self.T = seq_len
        self.overlap = overlap
        self.state_dtype = np.dtype(state_dtype)
        self.pack_frames = int(pack_frames)
        self._checked_sliding = False  # one-time invariant check on emit
        self._steps: List[_BuilderStep] = []

    def push(self, obs, action, reward, terminal, next_obs,
             carry: Tuple[np.ndarray, np.ndarray],
             episode_end: Optional[bool] = None,
             prov=None) -> List[Segment]:
        """``terminal`` is what the learner bootstraps on (False for
        time-limit truncations, which must bootstrap through);
        ``episode_end`` (default: terminal) is what ends the stream — a
        truncated episode ends the segment without marking a death.
        ``prov`` is this step's provenance vector (minted at action
        time); an emitted segment carries its FIRST step's provenance,
        overlap included — the retained steps keep the vectors they were
        pushed with."""
        if episode_end is None:
            episode_end = bool(terminal)
        c, h = carry
        self._steps.append(_BuilderStep(
            obs=np.asarray(obs), action=int(action),
            reward=float(reward), terminal=bool(terminal),
            next_obs=np.asarray(next_obs),
            c=np.asarray(c, np.float32).copy(),
            h=np.asarray(h, np.float32).copy(), prov=prov))
        out: List[Segment] = []
        if episode_end:
            out.append(self._emit(len(self._steps)))
            self._steps = []  # no overlap across episode boundaries
        elif len(self._steps) == self.T:
            out.append(self._emit(self.T))
            keep = self.overlap
            self._steps = self._steps[len(self._steps) - keep:] if keep \
                else []
        return out

    def _emit(self, n: int) -> Segment:
        T = self.T
        steps = self._steps[:n]
        obs0 = steps[0].obs
        action = np.zeros(T, np.int32)
        reward = np.zeros(T, np.float32)
        terminal = np.zeros(T, np.float32)
        mask = np.zeros(T, np.float32)
        for t, s in enumerate(steps):
            action[t] = s.action
            reward[t] = s.reward
            terminal[t] = float(s.terminal)
            mask[t] = 1.0
        if self.pack_frames:
            obs = self._emit_packed(steps, n)
        else:
            obs = np.zeros((T + 1, *obs0.shape), dtype=self.state_dtype)
            for t, s in enumerate(steps):
                obs[t] = s.obs
            obs[n] = steps[n - 1].next_obs  # bootstrap observation
            # pad slots keep the bootstrap obs so scans stay shape-static
            for t in range(n + 1, T + 1):
                obs[t] = obs[n]
        return Segment(obs=obs, action=action, reward=reward,
                       terminal=terminal, mask=mask,
                       c0=steps[0].c, h0=steps[0].h, prov=steps[0].prov)

    def _emit_packed(self, steps, n: int) -> np.ndarray:
        """De-duplicated frame sequence (T+C, H, W): frames [0, C) are
        step 0's full stack, frame C-1+t is step t's newest frame, frame
        C-1+n the bootstrap's newest; pad frames repeat the bootstrap
        frame (padded positions are mask=0 and the n-step bootstrap index
        clamps to <= n_valid, so reconstructed pad stacks are never
        read)."""
        C, T = self.pack_frames, self.T
        obs0 = steps[0].obs
        assert obs0.shape[0] == C, (
            f"pack_frames={C} but stacked obs has {obs0.shape[0]} channels")
        if not self._checked_sliding and n >= 2:
            # Packing is only sound for sliding-window stacks (each push's
            # stack = previous stack shifted one frame).  A non-sliding
            # env would pass the shape assert yet reconstruct corrupted
            # channels — check the invariant once, on the first real
            # segment, at negligible cost.
            self._checked_sliding = True
            assert np.array_equal(steps[1].obs[:-1], steps[0].obs[1:]), (
                "pack_frames set but observations are not a sliding "
                "frame-stack (obs[t][:-1] != obs[t-1][1:]); disable "
                "packing for this env")
            # next_obs must slide from obs the same way: the bootstrap
            # frame is taken from next_obs[-1] (frames[C-1+n] below), so
            # an env wrapper handing back e.g. the post-reset observation
            # as next_obs would silently store a wrong bootstrap frame at
            # truncation-style segment ends (advisor finding, round 3)
            assert np.array_equal(steps[0].next_obs[:-1],
                                  steps[0].obs[1:]), (
                "pack_frames set but next_obs does not slide from obs "
                "(next_obs[:-1] != obs[1:]); disable packing for this env")
        frames = np.zeros((T + C, *obs0.shape[1:]), dtype=self.state_dtype)
        frames[:C] = obs0
        for t in range(1, n):
            frames[C - 1 + t] = steps[t].obs[-1]
        frames[C - 1 + n] = steps[n - 1].next_obs[-1]  # bootstrap frame
        for t in range(n + 1, T + 1):
            frames[C - 1 + t] = frames[C - 1 + n]
        return frames

    def reset(self) -> None:
        self._steps = []


class SequenceReplay:
    """Ring of segments with proportional prioritized sampling.

    ``capacity`` counts SEGMENTS (the factory divides the transition-count
    memory_size by the segment length)."""

    def __init__(self, capacity: int, seq_len: int,
                 state_shape: Tuple[int, ...], lstm_dim: int,
                 state_dtype=np.float32,
                 priority_exponent: float = 0.9,
                 importance_weight: float = 0.6,
                 importance_anneal_steps: int = 500000,
                 pack_frames: int = 0):
        self.capacity = capacity
        self.T = seq_len
        self.lstm_dim = lstm_dim
        self.alpha = priority_exponent
        self.beta0 = importance_weight
        self.beta_steps = importance_anneal_steps
        self.pack_frames = int(pack_frames)
        S = tuple(state_shape)
        if self.pack_frames:
            # frame-packed rows: (T+C, H, W) — see SegmentBuilder
            assert S[0] == self.pack_frames, (S, pack_frames)
            obs_shape = (seq_len + self.pack_frames, *S[1:])
        else:
            obs_shape = (seq_len + 1, *S)
        self.obs = np.zeros((capacity, *obs_shape), dtype=state_dtype)
        self.action = np.zeros((capacity, seq_len), np.int32)
        self.reward = np.zeros((capacity, seq_len), np.float32)
        self.terminal = np.zeros((capacity, seq_len), np.float32)
        self.mask = np.zeros((capacity, seq_len), np.float32)
        self.c0 = np.zeros((capacity, lstm_dim), np.float32)
        self.h0 = np.zeros((capacity, lstm_dim), np.float32)
        # provenance sidecar (ISSUE 8): first-step provenance per
        # segment, -1 rows = unknown (legacy/synthetic feeds)
        self.prov = np.full((capacity, 4), -1, np.int64)
        self.priority = np.zeros(capacity, np.float64)  # p^alpha, 0 = empty
        self.max_priority = 1.0
        self.pos = 0
        self.full = False
        self.samples_drawn = 0

    @property
    def size(self) -> int:
        return self.capacity if self.full else self.pos

    def feed(self, segment: Segment, priority: Optional[float] = None
             ) -> None:
        i = self.pos
        self.obs[i] = segment.obs
        self.action[i] = segment.action
        self.reward[i] = segment.reward
        self.terminal[i] = segment.terminal
        self.mask[i] = segment.mask
        self.c0[i] = segment.c0
        self.h0[i] = segment.h0
        self.prov[i] = (-1 if getattr(segment, "prov", None) is None
                        else segment.prov)
        if priority is None:
            self.priority[i] = self.max_priority
        else:
            p = (abs(float(priority)) + 1e-6) ** self.alpha
            self.priority[i] = p
            self.max_priority = max(self.max_priority, p)
        self.pos += 1
        if self.pos == self.capacity:
            self.pos = 0
            self.full = True

    def beta(self) -> float:
        frac = min(1.0, self.samples_drawn / max(1, self.beta_steps))
        return self.beta0 + (1.0 - self.beta0) * frac

    def sample(self, batch_size: int, rng: np.random.Generator
               ) -> SegmentBatch:
        n = self.size
        assert n > 0, "sample from empty sequence replay"
        if self.alpha == 0.0:
            idx = rng.integers(0, n, size=batch_size)
            weights = np.ones(batch_size, np.float32)
        else:
            p = self.priority[:n]
            total = p.sum()
            cdf = np.cumsum(p)
            u = rng.random(batch_size) * total
            idx = np.minimum(np.searchsorted(cdf, u, side="right"), n - 1)
            probs = p[idx] / max(total, 1e-12)
            beta = self.beta()
            weights = (n * np.maximum(probs, 1e-12)) ** (-beta)
            min_p = p[p > 0].min() / max(total, 1e-12)
            weights /= max((n * max(min_p, 1e-12)) ** (-beta), 1e-12)
            weights = weights.astype(np.float32)
        self.samples_drawn += batch_size
        return SegmentBatch(
            obs=self.obs[idx], action=self.action[idx],
            reward=self.reward[idx], terminal=self.terminal[idx],
            mask=self.mask[idx], c0=self.c0[idx], h0=self.h0[idx],
            weight=weights, index=idx.astype(np.int32))

    def priority_leaves(self) -> np.ndarray:
        """The valid rows' priorities (p^alpha) — the priority X-ray's
        input (utils/health.priority_xray)."""
        return self.priority[:self.size]

    def provenance_of(self, indices: np.ndarray) -> np.ndarray:
        """(B, 4) int64 provenance of the given rows; -1 rows = unknown
        (the learner's data-plane telemetry masks on ``[:, 0] >= 0``)."""
        return self.prov[np.asarray(indices)]

    def update_priorities(self, indices: np.ndarray,
                          priorities: np.ndarray) -> None:
        """Per-sequence |TD| write-back (eta-blended by the learner)."""
        pr = (np.abs(np.asarray(priorities, np.float64)) + 1e-6) ** self.alpha
        self.priority[np.asarray(indices)] = pr
        if pr.size:
            self.max_priority = max(self.max_priority, float(pr.max()))

    # -- checkpoint (utils/checkpoint.py save_replay/load_replay) -----------

    _FIELDS = ("obs", "action", "reward", "terminal", "mask", "c0", "h0")

    def snapshot(self) -> dict:
        """Valid rows in AGE order (oldest first) + the priority leaves —
        the same keys and units as the HBM segment ring
        (memory/device_sequence.py snapshot), so host and device sequence
        planes restore each other's checkpoints: leaves pre-exponentiated
        p^alpha, running max in the shared UNexponentiated base unit."""
        n = self.size
        shift = -self.pos if self.full else 0
        out = {k: np.roll(getattr(self, k), shift, axis=0)[:n].copy()
               for k in self._FIELDS}
        out["prov"] = np.roll(self.prov, shift, axis=0)[:n].copy()
        out["leaf_priority"] = np.roll(self.priority, shift)[:n].copy()
        out["max_priority_base"] = np.float64(
            self.max_priority ** (1.0 / self.alpha) if self.alpha
            else self.max_priority)
        # the exponent the leaves were saved under, so a restoring run
        # with a different alpha converts instead of mixing units (same
        # convention as memory/prioritized.py)
        out["alpha"] = np.float64(self.alpha)
        out["samples_drawn"] = np.int64(self.samples_drawn)
        return out

    def restore(self, data: dict) -> int:
        """Refill from a snapshot (keeps the newest rows that fit);
        returns rows restored."""
        rows = np.asarray(data["reward"])
        n = min(len(rows), self.capacity)
        for k in self._FIELDS:
            getattr(self, k)[:n] = data[k][-n:]
        self.prov[:n] = (np.asarray(data["prov"], np.int64)[-n:]
                         if "prov" in data else -1)
        self.prov[n:] = -1
        if "leaf_priority" in data:
            leaves = np.asarray(data["leaf_priority"], np.float64)[-n:]
            saved_alpha = float(data.get("alpha", self.alpha))
            if saved_alpha != self.alpha and saved_alpha > 0:
                leaves = leaves ** (self.alpha / saved_alpha)
        else:  # priority-less source: everything replays at least once
            leaves = np.full(n, self.max_priority, np.float64)
        self.priority[:n] = leaves
        # rows beyond the restored region must never be drawn (0 = empty)
        self.priority[n:] = 0.0
        self.pos = n % self.capacity
        self.full = n == self.capacity
        base = float(data.get("max_priority_base", 1.0))
        self.max_priority = base ** self.alpha if self.alpha else base
        self.samples_drawn = int(data.get("samples_drawn", 0))
        return n
