"""Replay-memory abstraction.

Equivalent of reference core/memory.py:4-32 — shapes, capacity, and the
circular ``size`` accounting (reference :22-26) — with an explicit
``update_priorities`` hook so PER is part of the interface rather than the
discarded argument it is in the reference
(reference core/memories/shared_memory.py:45).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.utils.experience import Batch, Transition


class Memory:
    def __init__(self, capacity: int, state_shape: Tuple[int, ...],
                 action_shape: Tuple[int, ...] = (),
                 state_dtype: np.dtype = np.uint8,
                 action_dtype: np.dtype = np.int32):
        self.capacity = capacity
        self.state_shape = tuple(state_shape)
        self.action_shape = tuple(action_shape)
        self.state_dtype = np.dtype(state_dtype)
        self.action_dtype = np.dtype(action_dtype)

    @property
    def size(self) -> int:
        raise NotImplementedError

    def feed(self, transition: Transition, priority: Optional[float] = None) -> None:
        raise NotImplementedError

    def sample(self, batch_size: int, rng: np.random.Generator) -> Batch:
        raise NotImplementedError

    def update_priorities(self, indices: np.ndarray,
                          priorities: np.ndarray) -> None:
        """No-op for uniform replay."""
