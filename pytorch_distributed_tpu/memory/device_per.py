"""Prioritized replay resident in HBM, fused into the learner step.

The TPU-native completion of the reference's PER TODO beyond the host
sum-tree (memory/prioritized.py): the host tree exists because CPUs need
O(log N) sampling — a TPU doesn't.  Proportional sampling over a 50k-row
ring is a cumulative sum + inverse-CDF search (``cumsum`` +
``searchsorted``), microseconds of vectorized work that XLA fuses INTO the
training program, along with the importance weights and the |TD| priority
write-back.  One XLA program per learner step does: sample → forward →
backward → Adam → target update → priority scatter — the learner hot loop
never touches the host.

Priorities are stored pre-exponentiated (p_i = (|td|+eps)^alpha) so the
sampling pass needs no pow; new rows enter at the running max priority so
everything is replayed at least once (Ape-X standard).  Importance weights
are normalised by the max weight over valid rows (min-probability row),
annealed by beta supplied per call.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.memory.device_replay import (
    DeviceReplay, ring_write, ring_write_masked, round_capacity,
)
from pytorch_distributed_tpu.utils.experience import (
    REPLAY_FIELDS, Batch, Transition,
)

# single-owner declaration (apexlint): the masked PER scatter may only
# be composed into programs by the replay planes themselves and the
# fused rollout that receives it as ``ring_write_fn``
# (models/policies.build_fused_rollout, wired by agents/anakin.py)
__apex_fn_owners__ = {
    "per_write_masked": ("memory.", "models.policies", "agents.anakin"),
}


class PerReplayState(NamedTuple):
    state0: jax.Array
    action: jax.Array
    reward: jax.Array
    gamma_n: jax.Array
    state1: jax.Array
    terminal1: jax.Array
    prov: jax.Array          # (N, 4) int32 provenance columns; -1 = unknown
    priority: jax.Array      # (N,) f32, pre-exponentiated p^alpha; 0 = empty
    max_priority: jax.Array  # () f32, running max of p^alpha
    pos: jax.Array           # int32 write cursor
    fill: jax.Array          # int32 valid rows


def per_feed(state: PerReplayState, chunk: Transition,
             capacity: int) -> PerReplayState:
    """Ingest a chunk at the cursor (shared ring write, device_replay.py
    ring_write); new rows take the running max priority."""
    new, idx = ring_write(state, chunk, capacity)
    return new._replace(priority=new.priority.at[idx].set(new.max_priority))


def per_write_masked(state: PerReplayState, chunk: Transition, valid,
                     capacity: int):
    """Masked-scatter twin of ``per_feed`` for in-graph ingest
    (device_replay.ring_write_masked semantics): only the ``valid``
    rows take slots, and every written slot enters at the RUNNING MAX
    priority — the same everything-replayed-at-least-once contract the
    queue ingest path applies, so the co-located Anakin scatter and
    the split-process drain produce bit-identical PER rings.  Returns
    ``(state', n_written)``."""
    new, total = ring_write_masked(state, chunk, valid, capacity)
    # same drop-indexing as the field scatter: invalid rows point at
    # ``capacity`` (out of bounds) and are dropped branch-free
    offs = jnp.cumsum(valid.astype(jnp.int32)) - 1
    idx = jnp.where(valid, (state.pos + offs) % capacity, capacity)
    return new._replace(
        priority=new.priority.at[idx].set(new.max_priority,
                                          mode="drop")), total


def per_sample(state: PerReplayState, key: jax.Array, batch_size: int,
               beta: jax.Array, sample_fn=None) -> Batch:
    """Proportional sample + IS weights, all on device.

    ``sample_fn(priority, key, batch_size) -> (idx, probs)`` overrides the
    index draw — the hook the Pallas hierarchical sampler
    (ops/pallas_sampling.py) plugs into on unsharded TPU rings; None keeps
    the flat cumsum+searchsorted XLA scheme."""
    p = state.priority  # empty rows hold 0 and can never be drawn
    if sample_fn is not None:
        idx, probs = sample_fn(p, key, batch_size)
        total = jnp.sum(p)
    else:
        cdf = jnp.cumsum(p)
        total = cdf[-1]  # one O(N) pass serves both u-scaling and probs
        u = jax.random.uniform(key, (batch_size,)) * total
        idx = jnp.clip(jnp.searchsorted(cdf, u, side="right"),
                       0, state.priority.shape[0] - 1).astype(jnp.int32)
        probs = p[idx] / jnp.maximum(total, 1e-12)
    fill = jnp.maximum(state.fill.astype(jnp.float32), 1.0)
    weights = (fill * jnp.maximum(probs, 1e-12)) ** (-beta)
    # max weight = weight of the min-probability VALID row
    min_p = jnp.min(jnp.where(p > 0, p, jnp.inf)) / jnp.maximum(total, 1e-12)
    max_w = (fill * jnp.maximum(min_p, 1e-12)) ** (-beta)
    weights = weights / jnp.maximum(max_w, 1e-12)
    return Batch(
        state0=state.state0[idx],
        action=state.action[idx],
        reward=state.reward[idx],
        gamma_n=state.gamma_n[idx],
        state1=state.state1[idx],
        terminal1=state.terminal1[idx],
        weight=weights.astype(jnp.float32),
        index=idx,
    )


PRIORITY_XRAY_LOG10_LO = -6.0   # log10 bucket floor (p^alpha units)
PRIORITY_XRAY_LOG10_HI = 3.0    # log10 bucket ceiling


def priority_xray_device(state: PerReplayState, bins: int = 16):
    """In-jit priority X-ray over the HBM PER leaves (ISSUE 8): a
    log10-bucketed histogram of the non-empty leaves plus the
    effective sample size ``(sum p)^2 / sum p^2`` — the distribution
    shape the AnomalyDetector needs instead of a bare mass ratio, at
    the cost of ONE small D2H (bins + 3 scalars) per stats cadence.
    Bucket edges are the fixed [10^-6, 10^3) decade grid shared with
    the host X-ray (utils/health.priority_xray), so ``fleet_top``
    renders either identically.  Jit with ``static_argnames='bins'``.

    Returns ``(counts[bins] int32, ess, rows, mass)``."""
    p = state.priority
    valid = p > 0
    rows = jnp.sum(valid.astype(jnp.int32))
    s1 = jnp.sum(jnp.where(valid, p, 0.0))
    s2 = jnp.sum(jnp.where(valid, p * p, 0.0))
    ess = jnp.where(s2 > 0, s1 * s1 / jnp.maximum(s2, 1e-30), 0.0)
    logp = jnp.log10(jnp.maximum(p, 10.0 ** PRIORITY_XRAY_LOG10_LO))
    t = (logp - PRIORITY_XRAY_LOG10_LO) / (
        PRIORITY_XRAY_LOG10_HI - PRIORITY_XRAY_LOG10_LO)
    b = jnp.clip((t * bins).astype(jnp.int32), 0, bins - 1)
    counts = jnp.zeros((bins,), jnp.int32).at[
        jnp.where(valid, b, bins)].add(1, mode="drop")
    return counts, ess, rows, s1


def per_update_priorities(state: PerReplayState, idx: jax.Array,
                          td_abs: jax.Array, alpha: float,
                          epsilon: float = 1e-6) -> PerReplayState:
    """|TD| write-back (pre-exponentiated) + running-max maintenance."""
    pr = (jnp.abs(td_abs) + epsilon) ** alpha
    return state._replace(
        priority=state.priority.at[idx].set(pr.astype(jnp.float32)),
        max_priority=jnp.maximum(state.max_priority, jnp.max(pr)),
    )


# one jitted write-back program shared by every caller of the grouped
# apply below (alpha is static: one value per run, one compile)
_writeback_jit = jax.jit(per_update_priorities,
                         static_argnames=("alpha", "epsilon"))


def per_apply_writeback_groups(state: PerReplayState, groups,
                               alpha: float) -> PerReplayState:
    """Apply an ORDERED list of ``(idx, td_abs)`` write-back groups
    sequentially — the ISSUE-15 merged-priority application.  The
    replica plane's round reply carries every surviving contributor's
    |TD| write-back (ascending replica order, then out-of-round
    arrivals), and every replica applies the SAME groups in the SAME
    order through this function, so the N local rings remain one
    logical priority plane bit-for-bit.

    Sequential jitted scatters on purpose, not one fused scatter:
    XLA's duplicate-index ``.set`` order within a single scatter is
    unspecified, and cross-group index collisions must resolve exactly
    last-group-wins for the solo-parity oracle to hold."""
    for idx, td in groups:
        state = _writeback_jit(state,
                               jnp.asarray(idx, jnp.int32),
                               jnp.asarray(td, jnp.float32),
                               alpha=alpha)
    return state


class DevicePerReplay(DeviceReplay):
    """Stateful wrapper owning the HBM PER ring (learner process only):
    the uniform ring (device_replay.py DeviceReplay) extended with the
    priority vector and the running max.

    ``build_fused_step`` wraps a ``(TrainState, Batch) -> (TrainState,
    metrics, td_abs)`` train step into ``(TrainState, PerReplayState, key,
    beta) -> (TrainState, PerReplayState, metrics)`` — sampling and priority
    write-back fused in.
    """

    def __init__(self, capacity: int, state_shape: Tuple[int, ...],
                 action_shape: Tuple[int, ...] = (),
                 state_dtype=np.uint8, action_dtype=np.int32,
                 priority_exponent: float = 0.6,
                 importance_weight: float = 0.4,
                 importance_anneal_steps: int = 500000,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 channels_last: bool = False):
        self.alpha = priority_exponent
        self.beta0 = importance_weight
        self.beta_steps = importance_anneal_steps
        super().__init__(round_capacity(capacity, mesh, label="device PER"),
                         state_shape, action_shape, state_dtype,
                         action_dtype, mesh=mesh,
                         channels_last=channels_last)

        # Pallas hierarchical sampler on unsharded TPU rings; the flat XLA
        # scheme everywhere else (dp-sharded rings address rows through
        # collectives the kernel can't, and CPU interpret mode is slower
        # than XLA's cumsum).
        self._draw_fn = None
        if (self._row_sharding is None
                and jax.devices()[0].platform == "tpu"):
            from pytorch_distributed_tpu.ops.pallas_sampling import (
                hierarchical_sample,
            )

            self._draw_fn = hierarchical_sample

        feed = functools.partial(per_feed, capacity=self.capacity)
        if self.channels_last:
            from pytorch_distributed_tpu.memory.device_replay import (
                wrap_feed_nhwc,
            )

            feed = wrap_feed_nhwc(feed)
        self._feed_fn = jax.jit(feed, donate_argnums=0)
        self._sample_fn = jax.jit(
            functools.partial(per_sample, sample_fn=self._draw_fn),
            static_argnames="batch_size")

    def _init_state(self) -> PerReplayState:
        base = super()._init_state()
        return PerReplayState(
            *base[:6],
            prov=base.prov,
            priority=self._alloc((self.capacity,), jnp.float32),
            max_priority=self._alloc((), jnp.float32, sharded=False) + 1.0,
            pos=base.pos,
            fill=base.fill,
        )

    def beta(self, step: int) -> float:
        frac = min(1.0, step / max(1, self.beta_steps))
        return self.beta0 + (1.0 - self.beta0) * frac

    def build_fused_step(self, train_step, batch_size: int,
                         donate: bool = True, steps_per_call: int = 1,
                         megabatch: int = 1, megabatch_step=None):
        """Fused sample -> train -> priority write-back; ``steps_per_call``
        sub-steps scan inside one XLA program (keys then shaped (K, 2)),
        amortising dispatch latency like
        device_replay.build_uniform_fused_step — with the priority state
        chained through the scan so each sub-step samples from the
        previous one's updated priorities.

        ``megabatch`` M > 1 (ISSUE 13, with ``megabatch_step`` from
        factory.build_megabatch_train_step) regroups the K sub-steps
        into K/M groups: one WIDENED PER gather draws all M minibatches
        of a group from the GROUP-ENTRY priorities (consuming the same
        M keys the sequential schedule would — within-group priority
        freshness is the documented megabatch trade; groups still chain
        through each other's write-backs), one lane-filling batched
        forward/backward computes the M gradients, and the M |TD|
        write-backs land sequentially in minibatch order so index
        collisions resolve exactly as M sequential steps — skipped
        (guarded) minibatches suppressed per row."""
        alpha = self.alpha
        draw_fn = self._draw_fn

        from pytorch_distributed_tpu.utils.health import (
            SKIPPED_KEY, reduce_scan_metrics, suppress_writeback,
        )

        if megabatch > 1:
            assert megabatch_step is not None, \
                "megabatch > 1 needs the factory's megabatch step"
            assert steps_per_call % megabatch == 0, (
                f"megabatch {megabatch} must divide steps_per_call "
                f"{steps_per_call}")
            groups = steps_per_call // megabatch

            def one_group(ts, rs: PerReplayState, kset, beta):
                batches = jax.vmap(
                    lambda k: per_sample(rs, k, batch_size, beta,
                                         sample_fn=draw_fn))(kset)
                ts, metrics, td_abs, ok = megabatch_step(ts, batches)

                def writeback(rs_c, x):
                    idx, td, ok_i = x
                    rs_new = per_update_priorities(rs_c, idx, td, alpha)
                    # suppress_writeback takes the SKIPPED flag (1.0 =
                    # skipped); ok is the validity mask
                    return suppress_writeback(1.0 - ok_i, rs_new,
                                              rs_c), None

                rs, _ = jax.lax.scan(writeback, rs,
                                     (batches.index, td_abs, ok))
                return ts, rs, metrics

            def multi_mega(ts, rs, keys, beta):
                gkeys = keys.reshape(groups, megabatch, *keys.shape[1:])

                def body(carry, kset):
                    ts, rs = carry
                    ts, rs, metrics = one_group(ts, rs, kset, beta)
                    return (ts, rs), metrics

                (ts, rs), metrics = jax.lax.scan(body, (ts, rs), gkeys)
                return ts, rs, reduce_scan_metrics(metrics)

            return jax.jit(multi_mega,
                           donate_argnums=(0, 1) if donate else ())

        def one(ts, rs: PerReplayState, key, beta):
            batch = per_sample(rs, key, batch_size, beta, sample_fn=draw_fn)
            ts, metrics, td_abs = train_step(ts, batch)
            rs_new = per_update_priorities(rs, batch.index, td_abs, alpha)
            skipped = (metrics.get(SKIPPED_KEY)
                       if isinstance(metrics, dict) else None)
            if skipped is not None:
                # guarded step: a skipped (non-finite) substep must not
                # scatter its zeroed TD over real priorities either
                rs_new = suppress_writeback(skipped, rs_new, rs)
            return ts, rs_new, metrics

        if steps_per_call <= 1:
            return jax.jit(one, donate_argnums=(0, 1) if donate else ())

        def multi(ts, rs, keys, beta):
            def body(carry, key):
                ts, rs = carry
                ts, rs, metrics = one(ts, rs, key, beta)
                return (ts, rs), metrics

            (ts, rs), metrics = jax.lax.scan(body, (ts, rs), keys)
            return ts, rs, reduce_scan_metrics(metrics)

        return jax.jit(multi, donate_argnums=(0, 1) if donate else ())

    # -- checkpoint: uniform-ring snapshot + the priority leaves -----------

    def snapshot(self) -> dict:
        st = jax.device_get(self.state)
        fill, pos = int(st.fill), int(st.pos)
        shift = -pos if fill == self.capacity else 0
        out = {k: np.roll(np.asarray(getattr(st, k)), shift,
                          axis=0)[:fill].copy()
               for k in REPLAY_FIELDS}
        if self.channels_last:  # public schema is NCHW (see DeviceReplay)
            from pytorch_distributed_tpu.memory.device_replay import (
                snapshot_states_to_nchw,
            )

            out = snapshot_states_to_nchw(out)
        out["prov"] = np.roll(np.asarray(st.prov), shift,
                              axis=0)[:fill].astype(np.int64)
        out["leaf_priority"] = np.roll(
            np.asarray(st.priority), shift)[:fill].copy()
        # stored p^alpha on device; snapshot in the shared UNexponentiated
        # unit so host<->device PER resumes agree
        mx = float(np.asarray(st.max_priority))
        out["max_priority_base"] = np.float64(
            mx ** (1.0 / self.alpha) if self.alpha else mx)
        return out

    def restore(self, data: dict) -> int:
        n = super().restore(data)  # rows land at max priority...
        if n and "leaf_priority" in data:
            # ...then the saved (pre-exponentiated) leaves overwrite the
            # fresh slots [pos-n, pos) so sampling resumes where it left off
            st = self.state
            pos = int(jax.device_get(st.pos))
            idx = jnp.asarray(
                (np.arange(pos - n, pos) % self.capacity).astype(np.int32))
            pr = jnp.asarray(
                np.asarray(data["leaf_priority"], np.float32)[-n:])
            base = float(data.get("max_priority_base", 1.0))
            self.state = st._replace(
                priority=st.priority.at[idx].set(pr),
                max_priority=jnp.float32(
                    base ** self.alpha if self.alpha else base))
        return n

    def sample(self, batch_size: int, key: jax.Array,
               beta: float = 1.0) -> Batch:
        return self._sample_fn(self.state, key, batch_size=batch_size,
                               beta=jnp.asarray(beta))
