"""Prioritized replay resident in HBM, fused into the learner step.

The TPU-native completion of the reference's PER TODO beyond the host
sum-tree (memory/prioritized.py): the host tree exists because CPUs need
O(log N) sampling — a TPU doesn't.  Proportional sampling over a 50k-row
ring is a cumulative sum + inverse-CDF search (``cumsum`` +
``searchsorted``), microseconds of vectorized work that XLA fuses INTO the
training program, along with the importance weights and the |TD| priority
write-back.  One XLA program per learner step does: sample → forward →
backward → Adam → target update → priority scatter — the learner hot loop
never touches the host.

Priorities are stored pre-exponentiated (p_i = (|td|+eps)^alpha) so the
sampling pass needs no pow; new rows enter at the running max priority so
everything is replayed at least once (Ape-X standard).  Importance weights
are normalised by the max weight over valid rows (min-probability row),
annealed by beta supplied per call.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.utils.experience import Batch, Transition


class PerReplayState(NamedTuple):
    state0: jax.Array
    action: jax.Array
    reward: jax.Array
    gamma_n: jax.Array
    state1: jax.Array
    terminal1: jax.Array
    priority: jax.Array      # (N,) f32, pre-exponentiated p^alpha; 0 = empty
    max_priority: jax.Array  # () f32, running max of p^alpha
    pos: jax.Array           # int32 write cursor
    fill: jax.Array          # int32 valid rows


def per_feed(state: PerReplayState, chunk: Transition,
             capacity: int) -> PerReplayState:
    """Ingest a chunk at the cursor; new rows take the running max
    priority."""
    n = chunk.reward.shape[0]
    idx = (state.pos + jnp.arange(n, dtype=jnp.int32)) % capacity
    return PerReplayState(
        state0=state.state0.at[idx].set(chunk.state0),
        action=state.action.at[idx].set(chunk.action),
        reward=state.reward.at[idx].set(chunk.reward),
        gamma_n=state.gamma_n.at[idx].set(chunk.gamma_n),
        state1=state.state1.at[idx].set(chunk.state1),
        terminal1=state.terminal1.at[idx].set(chunk.terminal1),
        priority=state.priority.at[idx].set(state.max_priority),
        max_priority=state.max_priority,
        pos=(state.pos + n) % capacity,
        fill=jnp.minimum(state.fill + n, capacity),
    )


def per_sample(state: PerReplayState, key: jax.Array, batch_size: int,
               beta: jax.Array) -> Batch:
    """Proportional sample + IS weights, all on device."""
    p = state.priority  # empty rows hold 0 and can never be drawn
    cdf = jnp.cumsum(p)
    total = cdf[-1]
    u = jax.random.uniform(key, (batch_size,)) * total
    idx = jnp.clip(jnp.searchsorted(cdf, u, side="right"),
                   0, state.priority.shape[0] - 1).astype(jnp.int32)
    probs = p[idx] / jnp.maximum(total, 1e-12)
    fill = jnp.maximum(state.fill.astype(jnp.float32), 1.0)
    weights = (fill * jnp.maximum(probs, 1e-12)) ** (-beta)
    # max weight = weight of the min-probability VALID row
    min_p = jnp.min(jnp.where(p > 0, p, jnp.inf)) / jnp.maximum(total, 1e-12)
    max_w = (fill * jnp.maximum(min_p, 1e-12)) ** (-beta)
    weights = weights / jnp.maximum(max_w, 1e-12)
    return Batch(
        state0=state.state0[idx],
        action=state.action[idx],
        reward=state.reward[idx],
        gamma_n=state.gamma_n[idx],
        state1=state.state1[idx],
        terminal1=state.terminal1[idx],
        weight=weights.astype(jnp.float32),
        index=idx,
    )


def per_update_priorities(state: PerReplayState, idx: jax.Array,
                          td_abs: jax.Array, alpha: float,
                          epsilon: float = 1e-6) -> PerReplayState:
    """|TD| write-back (pre-exponentiated) + running-max maintenance."""
    pr = (jnp.abs(td_abs) + epsilon) ** alpha
    return state._replace(
        priority=state.priority.at[idx].set(pr.astype(jnp.float32)),
        max_priority=jnp.maximum(state.max_priority, jnp.max(pr)),
    )


class DevicePerReplay:
    """Stateful wrapper owning the HBM PER ring (learner process only).

    ``build_fused_step`` wraps a ``(TrainState, Batch) -> (TrainState,
    metrics, td_abs)`` train step into ``(TrainState, PerReplayState, key,
    beta) -> (TrainState, PerReplayState, metrics)`` — sampling and priority
    write-back fused in.
    """

    def __init__(self, capacity: int, state_shape: Tuple[int, ...],
                 action_shape: Tuple[int, ...] = (),
                 state_dtype=np.uint8, action_dtype=np.int32,
                 priority_exponent: float = 0.6,
                 importance_weight: float = 0.4,
                 importance_anneal_steps: int = 500000,
                 mesh: Optional[jax.sharding.Mesh] = None):
        self.capacity = capacity
        self.state_dtype = np.dtype(state_dtype)
        self.action_dtype = np.dtype(action_dtype)
        self.alpha = priority_exponent
        self.beta0 = importance_weight
        self.beta_steps = importance_anneal_steps
        self._row_sharding = None
        self._scalar_sharding = None
        if mesh is not None:
            ndev = mesh.shape["dp"]
            if capacity % ndev:
                # same rounding contract as DeviceReplayIngest.attach
                rounded = capacity + ndev - capacity % ndev
                import warnings

                warnings.warn(
                    f"device PER capacity {capacity} rounded up to "
                    f"{rounded} (multiple of mesh dp={ndev})", stacklevel=2)
                capacity = self.capacity = rounded
            P = jax.sharding.PartitionSpec
            self._row_sharding = jax.sharding.NamedSharding(mesh, P("dp"))
            self._scalar_sharding = jax.sharding.NamedSharding(mesh, P())

        def alloc(shape, dtype, sharded=True):
            arr = jnp.zeros(shape, dtype=dtype)
            if self._row_sharding is not None:
                arr = jax.device_put(
                    arr,
                    self._row_sharding if sharded else self._scalar_sharding)
            return arr

        N = capacity
        self.state = PerReplayState(
            state0=alloc((N, *state_shape), jnp.dtype(state_dtype)),
            action=alloc((N, *action_shape), jnp.dtype(action_dtype)),
            reward=alloc((N,), jnp.float32),
            gamma_n=alloc((N,), jnp.float32),
            state1=alloc((N, *state_shape), jnp.dtype(state_dtype)),
            terminal1=alloc((N,), jnp.float32),
            priority=alloc((N,), jnp.float32),
            max_priority=alloc((), jnp.float32, sharded=False) + 1.0,
            pos=alloc((), jnp.int32, sharded=False),
            fill=alloc((), jnp.int32, sharded=False),
        )
        self._feed_fn = jax.jit(
            functools.partial(per_feed, capacity=capacity),
            donate_argnums=0)
        self._sample_fn = jax.jit(per_sample, static_argnames="batch_size")

    def feed_chunk(self, chunk: Transition) -> None:
        self.state = self._feed_fn(self.state, chunk)

    def beta(self, step: int) -> float:
        frac = min(1.0, step / max(1, self.beta_steps))
        return self.beta0 + (1.0 - self.beta0) * frac

    def build_fused_step(self, train_step, batch_size: int,
                         donate: bool = True):
        alpha = self.alpha

        def fused(ts, rs: PerReplayState, key, beta):
            batch = per_sample(rs, key, batch_size, beta)
            ts, metrics, td_abs = train_step(ts, batch)
            rs = per_update_priorities(rs, batch.index, td_abs, alpha)
            return ts, rs, metrics

        return jax.jit(fused, donate_argnums=(0, 1) if donate else ())

    def sample(self, batch_size: int, key: jax.Array,
               beta: float = 1.0) -> Batch:
        return self._sample_fn(self.state, key, batch_size=batch_size,
                               beta=jnp.asarray(beta))
