"""Sequence (R2D2 segment) replay resident in HBM, fused into the learner.

The TPU-native completion of the sequence plane: the host SequenceReplay
(memory/sequence_replay.py) keeps segments in a queue-owned numpy ring and
pays one host->device transfer per sampled batch — measured at ~3 learner
updates/s on the pixel R2D2 run against a 219 updates/s chip row for the
same program (RESULTS.md), because every update re-ships (B, T+C, 84, 84)
pixels through the host.  Here the segment arrays live in device HBM as jax
Arrays (optionally dp-sharded over the learner mesh, rows split across
devices like memory/device_replay.py), actors stream FRAME-PACKED segments
through a spawn queue once, and one XLA program per dispatch runs

    proportional sample -> burn-in unroll -> train-window unroll
    -> n-step targets -> Adam -> target update -> |TD| priority scatter

for ``steps_per_call`` scanned sub-steps — the sequence counterpart of
memory/device_per.py build_fused_step, with the same pre-exponentiated
priority scheme (p_i = (|td|+eps)^alpha stored, new rows at the running
max so every segment trains at least once).

Sampling uses the flat cumsum+searchsorted XLA scheme only: segment rings
are small (capacity counts SEGMENTS — the pixel config holds ~1k rows, vs
50k transitions for the flat rings), so the O(N) pass is noise and the
Pallas hierarchical sampler's block padding (ops/pallas_sampling.py,
>=1024-wide superblocks) would exceed the whole ring.

Reference relationship: the reference stores single transitions only
(core/memories/shared_memory.py:59-67); SURVEY.md §5 requires the replay
layout not preclude "contiguous episode segments" — this module is that
layout's TPU-native home.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.memory.device_replay import round_capacity
from pytorch_distributed_tpu.memory.sequence_replay import SegmentBatch


class SegmentChunk(NamedTuple):
    """Host->device ingest payload: a stack of segments (leading chunk
    dim), field-for-field the Segment schema."""

    obs: np.ndarray        # (n, T+C, H, W) packed / (n, T+1, *S) unpacked
    action: np.ndarray     # (n, T) int32
    reward: np.ndarray     # (n, T) float32
    terminal: np.ndarray   # (n, T) float32
    mask: np.ndarray       # (n, T) float32
    c0: np.ndarray         # (n, lstm_dim) float32
    h0: np.ndarray         # (n, lstm_dim) float32


class SeqReplayState(NamedTuple):
    obs: jax.Array
    action: jax.Array
    reward: jax.Array
    terminal: jax.Array
    mask: jax.Array
    c0: jax.Array
    h0: jax.Array
    priority: jax.Array      # (N,) f32 pre-exponentiated p^alpha; 0 = empty
    max_priority: jax.Array  # () f32 running max of p^alpha
    pos: jax.Array           # int32 write cursor
    fill: jax.Array          # int32 valid rows


def seq_feed(state: SeqReplayState, chunk: SegmentChunk,
             capacity: int) -> SeqReplayState:
    """Ring-write a chunk of segments at the cursor; new rows enter at the
    running max priority (Ape-X/R2D2 standard — replayed at least once)."""
    n = chunk.reward.shape[0]
    idx = (state.pos + jnp.arange(n, dtype=jnp.int32)) % capacity
    return state._replace(
        obs=state.obs.at[idx].set(chunk.obs),
        action=state.action.at[idx].set(chunk.action),
        reward=state.reward.at[idx].set(chunk.reward),
        terminal=state.terminal.at[idx].set(chunk.terminal),
        mask=state.mask.at[idx].set(chunk.mask),
        c0=state.c0.at[idx].set(chunk.c0),
        h0=state.h0.at[idx].set(chunk.h0),
        priority=state.priority.at[idx].set(state.max_priority),
        pos=(state.pos + n) % capacity,
        fill=jnp.minimum(state.fill + n, capacity),
    )


def seq_sample(state: SeqReplayState, key: jax.Array, batch_size: int,
               beta: jax.Array) -> SegmentBatch:
    """Proportional segment sample + IS weights, all on device — the
    sequence twin of device_per.per_sample (same inverse-CDF scheme, same
    max-weight normalisation over valid rows)."""
    p = state.priority  # empty rows hold 0 and can never be drawn
    cdf = jnp.cumsum(p)
    total = cdf[-1]
    u = jax.random.uniform(key, (batch_size,)) * total
    idx = jnp.clip(jnp.searchsorted(cdf, u, side="right"),
                   0, p.shape[0] - 1).astype(jnp.int32)
    probs = p[idx] / jnp.maximum(total, 1e-12)
    fill = jnp.maximum(state.fill.astype(jnp.float32), 1.0)
    weights = (fill * jnp.maximum(probs, 1e-12)) ** (-beta)
    min_p = jnp.min(jnp.where(p > 0, p, jnp.inf)) / jnp.maximum(total, 1e-12)
    max_w = (fill * jnp.maximum(min_p, 1e-12)) ** (-beta)
    weights = weights / jnp.maximum(max_w, 1e-12)
    return SegmentBatch(
        obs=state.obs[idx],
        action=state.action[idx],
        reward=state.reward[idx],
        terminal=state.terminal[idx],
        mask=state.mask[idx],
        c0=state.c0[idx],
        h0=state.h0[idx],
        weight=weights.astype(jnp.float32),
        index=idx,
    )


def seq_update_priorities(state: SeqReplayState, idx: jax.Array,
                          td_abs: jax.Array, alpha: float,
                          epsilon: float = 1e-6) -> SeqReplayState:
    """Eta-blended per-sequence |TD| write-back (the learner's seq_pr,
    ops/sequence_losses.py _masked_loss_and_priority), pre-exponentiated."""
    pr = (jnp.abs(td_abs) + epsilon) ** alpha
    return state._replace(
        priority=state.priority.at[idx].set(pr.astype(jnp.float32)),
        max_priority=jnp.maximum(state.max_priority, jnp.max(pr)),
    )


class DeviceSequenceReplay:
    """Stateful wrapper owning the HBM segment ring (learner process only).

    ``build_fused_step`` wraps a sequence train step ``(TrainState,
    SegmentBatch) -> (TrainState, metrics, seq_pr)`` (ops/sequence_losses.py
    build_drqn_train_step / build_dtqn_train_step) into ``(TrainState,
    SeqReplayState, keys, beta) -> (TrainState, SeqReplayState, metrics)``
    with sampling and priority write-back fused in — the same contract
    DevicePerReplay.build_fused_step gives the learner, so the learner's
    device-PER hot loop drives this ring unchanged.
    """

    def __init__(self, capacity: int, seq_len: int,
                 state_shape: Tuple[int, ...], lstm_dim: int,
                 state_dtype=np.uint8,
                 priority_exponent: float = 0.9,
                 importance_weight: float = 0.6,
                 importance_anneal_steps: int = 500000,
                 pack_frames: int = 0,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 axis: str = "dp"):
        self.capacity = round_capacity(capacity, mesh, axis=axis,
                                       label="device sequence replay")
        self.T = seq_len
        self.lstm_dim = lstm_dim
        self.alpha = priority_exponent
        self.beta0 = importance_weight
        self.beta_steps = importance_anneal_steps
        self.pack_frames = int(pack_frames)
        self.state_dtype = jnp.dtype(state_dtype)
        S = tuple(state_shape)
        if self.pack_frames:
            # frame-packed rows (T+C, H, W): stacks rebuilt on device by the
            # train step (ops/sequence_losses.py unpack_frame_stacks) — the
            # C-fold pixel de-dup holds on the wire, in host RAM, AND here
            # in HBM, where the ring would otherwise be C times larger
            assert S[0] == self.pack_frames, (S, pack_frames)
            self.obs_shape = (seq_len + self.pack_frames, *S[1:])
        else:
            self.obs_shape = (seq_len + 1, *S)

        if mesh is not None:
            P = jax.sharding.PartitionSpec
            self._row_sharding = jax.sharding.NamedSharding(mesh, P(axis))
            self._scalar_sharding = jax.sharding.NamedSharding(mesh, P())
        else:
            self._row_sharding = None
            self._scalar_sharding = None

        self.state = self._init_state()
        self._feed_fn = jax.jit(
            functools.partial(seq_feed, capacity=self.capacity),
            donate_argnums=0)
        self._sample_fn = jax.jit(seq_sample, static_argnames="batch_size")

    def _alloc(self, shape, dtype, sharded: bool = True):
        arr = jnp.zeros(shape, dtype=dtype)
        if self._row_sharding is not None:
            arr = jax.device_put(
                arr,
                self._row_sharding if sharded else self._scalar_sharding)
        return arr

    def _init_state(self) -> SeqReplayState:
        N, T = self.capacity, self.T
        alloc = self._alloc
        return SeqReplayState(
            obs=alloc((N, *self.obs_shape), self.state_dtype),
            action=alloc((N, T), jnp.int32),
            reward=alloc((N, T), jnp.float32),
            terminal=alloc((N, T), jnp.float32),
            mask=alloc((N, T), jnp.float32),
            c0=alloc((N, self.lstm_dim), jnp.float32),
            h0=alloc((N, self.lstm_dim), jnp.float32),
            priority=alloc((N,), jnp.float32),
            max_priority=alloc((), jnp.float32, sharded=False) + 1.0,
            pos=alloc((), jnp.int32, sharded=False),
            fill=alloc((), jnp.int32, sharded=False),
        )

    @property
    def size(self) -> int:
        return int(jax.device_get(self.state.fill))

    def beta(self, step: int) -> float:
        frac = min(1.0, step / max(1, self.beta_steps))
        return self.beta0 + (1.0 - self.beta0) * frac

    def feed_chunk(self, chunk: SegmentChunk) -> None:
        """One host->device transfer per fixed-size chunk (fixed so the
        jitted feed never retraces)."""
        self.state = self._feed_fn(self.state, chunk)

    def sample(self, batch_size: int, key: jax.Array,
               beta: float = 1.0) -> SegmentBatch:
        return self._sample_fn(self.state, key, batch_size=batch_size,
                               beta=jnp.asarray(beta, jnp.float32))

    def update_priorities(self, idx, td_abs) -> None:
        self.state = seq_update_priorities(self.state, jnp.asarray(idx),
                                           jnp.asarray(td_abs), self.alpha)

    def build_fused_step(self, train_step, batch_size: int,
                         donate: bool = True, steps_per_call: int = 1):
        """Fused sample -> burn-in/train -> priority write-back;
        ``steps_per_call`` sub-steps scan inside one XLA program with the
        priority state chained through, so each sub-step samples from the
        previous one's refreshed priorities — dispatch latency amortised
        K-fold exactly like the transition planes (tunnel-measured: one
        unamortised dispatch costs ~1.4 ms, see bench.py)."""
        alpha = self.alpha

        from pytorch_distributed_tpu.utils.health import (
            SKIPPED_KEY, reduce_scan_metrics, suppress_writeback,
        )

        def one(ts, rs: SeqReplayState, key, beta):
            batch = seq_sample(rs, key, batch_size, beta)
            ts, metrics, seq_pr = train_step(ts, batch)
            rs_new = seq_update_priorities(rs, batch.index, seq_pr, alpha)
            skipped = (metrics.get(SKIPPED_KEY)
                       if isinstance(metrics, dict) else None)
            if skipped is not None:
                # a guard-skipped substep's zeroed priorities must not
                # overwrite the ring's real ones (utils/health.py)
                rs_new = suppress_writeback(skipped, rs_new, rs)
            return ts, rs_new, metrics

        if steps_per_call <= 1:
            return jax.jit(one, donate_argnums=(0, 1) if donate else ())

        def multi(ts, rs, keys, beta):
            def body(carry, key):
                ts, rs = carry
                ts, rs, metrics = one(ts, rs, key, beta)
                return (ts, rs), metrics

            (ts, rs), metrics = jax.lax.scan(body, (ts, rs), keys)
            return ts, rs, reduce_scan_metrics(metrics)

        return jax.jit(multi, donate_argnums=(0, 1) if donate else ())

    # -- checkpoint: the replay-contents tier (utils/checkpoint.py) --------

    _FIELDS = ("obs", "action", "reward", "terminal", "mask", "c0", "h0")

    def snapshot(self) -> dict:
        """Valid rows to host in age order, plus the priority leaves in the
        shared UNexponentiated unit (same convention as device_per.py)."""
        st = jax.device_get(self.state)
        fill, pos = int(st.fill), int(st.pos)
        shift = -pos if fill == self.capacity else 0
        out = {k: np.roll(np.asarray(getattr(st, k)), shift,
                          axis=0)[:fill].copy()
               for k in self._FIELDS}
        out["leaf_priority"] = np.roll(
            np.asarray(st.priority), shift)[:fill].copy()
        mx = float(np.asarray(st.max_priority))
        out["max_priority_base"] = np.float64(
            mx ** (1.0 / self.alpha) if self.alpha else mx)
        return out

    def restore(self, data: dict) -> int:
        """Refill through the normal chunked write path (newest rows that
        fit), then overwrite the fresh max-priority slots with the saved
        leaves so sampling resumes where it left off."""
        if self.size:
            self.state = self._init_state()
        rows = np.asarray(data["reward"])
        n = min(len(rows), self.capacity)
        if n:
            self.feed_chunk(SegmentChunk(*(
                np.asarray(data[k])[-n:] for k in self._FIELDS)))
            if "leaf_priority" in data:
                st = self.state
                pos = int(jax.device_get(st.pos))
                idx = jnp.asarray((np.arange(pos - n, pos)
                                   % self.capacity).astype(np.int32))
                pr = jnp.asarray(
                    np.asarray(data["leaf_priority"], np.float32)[-n:])
                base = float(data.get("max_priority_base", 1.0))
                self.state = st._replace(
                    priority=st.priority.at[idx].set(pr),
                    max_priority=jnp.float32(
                        base ** self.alpha if self.alpha else base))
        return n


class DeviceSequenceIngest:
    """Cross-process front end for the HBM segment ring.

    Actors cannot address HBM, so the ring is single-owner (the Ape-X
    topology proper): recurrent actors stream Segments over a spawn queue
    via ``make_feeder()`` and the learner calls ``attach`` (after it owns
    the mesh) then ``drain()`` between dispatches — stacking fixed-size
    SegmentChunks host-side and ingesting each with one transfer.  Same
    duck-typed learner surface as DevicePerIngest (attach / drain / size /
    capacity / replay.build_fused_step / replay.beta), so the learner's
    fused-priority hot loop needs no sequence-specific branch.
    """

    # single-owner declaration (apexlint): learner-only ingest pump
    __apex_mutators__ = ("drain",)
    __apex_owner__ = ("agents.learner", "memory.")

    def __init__(self, capacity: int, seq_len: int,
                 state_shape: Tuple[int, ...], lstm_dim: int,
                 state_dtype=np.uint8,
                 priority_exponent: float = 0.9,
                 importance_weight: float = 0.6,
                 importance_anneal_steps: int = 500000,
                 pack_frames: int = 0,
                 chunk_size: int = 16, max_queue_chunks: int = 4096):
        import multiprocessing as mp

        self.capacity = capacity
        self.seq_len = seq_len
        self.state_shape = tuple(state_shape)
        self.lstm_dim = lstm_dim
        self.state_dtype = np.dtype(state_dtype)
        self.priority_exponent = priority_exponent
        self.importance_weight = importance_weight
        self.importance_anneal_steps = importance_anneal_steps
        self.pack_frames = int(pack_frames)
        self.chunk_size = chunk_size
        # largest-first ingest sizes: a deep backlog moves in few large
        # transfers (one jit trace each) — same rationale as
        # DeviceReplayIngest.chunk_sizes, smaller multipliers because one
        # segment is ~T times a transition's bytes
        self.chunk_sizes = tuple(sorted(
            {min(s, capacity) for s in (chunk_size, chunk_size * 8)},
            reverse=True))
        self.max_queue_chunks = max_queue_chunks
        self._q = mp.get_context("spawn").Queue(max_queue_chunks)
        self.replay: Optional[DeviceSequenceReplay] = None
        self._pending: list = []
        self._fed_total = 0

    def make_feeder(self, chunk: int = 8):
        from pytorch_distributed_tpu.memory.feeder import QueueFeeder

        return QueueFeeder(self._q, chunk)

    def attach(self, mesh: Optional[jax.sharding.Mesh] = None
               ) -> DeviceSequenceReplay:
        self.replay = DeviceSequenceReplay(
            self.capacity, self.seq_len, self.state_shape, self.lstm_dim,
            state_dtype=self.state_dtype,
            priority_exponent=self.priority_exponent,
            importance_weight=self.importance_weight,
            importance_anneal_steps=self.importance_anneal_steps,
            pack_frames=self.pack_frames, mesh=mesh)
        self.capacity = self.replay.capacity  # mesh rounding
        return self.replay

    @property
    def size(self) -> int:
        # host-side accounting — no device sync in the hot loop
        assert self.replay is not None, "attach() first"
        return min(self._fed_total, self.capacity)

    def drain(self, max_chunks: int = 1024, max_rows: int = 512) -> int:
        """Move queued segments into HBM; bounded per call so a deep
        backlog cannot stall the learner's dispatch cadence."""
        from pytorch_distributed_tpu.memory.feeder import pop_chunks

        assert self.replay is not None, "attach() first"
        self._pending.extend(
            seg for seg, _priority in pop_chunks(self._q, max_chunks))
        fed = 0
        while fed < max_rows:
            C = next((s for s in self.chunk_sizes
                      if s <= len(self._pending)), None)
            if C is None:
                break
            rows, self._pending = self._pending[:C], self._pending[C:]
            self.replay.feed_chunk(self._stack(rows))
            fed += C
        self._fed_total += fed
        return fed

    def _stack(self, rows) -> SegmentChunk:
        dt = {"obs": self.state_dtype, "action": np.int32}
        return SegmentChunk(*(
            np.stack([getattr(r, f) for r in rows]).astype(
                dt.get(f, np.float32))
            for f in SegmentChunk._fields))

    # -- checkpoint: drain then delegate to the HBM ring -------------------

    def snapshot(self) -> dict:
        assert self.replay is not None, "attach() first"
        while self.drain():
            pass
        if self._pending:  # sub-chunk remainder: one odd-sized trace
            rows, self._pending = self._pending, []
            self.replay.feed_chunk(self._stack(rows))
            self._fed_total += len(rows)
        return self.replay.snapshot()

    def restore(self, data: dict) -> None:
        assert self.replay is not None, "attach() first"
        self._fed_total += self.replay.restore(data)

    def close(self) -> None:
        """See QueueOwner.close: discard, never join a dead pipe."""
        if hasattr(self._q, "cancel_join_thread"):
            self._q.cancel_join_thread()
        if hasattr(self._q, "close"):
            self._q.close()
