"""Proportional prioritized experience replay (PER).

This finishes what the reference left as a TODO: its ``enable_per`` flag is
off with "not completed for now" (reference utils/options.py:82), its
``priority`` argument is threaded into feed() and discarded (reference
core/memories/shared_memory.py:45), and its sum-tree sketch is dead code
(reference utils/segment_tree.py).  Here: a single-owner (learner-process)
buffer with proportional sampling via the vectorized SumTree, initial
priorities from actor-computed TD estimates (the plumbing the reference
already anticipated at dqn_actor.py:113-115), importance-sampling weights
normalised by the max weight via a MinTree, and priority write-back after
each learner step.  Schedule follows Ape-X: priority exponent alpha,
IS exponent beta annealed to 1.

Single-owner by design: actors stream transitions to the owner over a
queue (agents/actor.py) instead of writing shared pages, so the trees need
no cross-process locking.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.memory.base import Memory
from pytorch_distributed_tpu.utils import bandwidth
from pytorch_distributed_tpu.utils.experience import (
    REPLAY_FIELDS, Batch, Transition,
)
from pytorch_distributed_tpu.utils.segment_tree import MinTree, SumTree


class PrioritizedReplay(Memory):
    def __init__(self, capacity: int, state_shape: Tuple[int, ...],
                 action_shape: Tuple[int, ...] = (),
                 state_dtype=np.uint8, action_dtype=np.int32,
                 priority_exponent: float = 0.6,
                 importance_weight: float = 0.4,
                 importance_anneal_steps: int = 500000,
                 epsilon: float = 1e-6):
        super().__init__(capacity, state_shape, action_shape,
                         state_dtype, action_dtype)
        N = capacity
        self.state0 = np.zeros((N, *self.state_shape), dtype=self.state_dtype)
        self.action = np.zeros((N, *self.action_shape), dtype=self.action_dtype)
        self.reward = np.zeros((N,), dtype=np.float32)
        self.gamma_n = np.zeros((N,), dtype=np.float32)
        self.state1 = np.zeros((N, *self.state_shape), dtype=self.state_dtype)
        self.terminal1 = np.zeros((N,), dtype=np.float32)
        # provenance sidecar (ISSUE 8): (actor_id, env_slot,
        # param_version, birth_step) per row, -1 = unknown.  A sidecar,
        # NOT a seventh schema column: the six-array replay schema is a
        # wire/checkpoint contract shared with rings that predate it.
        self.prov = np.full((N, 4), -1, dtype=np.int64)
        self.sum_tree = SumTree(N)
        self.min_tree = MinTree(N)
        self.alpha = priority_exponent
        self.beta0 = importance_weight
        self.beta_steps = importance_anneal_steps
        self.eps = epsilon
        self.max_priority = 1.0
        self._pos = 0
        self._full = False
        self._samples_drawn = 0
        # replay occupancy gauge (bandwidth X-ray, ISSUE 18): columns
        # are preallocated, so one shot here is accurate for the run
        bandwidth.note_host_replay(self)

    @property
    def size(self) -> int:
        return self.capacity if self._full else self._pos

    @property
    def beta(self) -> float:
        frac = min(1.0, self._samples_drawn / max(1, self.beta_steps))
        return self.beta0 + (1.0 - self.beta0) * frac

    def _priority(self, p: Optional[float]) -> float:
        # new transitions default to the running max priority so everything
        # is replayed at least once (Ape-X / PER standard)
        base = self.max_priority if p is None else abs(float(p)) + self.eps
        return base ** self.alpha

    def feed(self, transition: Transition,
             priority: Optional[float] = None) -> None:
        i = self._pos
        self.state0[i] = transition.state0
        self.action[i] = transition.action
        self.reward[i] = transition.reward
        self.gamma_n[i] = transition.gamma_n
        self.state1[i] = transition.state1
        self.terminal1[i] = transition.terminal1
        self.prov[i] = (-1 if getattr(transition, "prov", None) is None
                        else transition.prov)
        pr = self._priority(priority)
        self.sum_tree.set(i, pr)
        self.min_tree.set(i, pr)
        self.max_priority = max(self.max_priority,
                                pr ** (1.0 / self.alpha) if self.alpha else pr)
        self._pos = (i + 1) % self.capacity
        self._full = self._full or self._pos == 0

    def feed_batch(self, ts: Transition, priorities=None) -> None:
        n = len(ts.reward)
        for j in range(n):
            self.feed(
                Transition(ts.state0[j], ts.action[j], ts.reward[j],
                           ts.gamma_n[j], ts.state1[j], ts.terminal1[j]),
                None if priorities is None else priorities[j])

    def sample(self, batch_size: int, rng: np.random.Generator) -> Batch:
        assert self.size > 0
        idx = self.sum_tree.sample(batch_size, rng)
        self._samples_drawn += 1
        probs = self.sum_tree.get(idx) / self.sum_tree.total
        beta = self.beta
        weights = (self.size * probs) ** (-beta)
        min_prob = self.min_tree.min / self.sum_tree.total
        max_weight = (self.size * min_prob) ** (-beta)
        weights = (weights / max_weight).astype(np.float32)
        return Batch(
            state0=self.state0[idx].copy(),
            action=self.action[idx].copy(),
            reward=self.reward[idx].copy(),
            gamma_n=self.gamma_n[idx].copy(),
            state1=self.state1[idx].copy(),
            terminal1=self.terminal1[idx].copy(),
            weight=weights,
            index=idx.astype(np.int32),
        )

    # -- checkpoint (utils/checkpoint.py save_replay/load_replay) -----------

    def snapshot(self) -> dict:
        """Valid rows in AGE order (oldest first) + tree LEAF priorities
        (already alpha-exponentiated, so restore sets them back verbatim —
        no double exponentiation)."""
        n = self.size
        shift = -self._pos if self._full else 0
        out = {k: np.roll(getattr(self, k), shift, axis=0)[:n].copy()
               for k in REPLAY_FIELDS}
        out["prov"] = np.roll(self.prov, shift, axis=0)[:n].copy()
        out["leaf_priority"] = np.roll(
            self.sum_tree.get(np.arange(self.capacity)), shift)[:n].copy()
        # UNexponentiated, the unit every restore path expects — the device
        # PER converts its p^alpha running max to base on snapshot too
        out["max_priority_base"] = np.float64(self.max_priority)
        out["samples_drawn"] = np.int64(self._samples_drawn)
        # The exponent the leaves are saved under, so a restoring run with a
        # different priority_exponent can convert instead of mixing units.
        out["alpha"] = np.float64(self.alpha)
        return out

    def restore(self, data: dict) -> None:
        rows = np.asarray(data["reward"])
        n = min(len(rows), self.capacity)
        for k in REPLAY_FIELDS:
            getattr(self, k)[:n] = data[k][-n:]
        self.prov[:n] = (np.asarray(data["prov"], np.int64)[-n:]
                         if "prov" in data else -1)
        self.prov[n:] = -1
        if "leaf_priority" in data:
            leaves = np.asarray(data["leaf_priority"],
                                dtype=np.float64)[-n:]
            # Leaves are saved p^alpha under the SAVING run's alpha; if the
            # restoring run uses a different exponent, re-exponentiate so
            # restored and freshly-fed priorities share one unit.
            saved_alpha = float(data.get("alpha", self.alpha))
            if saved_alpha != self.alpha and saved_alpha > 0:
                leaves = leaves ** (self.alpha / saved_alpha)
        else:  # snapshot from a uniform ring: everything replays once
            leaves = np.full(n, self._priority(None), dtype=np.float64)
        idx = np.arange(n)
        self.sum_tree.set(idx, leaves)
        self.min_tree.set(idx, leaves)
        if n < self.capacity:
            # Zero any leaves beyond the restored region so a snapshot
            # smaller than the current contents can't leave stale
            # priorities pointing at pre-restore rows.
            stale = np.arange(n, self.capacity)
            self.sum_tree.set(stale, np.zeros(len(stale)))
            # MinTree's neutral is +inf (segment_tree.py:116): zeros here
            # would drive min_prob to 0 and every IS weight to 0.
            self.min_tree.set(stale, np.full(len(stale), np.inf))
        self._pos = n % self.capacity
        self._full = n == self.capacity
        self.max_priority = float(data.get("max_priority_base", 1.0))
        self._samples_drawn = int(data.get("samples_drawn", 0))

    def provenance_of(self, indices: np.ndarray) -> np.ndarray:
        """(B, 4) int64 provenance of the given rows; -1 rows = unknown
        (the learner's data-plane telemetry masks on ``[:, 0] >= 0``)."""
        return self.prov[np.asarray(indices)]

    def priority_leaves(self) -> np.ndarray:
        """The valid rows' tree leaves (p^alpha) — the priority X-ray's
        input (utils/health.priority_xray)."""
        return self.sum_tree.get(np.arange(self.size))

    def update_priorities(self, indices: np.ndarray,
                          priorities: np.ndarray) -> None:
        priorities = np.abs(np.asarray(priorities, dtype=np.float64)) + self.eps
        pr = priorities ** self.alpha
        self.sum_tree.set(indices, pr)
        self.min_tree.set(indices, pr)
        self.max_priority = max(self.max_priority, float(priorities.max()))
