"""Queue-based transition routing for single-owner memories.

The shared ring buffer lets every process write the same pages (reference
core/memories/shared_memory.py); the prioritized buffer's sum/min trees
cannot be shared pages without a cross-process lock on every tree node, so
PER is **single-owner**: the learner process owns the buffer and actors
stream transitions to it over a spawn-context queue — the Ape-X topology
proper (actors push batches of experience to the replay holder).

``QueueFeeder`` is the actor-side handle (chunked, so one queue message
amortises pickling over ``chunk`` transitions); ``QueueOwner`` wraps the
real memory on the learner side and drains pending chunks before sampling.
"""

from __future__ import annotations

import multiprocessing as mp
import queue as _queue
import time
from typing import List, Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.utils import bandwidth, tracing
from pytorch_distributed_tpu.utils.experience import Transition

_CTX = mp.get_context("spawn")


class QueueFeeder:
    """Actor-side feed endpoint; matches the memory ``feed`` surface.

    Every flushed chunk is a ``tracing.TracedChunk`` — a list subclass
    carrying a trace id + birth wall-clock across the queue (or, via
    RemoteMemory, the DCN wire), so downstream drains can record per-hop
    latency.  With a tracer attached (``set_tracer``; the actor harness
    binds its role tracer) the flush itself records an ``enqueue`` span —
    a blocking put IS backpressure, and its duration is the measurement.
    """

    def __init__(self, q, chunk: int = 16):
        self._q = q
        self._chunk = chunk
        self._buf: List[Tuple[Transition, Optional[float]]] = []
        self._stop = None
        self._timeout_put = False
        self._tracer: Optional[tracing.Tracer] = None
        self._faults = None  # FEEDER_FAULTS injector, built lazily
        # ISSUE-11 local shed policy (utils/flow.py): None until
        # configure_flow — the default "block" path is byte-identical
        # to the pre-flow feeder (backpressure stalls the producer)
        self._flow_params = None
        self._flow_ring = None

    def clone(self) -> "QueueFeeder":
        """Same queue, fresh chunk buffer — thread-backend workers each get
        their own clone so the buffer is never shared across threads (the
        process backend gets per-child copies from pickling anyway)."""
        f = QueueFeeder(self._q, self._chunk)
        if self._stop is not None:
            f.set_stop(self._stop)
        if self._flow_params is not None:
            f.configure_flow(self._flow_params)
        return f

    def configure_flow(self, params=None) -> None:
        """Select the overload policy for this feeder (ISSUE 11):
        ``FlowParams.local_policy`` = "block" keeps the pre-flow
        blocking put (default — correct when the queue bound IS the
        intended backpressure), "shed" makes a full queue park chunks
        in a bounded drop-oldest ring (newest experience wins; drops
        counted + provenance-stamped) so a single-host topology
        degrades exactly like the DCN client does.  The actor harness
        calls this with the resolved ``opt.flow_params``; env overrides
        (``TPU_APEX_FLOW_LOCAL_POLICY=shed``) reach spawn children
        through ``flow.resolve_flow`` as usual."""
        from pytorch_distributed_tpu.utils import flow

        fp = flow.resolve_flow(params)
        self._flow_params = fp
        if (fp.enabled and fp.local_policy == "shed"
                and hasattr(self._q, "put_nowait")):
            if self._flow_ring is None:
                self._flow_ring = flow.DropOldestRing(fp.feeder_ring)
        else:
            self._flow_ring = None

    @property
    def flow_dropped_rows(self) -> int:
        return self._flow_ring.dropped_rows if self._flow_ring else 0

    def set_tracer(self, tracer) -> None:
        """Attach the owning role's span recorder (utils/tracing.py)."""
        self._tracer = tracer

    def __getstate__(self):
        # tracers and fault injectors hold threading locks: never ride a
        # spawn pickle — the child attaches its own role tracer after
        # unpickling and rebuilds the injector from FEEDER_FAULTS
        # (spawn children inherit the env, utils/faults.py).  The shed
        # ring holds a lock too (and buffered chunks are this process's
        # backlog, not the child's): the child re-engages its policy
        # via configure_flow (the actor harness calls it with opt).
        d = self.__dict__.copy()
        d["_tracer"] = None
        d["_faults"] = None
        d["_flow_ring"] = None
        return d

    def _injector(self):
        """The feeder fault plane (``FEEDER_FAULTS``, utils/faults.py):
        one frame per flush, so ``poison_chunk@N`` poisons exactly the
        Nth chunk this process ships."""
        if self._faults is None:
            from pytorch_distributed_tpu.utils.faults import FaultInjector

            self._faults = FaultInjector.from_env("feeder")
        return self._faults

    def set_stop(self, event) -> None:
        """Make flush() abort (dropping its buffer) once ``event`` is set:
        with the learner gone nobody drains the queue, and a put() blocked
        on the full pipe would stall the worker past the teardown join."""
        self._stop = event
        # The stop-aware branch needs put(timeout=...); duck-typed sinks
        # without it (e.g. the DCN fleet's _ChunkSink, whose put is its
        # own non-blocking send) keep the plain call.
        import inspect

        try:
            self._timeout_put = (
                "timeout" in inspect.signature(self._q.put).parameters)
        except (ValueError, TypeError):
            self._timeout_put = False

    def close(self) -> None:
        """Never block process exit on the mp queue's feeder thread: its
        buffered chunks can't flush into a full pipe once the learner
        stopped draining, and the default join-at-exit would hang the
        worker until the supervisor's terminate."""
        if hasattr(self._q, "cancel_join_thread"):
            self._q.cancel_join_thread()

    def feed(self, transition: Transition,
             priority: Optional[float] = None) -> None:
        self._buf.append((transition, priority))
        if len(self._buf) >= self._chunk:
            self.flush()

    def flush(self) -> None:
        if not self._buf:
            return
        for _action, _arg in self._injector().data_frame(("poison_chunk",)):
            # poison_chunk drill: NaN rewards / garbage priorities (and
            # NaN obs for float states) — the learner-side ingest
            # quarantine must catch this chunk (utils/health.py)
            from pytorch_distributed_tpu.utils import health

            self._buf = list(health.poison_items(self._buf))
            print("[faults:feeder] poison_chunk: chunk poisoned before "
                  "flush", flush=True)
        traced = tracing.active()  # TPU_APEX_TRACE=0: plain list, no
        if traced:
            from pytorch_distributed_tpu.utils import flow as _flow

            # brownout tier >= 2 (ISSUE 11): the trace-sampling rung —
            # new chunks ship untraced (counted) until the tier drops
            if _flow.trace_shed():
                _flow.note_shed("trace", 1)
                traced = False
        chunk = (tracing.TracedChunk(self._buf)  # mint, no wire columns
                 if traced else self._buf)
        # bandwidth X-ray (ISSUE 18): the spawn-plane mint boundary —
        # drops downstream are the flow ring's counted shed, so mint
        # here is a plane counter, not a ledger leg
        bandwidth.note_spawn("mint", chunk)
        t0 = time.perf_counter()
        delivered = True
        if self._flow_ring is not None:
            # "shed" policy (ISSUE 11): never block the producer — a
            # full queue parks the chunk in the bounded drop-oldest
            # ring; later flushes (and this one) drain oldest-first as
            # the queue frees up.  Drops are the ring's counted,
            # provenance-stamped shed point.
            self._flow_ring.put(chunk)
            while True:
                pending = self._flow_ring.pop()
                if pending is None:
                    break
                try:
                    self._q.put_nowait(pending)
                except _queue.Full:
                    self._flow_ring.unpop(pending)
                    delivered = False
                    break
        elif self._stop is None or not self._timeout_put:
            self._q.put(chunk)
        else:
            while True:
                if self._stop.is_set():
                    delivered = False
                    break  # shutdown: leftover experience is garbage
                try:
                    self._q.put(chunk, timeout=0.2)
                    break
                except _queue.Full:
                    continue
        if traced and delivered and self._tracer is not None:
            self._tracer.record("enqueue",
                                (time.perf_counter() - t0) * 1e3,
                                trace_id=chunk.trace_id)
        self._buf = []


def pop_chunks(q, max_chunks: int = 1024) -> List[Tuple[Transition,
                                                        Optional[float]]]:
    """Drain pending (transition, priority) items from a feeder queue —
    the single queue-pop loop every single-owner memory shares.  Chunks
    that arrive as TracedChunks record their queue-transit latency as a
    ``feed`` span on the drain side (the replay plane's hop of the
    actor→learner trace)."""
    out: List[Tuple[Transition, Optional[float]]] = []
    tracer = tracing.get_tracer("feeder")
    popped = 0
    for _ in range(max_chunks):
        try:
            chunk = q.get_nowait()
        except _queue.Empty:
            break
        if isinstance(chunk, tracing.TracedChunk):
            tracer.record_hop("feed", chunk.born, chunk.trace_id)
        out.extend(chunk)
        popped += 1
    # the shared drain boundary: one stamp covers QueueOwner and
    # DeviceReplayIngest alike (bandwidth X-ray, ISSUE 18)
    bandwidth.note_spawn("drain", out, frames=popped)
    return out


class QueueOwner:
    """Learner-side owner: real memory + drain pump.

    Delegates the sampling surface; ``drain()`` must run on the owner
    process (the learner calls it before every sample)."""

    # single-owner declaration (apexlint single-owner rule): only the
    # learner role — and this module's own checkpoint path — may pump
    # the ingest boundary; a second drainer corrupts fill accounting
    # and bypasses the quarantine validator's per-source counters
    __apex_mutators__ = ("drain",)
    __apex_owner__ = ("agents.learner", "memory.feeder")

    def __init__(self, memory, max_queue_chunks: int = 4096):
        self.memory = memory
        self.max_queue_chunks = max_queue_chunks  # backpressure bound
        self._q = _CTX.Queue(max_queue_chunks)
        self._validator = None  # ingest quarantine, built on first drain

    def make_feeder(self, chunk: int = 16) -> QueueFeeder:
        return QueueFeeder(self._q, chunk)

    def drain(self, max_chunks: int = 1024) -> int:
        """Pull pending chunks into the memory; returns transitions
        POPPED from the queue (fed + quarantined — drain-to-empty loops
        key on popped, so an all-quarantined batch never reads as
        "queue dry").

        This is the single-owner ingest boundary, so the health
        sentinel's quarantine runs here (utils/health.py): non-finite
        obs/reward/priority and shape/dtype drift are diverted to
        ``{log_dir}/quarantine/`` instead of entering replay — one bad
        chunk must never poison what every future minibatch samples
        from."""
        from pytorch_distributed_tpu.utils import health

        items = pop_chunks(self._q, max_chunks)
        popped = len(items)  # drain-to-empty loops key on POPPED, not
        # fed: an all-quarantined batch must not read as "queue dry"
        if items and health.quarantine_active():
            if self._validator is None:
                self._validator = health.ChunkValidator.for_memory(
                    self.memory)
            items, bad = self._validator.filter(items)
            if bad:
                health.get_quarantine("feeder-local").put(
                    bad, trace_id=tracing.current_trace())
        for transition, priority in items:
            self.memory.feed(transition, priority)
        return popped

    # -- checkpoint: drain then delegate ------------------------------------

    def snapshot(self) -> dict:
        if not hasattr(self.memory, "snapshot"):
            # snapshot-less wrapped memory: checkpoint.save_replay skips
            # cleanly instead of crashing the learner
            raise NotImplementedError(type(self.memory).__name__)
        while self.drain():  # a deep backlog needs multiple capped drains
            pass
        return self.memory.snapshot()

    def restore(self, data: dict) -> None:
        if not hasattr(self.memory, "restore"):
            raise NotImplementedError(type(self.memory).__name__)
        self.memory.restore(data)

    # -- delegated sampling surface ----------------------------------------

    @property
    def size(self) -> int:
        return self.memory.size

    @property
    def capacity(self) -> int:
        return self.memory.capacity

    def sample(self, batch_size: int, rng: np.random.Generator):
        return self.memory.sample(batch_size, rng)

    def update_priorities(self, indices: np.ndarray,
                          priorities: np.ndarray) -> None:
        self.memory.update_priorities(indices, priorities)

    def provenance_of(self, indices: np.ndarray):
        """Delegated provenance gather (ISSUE 8 data-plane telemetry);
        None when the wrapped memory keeps no sidecar."""
        fn = getattr(self.memory, "provenance_of", None)
        return None if fn is None else fn(indices)

    def priority_leaves(self):
        """Delegated PER leaf read for the priority X-ray; None for
        uniform memories."""
        fn = getattr(self.memory, "priority_leaves", None)
        return None if fn is None else fn()

    def feed(self, transition: Transition,
             priority: Optional[float] = None) -> None:
        self.memory.feed(transition, priority)

    def close(self) -> None:
        """Shut the queue's feeder thread down cleanly — a daemon
        QueueFeederThread left alive at interpreter exit aborts the process
        from C++ teardown.  Pending items are discarded, not flushed:
        leftover experience is garbage at shutdown, and joining a feeder
        blocked on a full pipe nobody drains anymore deadlocks the run."""
        if hasattr(self._q, "cancel_join_thread"):  # mp queue only
            self._q.cancel_join_thread()
        if hasattr(self._q, "close"):  # queue.Queue has no close
            self._q.close()
