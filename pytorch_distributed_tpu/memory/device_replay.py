"""HBM-resident replay buffer as a jitted functional ring buffer.

TPU-native upgrade over the host shared-memory plane (SURVEY.md §7 step 4):
the six transition arrays live in device HBM as jax Arrays, optionally
sharded over the learner mesh's data axis, so sampling a minibatch never
crosses the host-device boundary — the learner consumes batches straight
from HBM and actors only pay one host->device transfer per *feed chunk*
(amortised), not per sampled batch.

Functional design: the buffer is a ``ReplayState`` pytree; ``feed`` and
``sample`` are jit-compiled pure functions with donated state so XLA updates
the rings in place.  Capacity is statically padded; the write cursor wraps
with modular index arithmetic (the jit-safe equivalent of the reference's
circular cursor, reference core/memories/shared_memory.py:45-57).

No reference equivalent — the reference buffer is host memory only.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.utils import bandwidth, experience
from pytorch_distributed_tpu.utils.experience import (
    REPLAY_FIELDS, Batch, Transition,
)


class ReplayState(NamedTuple):
    state0: jax.Array
    action: jax.Array
    reward: jax.Array
    gamma_n: jax.Array
    state1: jax.Array
    terminal1: jax.Array
    # data-plane provenance columns (ISSUE 8): (actor_id, env_slot,
    # param_version, birth_step) per row as int32 (-1 = unknown) — kept
    # AFTER the six replay columns so ``state[:6]`` keeps meaning the
    # replay schema for the PER subclass's constructor
    prov: jax.Array       # (N, 4) int32
    pos: jax.Array        # int32 write cursor
    fill: jax.Array       # int32 number of valid rows


# single-owner declaration for the module-level ring mutators
# (apexlint single-owner rule): the functional ring writes may only be
# composed into programs by the replay backends themselves and the
# fused rollout (models/policies emit="replay") — any other caller is
# a second writer racing the cursor
__apex_fn_owners__ = {
    "ring_write": ("memory.",),
    "ring_write_masked": ("memory.", "models.policies"),
}


def ring_write(state, chunk: Transition, capacity: int):
    """Write a chunk at the cursor of ANY ring state carrying the six-array
    schema plus pos/fill (ReplayState, and device_per.py's PerReplayState).
    Returns (state', idx) so extended schemas can set their extra
    per-row fields at the same slots."""
    n = chunk.reward.shape[0]
    idx = (state.pos + jnp.arange(n, dtype=jnp.int32)) % capacity
    repl = dict(
        state0=state.state0.at[idx].set(chunk.state0),
        action=state.action.at[idx].set(chunk.action),
        reward=state.reward.at[idx].set(chunk.reward),
        gamma_n=state.gamma_n.at[idx].set(chunk.gamma_n),
        state1=state.state1.at[idx].set(chunk.state1),
        terminal1=state.terminal1.at[idx].set(chunk.terminal1),
        pos=(state.pos + n) % capacity,
        fill=jnp.minimum(state.fill + n, capacity),
    )
    prov_col = getattr(state, "prov", None)
    if prov_col is not None:
        # rows without provenance overwrite with the -1 sentinel (a
        # recycled slot must never keep its previous row's provenance)
        repl["prov"] = prov_col.at[idx].set(
            jnp.full((n, prov_col.shape[1]), -1, prov_col.dtype)
            if chunk.prov is None else chunk.prov.astype(prov_col.dtype))
    return state._replace(**repl), idx


def _feed(state: ReplayState, chunk: Transition, capacity: int) -> ReplayState:
    return ring_write(state, chunk, capacity)[0]


def ring_write_masked(state, chunk: Transition, valid,
                      capacity: int):
    """Write only the ``valid`` rows of a chunk at the cursor, in chunk
    order, inside jit — the device actor plane's ingest primitive
    (models/policies.build_fused_rollout emit="replay"): the fused
    rollout's per-tick emissions carry a validity column (warmup ticks
    have no closed n-step window yet), and invalid rows must neither
    consume ring slots nor corrupt neighbours.

    Valid rows take positions ``pos + rank`` (rank = prefix count of
    valid rows); invalid rows are pointed at index ``capacity`` —
    out of bounds — and dropped by the scatter (``mode="drop"``), which
    XLA resolves with no branch.  Returns ``(state', n_written)``."""
    offs = jnp.cumsum(valid.astype(jnp.int32)) - 1
    idx = jnp.where(valid, (state.pos + offs) % capacity, capacity)
    total = jnp.sum(valid.astype(jnp.int32))
    wr = lambda buf, x: buf.at[idx].set(x, mode="drop")
    repl = dict(
        state0=wr(state.state0, chunk.state0),
        action=wr(state.action, chunk.action),
        reward=wr(state.reward, chunk.reward),
        gamma_n=wr(state.gamma_n, chunk.gamma_n),
        state1=wr(state.state1, chunk.state1),
        terminal1=wr(state.terminal1, chunk.terminal1),
        pos=(state.pos + total) % capacity,
        fill=jnp.minimum(state.fill + total, capacity),
    )
    prov_col = getattr(state, "prov", None)
    if prov_col is not None:
        n = chunk.reward.shape[0]
        repl["prov"] = wr(prov_col, (
            jnp.full((n, prov_col.shape[1]), -1, prov_col.dtype)
            if chunk.prov is None else chunk.prov.astype(prov_col.dtype)))
    return state._replace(**repl), total


def chunk_to_nhwc(chunk: Transition) -> Transition:
    """Transpose a chunk's (N, C, H, W) states to (N, H, W, C) — runs
    inside the jitted feed, so a channels-last ring pays the layout copy
    ONCE per ingested row instead of every time the row is sampled (each
    row is trained on ~replay_ratio times, and each update runs 3 CNN
    forwards that each needed the copy: ~25% of device time in the XLA
    profile, tools/mfu_probe.py)."""
    t = lambda x: jnp.transpose(x, (0, 2, 3, 1))
    return chunk._replace(state0=t(chunk.state0), state1=t(chunk.state1))


def wrap_feed_nhwc(feed_fn):
    """Single point wrapping a ring's feed with the ingest transpose —
    DeviceReplay and DevicePerReplay share it so the layout contract
    lives in one place."""
    return lambda st, ch: feed_fn(st, chunk_to_nhwc(ch))


def snapshot_states_to_nchw(out: dict) -> dict:
    """Roll a channels-last snapshot's states back to the public NCHW
    schema (checkpoints are layout-independent); shared by both ring
    classes."""
    for k in ("state0", "state1"):
        out[k] = np.ascontiguousarray(np.transpose(out[k], (0, 3, 1, 2)))
    return out


def round_capacity(capacity: int, mesh: Optional[jax.sharding.Mesh],
                   axis: str = "dp", label: str = "device replay") -> int:
    """Round capacity up to a multiple of the mesh axis so ring rows split
    evenly across devices (e.g. the default 50000 on a 32-wide mesh ->
    50016)."""
    if mesh is None:
        return capacity
    ndev = mesh.shape[axis]
    if capacity % ndev:
        rounded = capacity + ndev - capacity % ndev
        import warnings

        warnings.warn(
            f"{label} capacity {capacity} rounded up to {rounded} "
            f"(multiple of mesh {axis}={ndev})", stacklevel=3)
        return rounded
    return capacity


def sample_rows(state: ReplayState, key: jax.Array,
                batch_size: int) -> Batch:
    """Uniform on-device sampling from the ring — public so the learner and
    the driver dryrun can fuse it into their train-step programs."""
    idx = jax.random.randint(key, (batch_size,), 0, jnp.maximum(state.fill, 1))
    return Batch(
        state0=state.state0[idx],
        action=state.action[idx],
        reward=state.reward[idx],
        gamma_n=state.gamma_n[idx],
        state1=state.state1[idx],
        terminal1=state.terminal1[idx],
        weight=jnp.ones((batch_size,), dtype=jnp.float32),
        index=idx.astype(jnp.int32),
    )


def provenance_sample(state: ReplayState, key: jax.Array,
                      n: int):
    """Gather ``n`` uniformly-drawn rows' provenance columns — the
    learner's ONE small D2H per stats cadence on the device replay
    paths (n * 4 int32s; the telemetry is a distribution read, so a
    bounded sample is the whole point).  Returns ``(prov[n, 4],
    fill)``; jit with ``static_argnames='n'``."""
    idx = jax.random.randint(key, (n,), 0, jnp.maximum(state.fill, 1))
    return state.prov[idx], state.fill


def build_uniform_fused_step(step_fn, batch_size: int,
                             steps_per_call: int = 1, donate: bool = True,
                             megabatch: int = 1, megabatch_step=None):
    """One XLA program running ``steps_per_call`` sample+train steps over
    the HBM ring: ``(train_state, ring_state, keys (K, 2)) ->
    (train_state', metrics_of_last_substep)``.

    Multi-step fusion exists because program-launch latency, not chip
    compute, bounds the learner when the device sits behind a network
    tunnel (or any high-latency dispatch path): K updates per dispatch
    amortise the launch to 1/K per update.  The ring is read-only inside —
    ingest stays on the host drain cadence between dispatches.

    ``megabatch`` M > 1 (ISSUE 13, with ``megabatch_step`` from
    factory.build_megabatch_train_step) regroups the K scanned steps
    into K/M groups: each group samples its M minibatches in one
    WIDENED gather — consuming exactly the keys the sequential schedule
    would (key g*M+i draws minibatch i of group g, bit-identical index
    streams) — and runs them as one lane-filling (M*B, ...) batched
    forward/backward with sequential in-graph optimizer applies
    (ops/losses.build_dqn_megabatch_step).  Dispatch count is
    unchanged; per-update op count drops ~M-fold, which is the whole
    win on dispatch-bound families.
    """
    from pytorch_distributed_tpu.utils.health import reduce_scan_metrics

    if megabatch > 1:
        assert megabatch_step is not None, \
            "megabatch > 1 needs the factory's megabatch step"
        assert steps_per_call % megabatch == 0, (
            f"megabatch {megabatch} must divide steps_per_call "
            f"{steps_per_call}")
        groups = steps_per_call // megabatch

        def multi_mega(ts, ring_state, keys):
            gkeys = keys.reshape(groups, megabatch, *keys.shape[1:])

            def one_group(ts, kset):
                batches = jax.vmap(
                    lambda k: sample_rows(ring_state, k, batch_size))(kset)
                ts, metrics, _td, _ok = megabatch_step(ts, batches)
                return ts, metrics

            ts, metrics = jax.lax.scan(one_group, ts, gkeys)
            return ts, reduce_scan_metrics(metrics)

        return jax.jit(multi_mega, donate_argnums=(0,) if donate else ())

    def multi(ts, ring_state, keys):
        def one(ts, key):
            ts, metrics, _td = step_fn(ts, sample_rows(ring_state, key,
                                                       batch_size))
            return ts, metrics

        ts, metrics = jax.lax.scan(one, ts, keys)
        # last substep's metrics stand in for the dispatch, EXCEPT the
        # guard's skip counter, which sums over the scan
        # (utils/health.py reduce_scan_metrics)
        return ts, reduce_scan_metrics(metrics)

    return jax.jit(multi, donate_argnums=(0,) if donate else ())


class DeviceReplay:
    """Convenience stateful wrapper around the functional ring.

    ``mesh``/``axis`` shard every buffer row-wise across the data axis so
    each device holds capacity/n_dev rows of the ring and gathers ride ICI.
    """

    def __init__(self, capacity: int, state_shape: Tuple[int, ...],
                 action_shape: Tuple[int, ...] = (),
                 state_dtype=np.uint8, action_dtype=np.int32,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 axis: str = "dp", channels_last: bool = False):
        self.capacity = capacity
        self.state_shape = tuple(state_shape)
        self.action_shape = tuple(action_shape)
        self.state_dtype = jnp.dtype(state_dtype)
        self.action_dtype = jnp.dtype(action_dtype)
        self.mesh = mesh
        self.axis = axis
        # channels-last storage: rows live as (H, W, C) so the fused
        # sampler hands the CNN NHWC batches directly (model nhwc_input);
        # feeds transpose on device at ingest (chunk_to_nhwc), snapshots
        # roll back to the public NCHW schema
        self.channels_last = bool(channels_last and len(state_shape) == 3)
        self._store_shape = (tuple(state_shape[1:]) + (state_shape[0],)
                             if self.channels_last else tuple(state_shape))

        if mesh is not None:
            ndev = mesh.shape[axis]
            assert capacity % ndev == 0, (
                f"capacity {capacity} must be divisible by mesh axis "
                f"{axis}={ndev} (round it via DeviceReplayIngest.attach)")
            P = jax.sharding.PartitionSpec
            self._row_sharding = jax.sharding.NamedSharding(mesh, P(axis))
            self._scalar_sharding = jax.sharding.NamedSharding(mesh, P())
        else:
            self._row_sharding = None
            self._scalar_sharding = None

        self.state = self._init_state()
        feed = functools.partial(_feed, capacity=capacity)
        if self.channels_last:
            feed = wrap_feed_nhwc(feed)
        self._feed_fn = jax.jit(feed, donate_argnums=0)
        self._sample_fn = jax.jit(
            sample_rows, static_argnames="batch_size", donate_argnums=())

    def _alloc(self, shape, dtype, sharded: bool = True):
        arr = jnp.zeros(shape, dtype=dtype)
        if self._row_sharding is not None:
            arr = jax.device_put(
                arr,
                self._row_sharding if sharded else self._scalar_sharding)
        return arr

    def _init_state(self) -> ReplayState:
        N = self.capacity
        alloc = self._alloc
        return ReplayState(
            state0=alloc((N, *self._store_shape), self.state_dtype),
            action=alloc((N, *self.action_shape), self.action_dtype),
            reward=alloc((N,), jnp.float32),
            gamma_n=alloc((N,), jnp.float32),
            state1=alloc((N, *self._store_shape), self.state_dtype),
            terminal1=alloc((N,), jnp.float32),
            # -1 = unknown provenance (the zeros alloc carries the row
            # sharding; the elementwise subtract preserves it)
            prov=alloc((N, 4), jnp.int32) - 1,
            pos=alloc((), jnp.int32, sharded=False),
            fill=alloc((), jnp.int32, sharded=False),
        )

    @property
    def size(self) -> int:
        return int(self.state.fill)

    # -- checkpoint (utils/checkpoint.py save_replay/load_replay) -----------

    def snapshot(self) -> dict:
        """Pull the valid HBM rows to host in AGE order (when full, the
        cursor points at the oldest row; before that, [0, fill) is already
        oldest-first).  Channels-last rings roll back to the public NCHW
        schema so checkpoints are layout-independent."""
        st = jax.device_get(self.state)
        fill, pos = int(st.fill), int(st.pos)
        shift = -pos if fill == self.capacity else 0
        out = {k: np.roll(np.asarray(getattr(st, k)), shift,
                          axis=0)[:fill].copy()
               for k in REPLAY_FIELDS}
        out["prov"] = np.roll(np.asarray(st.prov), shift,
                              axis=0)[:fill].astype(np.int64)
        if self.channels_last:
            out = snapshot_states_to_nchw(out)
        return out

    def restore(self, data: dict) -> int:
        """Refill via the normal chunked write path (works across capacity
        changes, keeps the newest rows that fit).  Returns rows restored.

        Replaces any existing contents — the ring is re-initialised first so
        restore has the same overwrite-[:n] semantics as the host-side
        replays (SharedReplay/PrioritizedReplay) rather than appending at
        the current cursor."""
        if self.size:
            self.state = self._init_state()
        rows = np.asarray(data["reward"])
        n = min(len(rows), self.capacity)
        if n:
            self.feed_chunk(Transition(
                *(np.asarray(data[k])[-n:] for k in REPLAY_FIELDS),
                prov=(np.asarray(data["prov"], np.int32)[-n:]
                      if "prov" in data else None)))
        return n

    def feed_chunk(self, chunk: Transition) -> None:
        """Host->device ingest of a chunk of transitions (leading dim = chunk
        size).  Chunk sizes should be fixed (e.g. the actor flush size) to
        avoid retracing."""
        self.state = self._feed_fn(self.state, chunk)

    def sample(self, batch_size: int, key: jax.Array) -> Batch:
        return self._sample_fn(self.state, key, batch_size=batch_size)


class DeviceReplayIngest:
    """Cross-process front end for a device-resident ring.

    Actors cannot address HBM, so (like PER) the device ring is
    single-owner: actors stream transitions over a spawn queue via
    ``make_feeder()`` and the learner process calls ``attach`` (after it
    owns the mesh) then ``drain()`` per step — which assembles **fixed-size
    chunks** host-side (fixed so ``feed_chunk`` never retraces) and ingests
    them with one host->device transfer each; partial chunks stay pending
    until filled.
    """

    # single-owner declaration (apexlint): the learner process owns the
    # HBM ring's ingest; actors can only reach it through make_feeder()
    __apex_mutators__ = ("drain",)
    __apex_owner__ = ("agents.learner", "memory.")

    def __init__(self, capacity: int, state_shape: Tuple[int, ...],
                 action_shape: Tuple[int, ...] = (),
                 state_dtype=np.uint8, action_dtype=np.int32,
                 chunk_size: int = 64, max_queue_chunks: int = 4096,
                 channels_last: bool = False):
        import multiprocessing as mp

        self.capacity = capacity
        self.state_shape = tuple(state_shape)
        self.action_shape = tuple(action_shape)
        self.state_dtype = np.dtype(state_dtype)
        self.action_dtype = np.dtype(action_dtype)
        self.chunk_size = chunk_size
        self.channels_last = channels_last
        # Ingest sizes, largest-first: a deep backlog moves in few large
        # transfers (one jit trace per size) instead of many chunk_size
        # ones — host->device transfer count, not bytes, is what stalls a
        # learner step when actors outpace it.  Capped at capacity: a chunk
        # larger than the ring would scatter duplicate indices, whose
        # winner XLA leaves unspecified.
        self.chunk_sizes = tuple(sorted(
            {min(s, capacity)
             for s in (chunk_size, chunk_size * 8, chunk_size * 64)},
            reverse=True))
        self.max_queue_chunks = max_queue_chunks  # backpressure bound
        self._q = mp.get_context("spawn").Queue(max_queue_chunks)
        self.replay: Optional[DeviceReplay] = None
        # second half-capacity ring under the Anakin double-buffer mode
        # (attach_halves); None on every other path
        self.replay_b: Optional[DeviceReplay] = None
        self._pending: list = []
        self._fed_total = 0
        self._validator = None  # ingest quarantine, built on first drain
        # ISSUE-11 shed policy (utils/flow.py): under
        # ``local_policy="shed"`` the host-side pending list is bounded
        # at ``max_pending_rows`` — oldest rows beyond it are dropped
        # (counted + prov-stamped into flow_counters) instead of
        # growing without bound when actors outrun the drain cadence.
        # Default "block" keeps the pre-flow behaviour: the bounded mp
        # queue is the backpressure point, pending stays unbounded.
        self._flow_params = None  # resolved lazily on first drain
        self.flow_counters: dict = {}

    def make_feeder(self, chunk: int = 16):
        from pytorch_distributed_tpu.memory.feeder import QueueFeeder

        return QueueFeeder(self._q, chunk)

    def configure_flow(self, params=None) -> None:
        """Pin the ISSUE-11 shed-vs-block policy for this ingest
        (otherwise resolved from the environment on first drain)."""
        from pytorch_distributed_tpu.utils import flow

        self._flow_params = flow.resolve_flow(params)

    def _make_replay(self, capacity: int,
                     mesh: Optional[jax.sharding.Mesh]) -> DeviceReplay:
        """One construction point for the HBM ring so ``attach`` and the
        Anakin ``attach_halves`` (and the PER subclass's overrides) can
        never diverge on geometry."""
        return DeviceReplay(
            capacity, self.state_shape, self.action_shape,
            self.state_dtype, self.action_dtype, mesh=mesh,
            channels_last=self.channels_last)

    def attach(self, mesh: Optional[jax.sharding.Mesh] = None
               ) -> DeviceReplay:
        """Allocate the HBM ring on the learner's mesh (geometry was fixed
        at construction by the memory factory)."""
        self.replay = self._make_replay(round_capacity(self.capacity, mesh),
                                        mesh)
        bandwidth.note_device_replay(self.replay.state)
        return self.replay

    def attach_halves(self, mesh: Optional[jax.sharding.Mesh] = None
                      ) -> Tuple[DeviceReplay, DeviceReplay]:
        """Double-buffer allocation for the co-located Anakin loop
        (agents/anakin.py, AnakinParams.double_buffer): TWO
        half-capacity rings instead of one — learner dispatches sample
        one half while rollouts scatter into the other; the driver owns
        the swap schedule.  Returns ``(half_a, half_b)``; ``half_a`` is
        also ``self.replay``, so the cross-process ingest drain (remote
        DCN rows in a hybrid topology) and the checkpoint snapshot keep
        working against half A — a documented asymmetry, not a race
        (the driver treats half A as a normal half)."""
        cap = round_capacity(max(self.capacity // 2, 1), mesh,
                             label="anakin half ring")
        self.replay = self._make_replay(cap, mesh)
        self.replay_b = self._make_replay(cap, mesh)
        bandwidth.note_device_replay(self.replay.state,
                                     self.replay_b.state)
        return self.replay, self.replay_b

    def note_scatter(self, rows: int) -> None:
        """Account rows written into the attached ring(s) by an
        in-graph scatter (the co-located Anakin rollout's replay-emit
        leg) — the zero-copy path never crosses ``drain``, so without
        this the host-side ``size``/fill reporting (fleet STATUS,
        checkpoint extras) would read a full ring as empty."""
        self._fed_total += int(rows)

    @property
    def size(self) -> int:
        # host-side accounting — no device sync in the hot loop
        assert self.replay is not None, "attach() first"
        cap = self.replay.capacity * (2 if self.replay_b is not None
                                      else 1)
        return min(self._fed_total, cap)

    # -- checkpoint: delegate to the attached HBM ring ---------------------

    def snapshot(self) -> dict:
        assert self.replay is not None, "attach() first"
        while self.drain():  # a deep backlog needs multiple capped drains
            pass
        if self._pending:
            # sub-chunk remainder: the drain cadence leaves rows below the
            # smallest preset chunk size pending; a checkpoint must not
            # lose them, so flush the remainder as one odd-sized chunk
            # (costs a single extra jit trace).
            from pytorch_distributed_tpu.utils.experience import (
                transition_dtypes,
            )

            dt = transition_dtypes(self.replay.state_dtype,
                                   self.replay.action_dtype)
            rows, self._pending = self._pending, []
            self.replay.feed_chunk(Transition(*(
                np.stack([getattr(r, f) for r in rows]).astype(dt[f])
                for f in REPLAY_FIELDS),
                prov=experience.stack_prov(rows).astype(np.int32)))
            self._fed_total += len(rows)
        return self.replay.snapshot()

    def restore(self, data: dict) -> None:
        assert self.replay is not None, "attach() first"
        self._fed_total += self.replay.restore(data)

    def close(self) -> None:
        """See QueueOwner.close: reap the queue feeder thread."""
        # discard rather than flush: leftover experience is garbage at
        # shutdown, and join_thread would block forever on a full pipe
        # nobody drains anymore
        if hasattr(self._q, "cancel_join_thread"):  # mp queue only
            self._q.cancel_join_thread()
        if hasattr(self._q, "close"):  # queue.Queue has no close
            self._q.close()

    def drain(self, max_chunks: int = 1024,
              max_rows: int = 32768) -> int:
        """Move queued transitions into HBM; bounded by ``max_rows`` per
        call so a deep backlog cannot stall the learner's update cadence —
        leftover rows carry to the next step's drain.

        Also the single-owner ingest boundary for the HBM rings, so the
        health sentinel's quarantine runs here (utils/health.py): a
        non-finite or schema-drifted row diverted to
        ``{log_dir}/quarantine/`` instead of being scattered into a ring
        every future minibatch samples from — and instead of crashing
        the learner's np.stack below on a shape drift."""
        from pytorch_distributed_tpu.memory.feeder import pop_chunks
        from pytorch_distributed_tpu.utils import flow, health, tracing
        from pytorch_distributed_tpu.utils.experience import (
            transition_dtypes,
        )

        assert self.replay is not None, "attach() first"
        items = pop_chunks(self._q, max_chunks)
        if items and health.quarantine_active():
            if self._validator is None:
                self._validator = health.ChunkValidator(
                    state_shape=self.state_shape,
                    state_dtype=self.state_dtype)
            items, bad = self._validator.filter(items)
            if bad:
                health.get_quarantine("feeder-device").put(
                    bad, trace_id=tracing.current_trace())
        self._pending.extend(t for t, _priority in items)
        if self._flow_params is None:
            self._flow_params = flow.resolve_flow()
        fp = self._flow_params
        if (fp.enabled and fp.local_policy == "shed"
                and len(self._pending) > fp.max_pending_rows):
            # the device-ingest shed point (ISSUE 11): oldest pending
            # rows beyond the bound are dropped, counted and
            # prov-stamped — newest experience wins, memory stays
            # bounded even when the drain cadence loses the race
            self._pending = flow.shed_overflow(
                self._pending, fp.max_pending_rows, self.flow_counters)
        fed = 0
        dt = transition_dtypes(self.replay.state_dtype,
                               self.replay.action_dtype)
        while fed < max_rows:
            C = next((s for s in self.chunk_sizes
                      if s <= len(self._pending)), None)
            if C is None:
                break
            rows, self._pending = self._pending[:C], self._pending[C:]
            chunk = Transition(*(
                np.stack([getattr(r, f) for r in rows]).astype(dt[f])
                for f in REPLAY_FIELDS),
                prov=experience.stack_prov(rows).astype(np.int32))
            self.replay.feed_chunk(chunk)
            fed += C
        self._fed_total += fed
        return fed


class DevicePerIngest(DeviceReplayIngest):
    """Queue front end for the HBM prioritized ring (device_per.py): same
    chunked ingestion; new rows enter at max priority, so the actor-side
    initial-priority plumbing is intentionally bypassed on this path —
    priorities live and update entirely on device."""

    def __init__(self, *args, priority_exponent: float = 0.6,
                 importance_weight: float = 0.4,
                 importance_anneal_steps: int = 500000, **kw):
        super().__init__(*args, **kw)
        self.priority_exponent = priority_exponent
        self.importance_weight = importance_weight
        self.importance_anneal_steps = importance_anneal_steps

    def _make_replay(self, capacity: int,
                     mesh: Optional[jax.sharding.Mesh]):
        from pytorch_distributed_tpu.memory.device_per import DevicePerReplay

        return DevicePerReplay(
            capacity, self.state_shape, self.action_shape,
            self.state_dtype, self.action_dtype,
            priority_exponent=self.priority_exponent,
            importance_weight=self.importance_weight,
            importance_anneal_steps=self.importance_anneal_steps,
            mesh=mesh, channels_last=self.channels_last)
