"""HBM-resident replay buffer as a jitted functional ring buffer.

TPU-native upgrade over the host shared-memory plane (SURVEY.md §7 step 4):
the six transition arrays live in device HBM as jax Arrays, optionally
sharded over the learner mesh's data axis, so sampling a minibatch never
crosses the host-device boundary — the learner consumes batches straight
from HBM and actors only pay one host->device transfer per *feed chunk*
(amortised), not per sampled batch.

Functional design: the buffer is a ``ReplayState`` pytree; ``feed`` and
``sample`` are jit-compiled pure functions with donated state so XLA updates
the rings in place.  Capacity is statically padded; the write cursor wraps
with modular index arithmetic (the jit-safe equivalent of the reference's
circular cursor, reference core/memories/shared_memory.py:45-57).

No reference equivalent — the reference buffer is host memory only.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.utils.experience import Batch, Transition


class ReplayState(NamedTuple):
    state0: jax.Array
    action: jax.Array
    reward: jax.Array
    gamma_n: jax.Array
    state1: jax.Array
    terminal1: jax.Array
    pos: jax.Array        # int32 write cursor
    fill: jax.Array       # int32 number of valid rows


def _feed(state: ReplayState, chunk: Transition, capacity: int) -> ReplayState:
    n = chunk.reward.shape[0]
    idx = (state.pos + jnp.arange(n, dtype=jnp.int32)) % capacity
    return ReplayState(
        state0=state.state0.at[idx].set(chunk.state0),
        action=state.action.at[idx].set(chunk.action),
        reward=state.reward.at[idx].set(chunk.reward),
        gamma_n=state.gamma_n.at[idx].set(chunk.gamma_n),
        state1=state.state1.at[idx].set(chunk.state1),
        terminal1=state.terminal1.at[idx].set(chunk.terminal1),
        pos=(state.pos + n) % capacity,
        fill=jnp.minimum(state.fill + n, capacity),
    )


def _sample(state: ReplayState, key: jax.Array, batch_size: int) -> Batch:
    idx = jax.random.randint(key, (batch_size,), 0, jnp.maximum(state.fill, 1))
    return Batch(
        state0=state.state0[idx],
        action=state.action[idx],
        reward=state.reward[idx],
        gamma_n=state.gamma_n[idx],
        state1=state.state1[idx],
        terminal1=state.terminal1[idx],
        weight=jnp.ones((batch_size,), dtype=jnp.float32),
        index=idx.astype(jnp.int32),
    )


class DeviceReplay:
    """Convenience stateful wrapper around the functional ring.

    ``mesh``/``axis`` shard every buffer row-wise across the data axis so
    each device holds capacity/n_dev rows of the ring and gathers ride ICI.
    """

    def __init__(self, capacity: int, state_shape: Tuple[int, ...],
                 action_shape: Tuple[int, ...] = (),
                 state_dtype=np.uint8, action_dtype=np.int32,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 axis: str = "dp"):
        self.capacity = capacity
        self.state_shape = tuple(state_shape)
        self.action_shape = tuple(action_shape)
        self.state_dtype = jnp.dtype(state_dtype)
        self.action_dtype = jnp.dtype(action_dtype)
        self.mesh = mesh
        self.axis = axis

        if mesh is not None:
            ndev = mesh.shape[axis]
            assert capacity % ndev == 0, (
                f"capacity {capacity} must divide mesh axis {axis}={ndev}")
            P = jax.sharding.PartitionSpec
            self._row_sharding = jax.sharding.NamedSharding(mesh, P(axis))
            self._scalar_sharding = jax.sharding.NamedSharding(mesh, P())
        else:
            self._row_sharding = None
            self._scalar_sharding = None

        self.state = self._init_state()
        self._feed_fn = jax.jit(
            functools.partial(_feed, capacity=capacity), donate_argnums=0)
        self._sample_fn = jax.jit(
            _sample, static_argnames="batch_size", donate_argnums=())

    def _init_state(self) -> ReplayState:
        N = self.capacity

        def alloc(shape, dtype, sharded=True):
            arr = jnp.zeros(shape, dtype=dtype)
            if self._row_sharding is not None:
                arr = jax.device_put(
                    arr, self._row_sharding if sharded else self._scalar_sharding)
            return arr

        return ReplayState(
            state0=alloc((N, *self.state_shape), self.state_dtype),
            action=alloc((N, *self.action_shape), self.action_dtype),
            reward=alloc((N,), jnp.float32),
            gamma_n=alloc((N,), jnp.float32),
            state1=alloc((N, *self.state_shape), self.state_dtype),
            terminal1=alloc((N,), jnp.float32),
            pos=alloc((), jnp.int32, sharded=False),
            fill=alloc((), jnp.int32, sharded=False),
        )

    @property
    def size(self) -> int:
        return int(self.state.fill)

    def feed_chunk(self, chunk: Transition) -> None:
        """Host->device ingest of a chunk of transitions (leading dim = chunk
        size).  Chunk sizes should be fixed (e.g. the actor flush size) to
        avoid retracing."""
        self.state = self._feed_fn(self.state, chunk)

    def sample(self, batch_size: int, key: jax.Array) -> Batch:
        return self._sample_fn(self.state, key, batch_size=batch_size)
