"""Sharded prioritized replay: one fault-fenced priority plane across
gateway hosts (ISSUE 20).

Ape-X's single global prioritized replay stops scaling at one host's HBM
and ingest bandwidth; the INES topology (PAPERS.md "In-Network Experience
Sampling") samples where experience LANDS instead of shipping raw
transitions to a central buffer.  Here each gateway host owns a replay
ring SHARD — a whole ``PrioritizedReplay`` with its own sum/min trees —
and the learner samples through a TWO-LEVEL tree:

- **level 1, learner-side** (``ShardedReplayPlane``): a global
  priority-mass vector over the live shards.  One stratified draw over
  the GLOBAL mass (the same ``linspace`` + one ``rng.uniform`` call the
  single-host ``SumTree.sample`` makes, so the RNG stream is consumed
  identically) routes each sample value to the shard owning its mass
  stratum;
- **level 2, shard-local** (``LocalShard``): the existing sum-tree
  descent answers with rows + leaf priorities — the raw transitions
  never move except as sampled minibatch rows.

Fault tolerance is the first-class axis, not an afterthought:

- **Lease-fenced membership** (``ShardRegistry``, the PR-14
  ``ReplicaRegistry`` contract on the replay plane): every shard holds a
  renewable lease stamped with a monotonic GENERATION; renews carry the
  shard's mass/fill/ingest report.  A shard silent past one lease window
  is expired and FENCED — the global mass vector reconfigures and
  sampling continues over the survivors within one window.
- **Exact degradation ledger**: the expired shard's cumulative ingested
  rows move into the ``shard_lost`` bucket, so conservation stays exact
  through the loss: minted = Σ live ingested + shard_lost + dropped +
  shed + quarantined + buffered (the ISSUE-11 flow identity, extended).
- **Deterministic fenced write-back**: the plane stamps each sample with
  the per-shard generations it sampled under; |TD| write-backs are
  decoded to (shard, local-row) groups applied in ascending shard order,
  and a write-back to a shard whose generation moved (died, rejoined) is
  a COUNTED reject — never applied.  A zombie shard host can never
  resurrect stale priorities.
- **Slot-routed ingest rebalance**: transitions route to shards by actor
  slot over the live-member table; membership change rebuilds the route
  (counted), so ingest drains onto survivors without pausing.
- **Rejoin barrier** (the PR-14 epoch-barrier pattern): a REjoining
  shard re-leases at a fresh generation in a ``joining`` state — it
  receives routed ingest immediately but is excluded from the sample
  mass vector until it ``activate``s (its ring is warm), bounded by
  ``join_timeout_s``.

At ``ShardParams.shards <= 1`` the plane is off everywhere:
``factory.build_memory`` constructs the plain single-host PER, no
registry exists, no shard verb ever rides the wire, and STATUS carries
zero new fields.  A 1-shard plane, when constructed explicitly, is
BIT-identical to the single-host PER path (tests/test_shard_plane.py
oracle) — sampled indices, IS weights, priorities, and write-backs all
reduce to the same floats, which is what makes the degraded
(last-survivor) state trustworthy.

Pure stdlib+numpy — no jax — so tools/chaos_soak.py drills the whole
plane in milliseconds.  Wire codecs for the sessionless-adjacent shard
verbs (T_SSAMPLE/T_SMASS/T_SPRIO, parallel/dcn.py) live here; the
gateway stays ignorant of this module and dispatches to duck-typed
``handle_*`` methods on whatever ``shards=`` object it was wired with.
"""

from __future__ import annotations

import io
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.memory.prioritized import PrioritizedReplay
from pytorch_distributed_tpu.utils import flight_recorder
from pytorch_distributed_tpu.utils.experience import (
    PROV_NONE, REPLAY_FIELDS, Batch, Transition,
)

# ---------------------------------------------------------------------------
# params + env plane
# ---------------------------------------------------------------------------


def resolve_shard(sp=None):
    """ShardParams + ``TPU_APEX_SHARD_<FIELD>`` env overrides — the same
    override-by-env contract as the health/perf/flow/replica planes.
    Returns a NEW instance; the input is never mutated (Options rides
    spawn pickles)."""
    import dataclasses

    from pytorch_distributed_tpu.config import ShardParams

    if sp is None:
        sp = ShardParams()
    changes: Dict[str, Any] = {}
    for f in dataclasses.fields(sp):
        raw = os.environ.get("TPU_APEX_SHARD_" + f.name.upper())
        if raw is None:
            continue
        cur = getattr(sp, f.name)
        if isinstance(cur, bool):
            changes[f.name] = raw.strip().lower() not in (
                "0", "false", "off", "no", "")
        elif isinstance(cur, int) and not isinstance(cur, bool):
            changes[f.name] = int(float(raw))
        elif isinstance(cur, float):
            changes[f.name] = float(raw)
        else:
            changes[f.name] = raw.strip()
    return dataclasses.replace(sp, **changes) if changes else sp


def export_shard_env(sp) -> None:
    """Export a RESOLVED ShardParams into the environment so spawn
    children (remote shard hosts, actor mains) resolve the same plane
    the topology configured.  setdefault: an operator's explicit env
    wins."""
    import dataclasses

    for f in dataclasses.fields(sp):
        val = getattr(sp, f.name)
        if val != f.default:
            os.environ.setdefault("TPU_APEX_SHARD_" + f.name.upper(),
                                  str(val))


def sharding_active(sp=None) -> bool:
    """The one predicate every integration point keys on: > 1 configured
    shards.  False = the pre-shard code path, bit-for-bit."""
    return resolve_shard(sp).shards > 1


# ---------------------------------------------------------------------------
# wire codecs (T_SSAMPLE / T_SPRIO payloads; T_SMASS is plain JSON)
# ---------------------------------------------------------------------------

# T_SSAMPLE reply status codes (int64 ``status`` column)
SSTAT_OK = 0      # answered; mass report (+ rows when values were sent)
SSTAT_STALE = 1   # request stamped a dead generation: counted reject
SSTAT_DEAD = 2    # shard host is draining/dead: caller treats as loss
SSTAT_NOSHARD = 3  # no shard host wired on this gateway

# every savez column the shard codecs may ship, either direction (the
# declared wire schema, same contract as dcn.REPLICA_WIRE_COLUMNS; the
# pack/unpack helpers below are the only writers/readers)
SHARD_WIRE_COLUMNS = REPLAY_FIELDS + (
    "meta", "values", "status", "generation", "total", "size",
    "min_leaf", "ingested", "stale_rejected", "idx", "leaves", "prov",
    "pidx", "ptd")


def _pack_ssample(shard: int, generation: int,
                  values: Optional[np.ndarray] = None) -> bytes:
    """Sample request: ``values`` are SHARD-LOCAL mass coordinates (the
    plane already subtracted the global stratum offset).  Empty values =
    a pure mass poll (the level-1 refresh)."""
    cols = {"meta": np.asarray([shard, generation], np.int64)}
    if values is not None and len(values):
        cols["values"] = np.ascontiguousarray(values, dtype=np.float64)
    out = io.BytesIO()
    np.savez(out, **cols)
    return out.getvalue()


def _unpack_ssample(payload: bytes) -> Tuple[int, int, np.ndarray]:
    try:
        with np.load(io.BytesIO(payload)) as z:
            meta = z["meta"]
            values = z["values"] if "values" in z.files else \
                np.zeros(0, np.float64)
    except Exception as e:
        raise ConnectionError(f"unparseable SSAMPLE payload: {e!r}")
    if meta.shape != (2,) or meta.dtype.kind not in "iu":
        raise ConnectionError("malformed SSAMPLE frame: bad meta column")
    return int(meta[0]), int(meta[1]), values


def _pack_ssample_reply(status: int, generation: int = 0,
                        mass: Optional[dict] = None,
                        rows: Optional[dict] = None) -> bytes:
    cols: Dict[str, np.ndarray] = {
        "status": np.asarray([status], np.int64),
        "generation": np.asarray([generation], np.int64),
    }
    if mass is not None:
        cols["total"] = np.asarray([mass["total"]], np.float64)
        cols["size"] = np.asarray([mass["size"]], np.int64)
        cols["min_leaf"] = np.asarray([mass["min_leaf"]], np.float64)
        cols["ingested"] = np.asarray([mass["ingested"]], np.int64)
        cols["stale_rejected"] = np.asarray([mass["stale_rejected"]],
                                            np.int64)
    if rows is not None:
        cols["idx"] = np.ascontiguousarray(rows["idx"], np.int64)
        cols["leaves"] = np.ascontiguousarray(rows["leaves"], np.float64)
        cols["prov"] = np.ascontiguousarray(rows["prov"], np.int64)
        for f in REPLAY_FIELDS:
            cols[f] = np.ascontiguousarray(rows[f])
    out = io.BytesIO()
    np.savez(out, **cols)
    return out.getvalue()


def _unpack_ssample_reply(payload: bytes) -> dict:
    try:
        with np.load(io.BytesIO(payload)) as z:
            cols = {k: z[k] for k in z.files}
    except Exception as e:
        raise ConnectionError(f"unparseable SSAMPLE reply: {e!r}")
    out: Dict[str, Any] = {
        "status": int(cols["status"][0]),
        "generation": int(cols.get("generation", [0])[0]),
    }
    if "total" in cols:
        out["mass"] = {
            "total": float(cols["total"][0]),
            "size": int(cols["size"][0]),
            "min_leaf": float(cols["min_leaf"][0]),
            "ingested": int(cols["ingested"][0]),
            "stale_rejected": int(cols["stale_rejected"][0]),
        }
    if "idx" in cols:
        out["rows"] = {k: cols[k] for k in
                       ("idx", "leaves", "prov") + REPLAY_FIELDS}
    return out


def _pack_sprio(shard: int, generation: int, pidx: np.ndarray,
                ptd: np.ndarray) -> bytes:
    out = io.BytesIO()
    np.savez(out,
             meta=np.asarray([shard, generation], np.int64),
             pidx=np.ascontiguousarray(pidx, dtype=np.int32),
             ptd=np.ascontiguousarray(ptd, dtype=np.float32))
    return out.getvalue()


# ---------------------------------------------------------------------------
# the shard itself (lives on a gateway host; served over T_SSAMPLE/T_SPRIO)
# ---------------------------------------------------------------------------

class LocalShard:
    """One host's replay shard: a whole PrioritizedReplay + the fencing
    state and ledger legs the fault plane needs.  Server-side handler
    for the shard verbs (the gateway dispatches ``handle_ssample`` /
    ``handle_sprio`` to whatever ``shards=`` object it holds) AND the
    in-process shard of a loopback plane (tests, bench, the co-located
    shard-0 of a production learner host)."""

    # single-owner declaration (apexlint single-owner rule): the shard's
    # ring and trees mutate only through the plane's routed ingest, the
    # gateway's ingest path, and the fenced write-back — a second writer
    # forks the priority plane the whole design keeps singular
    __apex_mutators__ = ("feed", "write_prio", "restore")
    __apex_owner__ = ("memory.shard_plane", "parallel.dcn",
                      "agents.learner", "fleet", "tools.chaos_soak")

    def __init__(self, shard_id: int, per: PrioritizedReplay,
                 generation: int = 0):
        self.shard_id = int(shard_id)
        self.per = per
        # stamped by the registry at acquire (ShardLease/loopback build);
        # every write-back and sample request is checked against it
        self.generation = int(generation)
        # flipped by drills (and by a draining host) to model the crash:
        # a dead shard answers nothing, renews nothing, and expires
        self.alive = True
        self.ingested_rows = 0        # cumulative ledger leg
        self.stale_rejected = 0       # write-backs fenced HERE (rows)
        self._recorder = flight_recorder.get_recorder("shard")

    # -- mass report (level-1 refresh + lease renew payload) ----------------

    def mass(self) -> dict:
        return {
            "total": float(self.per.sum_tree.total),
            "size": int(self.per.size),
            "min_leaf": float(self.per.min_tree.min),
            "ingested": int(self.ingested_rows),
            "stale_rejected": int(self.stale_rejected),
        }

    # -- ingest (slot-routed by the plane / T_EXP on the shard gateway) -----

    def feed(self, transition: Transition,
             priority: Optional[float] = None) -> bool:
        if not self.alive:
            return False
        self.per.feed(transition, priority)
        self.ingested_rows += 1
        return True

    # -- level-2 sample: local find + row gather ----------------------------

    def find_rows(self, values: np.ndarray) -> dict:
        """Answer shard-local sample values with rows + leaf priorities.
        ``values`` are already in this shard's mass coordinates; the
        descent is the exact single-host ``SumTree.find``, so a 1-shard
        plane draws bit-identical indices."""
        idx = self.per.sum_tree.find(values)
        return {
            "idx": idx,
            "leaves": self.per.sum_tree.get(idx),
            "prov": self.per.prov[idx],
            **{f: getattr(self.per, f)[idx].copy()
               for f in REPLAY_FIELDS},
        }

    # -- fenced |TD| write-back --------------------------------------------

    def write_prio(self, indices: np.ndarray, priorities: np.ndarray,
                   generation: int) -> bool:
        """Apply a |TD| write-back IF ``generation`` still names this
        shard's live incarnation; a stale generation (the writer sampled
        before this shard died/rejoined) is a counted reject — the
        last-generation-wins contract, so a zombie writer can never
        resurrect pre-loss priorities."""
        if not self.alive or int(generation) != self.generation:
            self.stale_rejected += int(len(indices))
            self._recorder.record("stale-writeback-rejected",
                                  shard=self.shard_id,
                                  generation=int(generation),
                                  rows=int(len(indices)))
            return False
        self.per.update_priorities(indices, priorities)
        return True

    # -- checkpoint / oracle plumbing ---------------------------------------

    def snapshot(self) -> dict:
        return self.per.snapshot()

    def restore(self, data: dict) -> None:
        self.per.restore(data)

    # -- wire dispatch (called by DcnGateway serve threads) ------------------

    def handle_ssample(self, payload: bytes) -> bytes:
        sid, gen, values = _unpack_ssample(payload)
        if not self.alive:
            return _pack_ssample_reply(SSTAT_DEAD)
        if sid != self.shard_id:
            return _pack_ssample_reply(SSTAT_STALE)
        rows = self.find_rows(values) if len(values) else None
        return _pack_ssample_reply(SSTAT_OK, generation=self.generation,
                                   mass=self.mass(), rows=rows)

    def handle_sprio(self, payload: bytes) -> dict:
        try:
            with np.load(io.BytesIO(payload)) as z:
                meta = z["meta"]
                pidx = z["pidx"]
                ptd = z["ptd"]
        except Exception as e:
            raise ConnectionError(f"unparseable SPRIO payload: {e!r}")
        if not self.alive:
            return {"status": "dead"}
        ok = self.write_prio(pidx.astype(np.int64), ptd, int(meta[1]))
        return {"status": "ok" if ok else "stale",
                "rows": int(len(pidx))}

    def handle_smass(self, msg: dict) -> dict:
        # a shard HOST answers only the mass poll; membership actions
        # belong to the coordinator's ShardRegistry
        if str(msg.get("action", "mass")) == "mass":
            if not self.alive:
                return {"status": "dead"}
            return {"status": "ok", "shard": self.shard_id,
                    "generation": self.generation, **self.mass()}
        return {"status": "error",
                "error": "membership actions need the coordinator "
                         "gateway (this is a shard host)"}


# ---------------------------------------------------------------------------
# coordinator-side membership: lease-fenced, generation-stamped
# ---------------------------------------------------------------------------

class ShardRegistry:
    """Coordinator-side shard membership + the degradation ledger
    (ISSUE 20) — the PR-14 ``ReplicaRegistry`` lease contract on the
    replay plane, minus rounds (sampling has no barrier: the mass
    vector reconfigures and the next sample just runs over survivors).

    Leases are stamped with one monotonic GENERATION counter across the
    registry; renews carry the shard's mass/fill/ingest report, so the
    registry always holds the last-acked ledger legs.  Expiry moves the
    dead shard's cumulative ingested rows into ``shard_lost_rows`` —
    the bucket that keeps minted = ingested + dropped + shed +
    quarantined + shard_lost + buffered EXACT through the loss.  A
    rejoin (an id with a fenced past generation) enters ``joining``:
    routed ingest immediately, excluded from the sample mass vector
    until ``activate`` (the epoch-barrier pattern, replay-plane
    flavour), bounded by ``join_timeout_s``."""

    def __init__(self, params=None, writer=None):
        self.params = resolve_shard(params)
        self._cond = threading.Condition()
        self._gen = 0
        # shard -> {generation, incarnation, expires, joining, endpoint,
        #           capacity, renews, born, mass, size, fill, ingested,
        #           stale_rejected, join_deadline}
        self._members: Dict[int, Dict[str, Any]] = {}
        self._fenced_gen: Dict[int, int] = {}
        self._writer = writer
        self._last_emit = 0.0
        self._recorder = flight_recorder.get_recorder("shard-registry")
        # membership epoch: bumped on every acquire/expire/release/
        # activate — the plane rebuilds its route table when it moves
        self.route_epoch = 0
        # counters (the drill ledger: chaos_soak asserts these EXACTLY)
        self.leases_granted = 0
        self.leases_expired = 0
        self.leases_released = 0
        self.lease_fenced = 0            # double-lease evictions
        self.shard_lost_rows = 0         # ledger bucket, cumulative
        self.stale_writeback_rejected = 0  # rows fenced plane- or shard-side
        self.route_dropped = 0           # rows routed at a dead shard
        self.rebalances = 0              # membership-change route rebuilds
        self.joins_completed = 0
        self.joins_timed_out = 0

    # -- internals (all under self._cond) -----------------------------------

    def _lease_window(self) -> float:
        return max(0.05, float(self.params.lease_s))

    def _emit_locked(self, force: bool = False) -> None:
        """``replay/shard_*`` scalar rows for mission control: live
        member count (vs expected), mass skew (max shard share over the
        balanced share — 1.0 is perfect balance), and the 0/1 degraded
        flag the ``shard_membership`` DEFAULT_RULE watches.  Rate-
        limited; membership events force.  Fleets without sharding
        never construct a registry, so the series are never written and
        the rule stays silently inert there."""
        if self._writer is None:
            return
        now = time.monotonic()
        if not force and now - self._last_emit < 1.0:
            return
        self._last_emit = now
        wall = time.time()
        expected = max(1, int(self.params.shards))
        masses = [m["mass"] for m in self._members.values()
                  if not m["joining"]]
        total = float(sum(masses))
        n = max(1, len(masses))
        skew = (max(masses) / (total / n)) if total > 0 else 0.0
        try:
            self._writer.scalar("replay/shard_members",
                                float(len(self._members)), wall=wall)
            self._writer.scalar("replay/shard_mass_skew", round(skew, 4),
                                wall=wall)
            self._writer.scalar(
                "replay/shard_degraded",
                1.0 if len(self._members) < expected else 0.0, wall=wall)
            self._writer.flush()
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass

    def _expire_locked(self, now: float) -> None:
        for sid, m in list(self._members.items()):
            if m["joining"] and now > m["join_deadline"]:
                # the rejoiner never warmed up: cancel the join so the
                # plane's route stops feeding a ghost
                del self._members[sid]
                self._fenced_gen[sid] = m["generation"]
                self.joins_timed_out += 1
                self.shard_lost_rows += int(m["ingested"])
                self.route_epoch += 1
                self.rebalances += 1
                self._recorder.record("join-timeout", shard=sid,
                                      generation=m["generation"])
                self._emit_locked(force=True)
                continue
            if now > m["expires"]:
                del self._members[sid]
                self._fenced_gen[sid] = m["generation"]
                self.leases_expired += 1
                # THE degradation ledger move: the dead shard's acked
                # transitions leave the live-ingested leg and land in
                # shard_lost in the same locked step — conservation is
                # exact at every quiescent point, not eventually
                self.shard_lost_rows += int(m["ingested"])
                self.route_epoch += 1
                self.rebalances += 1
                self._recorder.record("lease-expired", shard=sid,
                                      generation=m["generation"],
                                      lost_rows=int(m["ingested"]))
                print(f"[shard] lease expired: shard {sid} (generation "
                      f"{m['generation']}, {int(m['ingested'])} rows -> "
                      f"shard_lost)", flush=True)
                self._emit_locked(force=True)

    def _live(self, sid: int, generation: int) -> bool:
        m = self._members.get(sid)
        return m is not None and m["generation"] == generation

    # -- lease verbs ---------------------------------------------------------

    def acquire(self, shard: int, incarnation: int, endpoint: str = "",
                capacity: int = 0) -> dict:
        with self._cond:
            now = time.monotonic()
            self._expire_locked(now)
            held = self._members.get(shard)
            if held is not None:
                if incarnation <= held["incarnation"]:
                    return {"status": "refused",
                            "error": f"shard {shard} already leased "
                                     f"(incarnation {incarnation} <= "
                                     f"{held['incarnation']})"}
                # double-lease: newer incarnation fences its own
                # half-open predecessor (PR-1 slot fencing, PR-14
                # replica fencing — same contract, replay plane)
                self._fenced_gen[shard] = held["generation"]
                self.lease_fenced += 1
                self.shard_lost_rows += int(held["ingested"])
                self._recorder.record("lease-fenced", shard=shard,
                                      old=held["generation"])
            self._gen += 1
            g = self._gen
            # a shard id with a fenced PAST generation is a REJOIN: it
            # enters joining (routed ingest, no sample mass) until it
            # activates — the epoch-barrier pattern.  First-ever
            # acquires are full members at once: an empty fresh shard
            # carries zero mass, so the vector excludes it naturally.
            joining = shard in self._fenced_gen
            self._members[shard] = {
                "generation": g, "incarnation": int(incarnation),
                "expires": now + self._lease_window(),
                "joining": joining, "endpoint": str(endpoint),
                "capacity": int(capacity), "renews": 0, "born": now,
                "mass": 0.0, "size": 0, "fill": 0.0, "ingested": 0,
                "stale_rejected": 0, "min_leaf": float("inf"),
                "join_deadline": now + max(self.params.join_timeout_s,
                                           self._lease_window()),
            }
            self.leases_granted += 1
            self.route_epoch += 1
            self.rebalances += 1
            self._recorder.record("lease-granted", shard=shard,
                                  generation=g, joining=joining)
            self._emit_locked(force=True)
            self._cond.notify_all()
            return {"status": "ok", "generation": g,
                    "lease_s": self._lease_window(),
                    "joining": joining,
                    "members": sorted(self._members)}

    def renew(self, shard: int, generation: int,
              report: Optional[dict] = None) -> dict:
        with self._cond:
            now = time.monotonic()
            self._expire_locked(now)
            if not self._live(shard, generation):
                return {"status": "expired"}
            m = self._members[shard]
            m["expires"] = now + self._lease_window()
            m["renews"] += 1
            if report:
                for k in ("mass", "size", "fill", "ingested",
                          "stale_rejected", "min_leaf"):
                    if k in report:
                        m[k] = report[k]
            self._emit_locked()
            return {"status": "ok", "generation": generation,
                    "joining": m["joining"],
                    "members": sorted(self._members)}

    def release(self, shard: int, generation: int) -> dict:
        with self._cond:
            if self._live(shard, generation):
                m = self._members.pop(shard)
                self._fenced_gen[shard] = m["generation"]
                self.leases_released += 1
                # a graceful release still abandons the rows (the host
                # is going away): same ledger move as expiry, so the
                # conservation identity never depends on HOW a shard
                # left
                self.shard_lost_rows += int(m["ingested"])
                self.route_epoch += 1
                self.rebalances += 1
                self._recorder.record("lease-released", shard=shard,
                                      generation=generation)
                self._emit_locked(force=True)
                self._cond.notify_all()
            return {"status": "ok"}

    def activate(self, shard: int, generation: int) -> dict:
        """A rejoiner confirms its ring is warm: it leaves ``joining``
        and its mass enters the sample vector from the next refresh."""
        with self._cond:
            if not self._live(shard, generation):
                return {"status": "expired"}
            m = self._members[shard]
            if m["joining"]:
                m["joining"] = False
                m["expires"] = time.monotonic() + self._lease_window()
                self.joins_completed += 1
                self.route_epoch += 1
                self.rebalances += 1
                self._recorder.record("join-activated", shard=shard,
                                      generation=generation)
                self._emit_locked(force=True)
                self._cond.notify_all()
            return {"status": "ok", "members": sorted(self._members)}

    # -- plane-side reads + ledger notes -------------------------------------

    def live_members(self, include_joining: bool = False) -> List[dict]:
        """Ascending-shard-id list of live members (expiry applied
        first) — the level-1 route/mass order.  ``include_joining``
        True is the INGEST view (rejoiners receive routed transitions
        while still barred from the sample vector)."""
        with self._cond:
            self._expire_locked(time.monotonic())
            return [{"shard": sid, "generation": m["generation"],
                     "endpoint": m["endpoint"],
                     "joining": m["joining"]}
                    for sid, m in sorted(self._members.items())
                    if include_joining or not m["joining"]]

    def touch(self, shard: int, generation: int,
              report: Optional[dict] = None) -> bool:
        """An answered in-process poll/ingest is proof of life — the
        loopback plane renews THROUGH its channel traffic, exactly as a
        wire shard host renews on its ingest acks."""
        return self.renew(shard, generation, report)["status"] == "ok"

    def note_stale_writeback(self, shard: int, rows: int) -> None:
        with self._cond:
            self.stale_writeback_rejected += int(rows)
            self._recorder.record("stale-writeback-rejected",
                                  shard=shard, rows=int(rows))

    def note_route_dropped(self, shard: int, rows: int) -> None:
        with self._cond:
            self.route_dropped += int(rows)
            self._recorder.record("route-dropped", shard=shard,
                                  rows=int(rows))

    # -- observability -------------------------------------------------------

    def ledger(self) -> Dict[str, int]:
        """The conservation legs this registry owns: live-acked ingest
        per shard + the loss buckets.  chaos_soak asserts
        minted == sum(ingested) + shard_lost + route_dropped (+ the
        flow plane's dropped/shed/quarantined/buffered legs) EXACTLY."""
        with self._cond:
            return {
                "ingested": int(sum(m["ingested"]
                                    for m in self._members.values())),
                "shard_lost": int(self.shard_lost_rows),
                "route_dropped": int(self.route_dropped),
                "stale_writeback_rejected":
                    int(self.stale_writeback_rejected),
            }

    def status_block(self) -> dict:
        """The gateway STATUS ``shards`` block: membership with lease
        ages, per-shard fill + priority-mass share + the rejected-stale
        ledger — tools/fleet_top.py's shards panel and the chaos
        drills' exact-counter verdicts both read this."""
        with self._cond:
            now = time.monotonic()
            sampling = [m for m in self._members.values()
                        if not m["joining"]]
            total = float(sum(m["mass"] for m in sampling))
            members = {}
            for sid, m in sorted(self._members.items()):
                members[str(sid)] = {
                    "generation": m["generation"],
                    "lease_age": round(
                        max(0.0, now - (m["expires"]
                                        - self._lease_window())), 3),
                    "joining": m["joining"],
                    "fill": round(float(m["fill"]), 4),
                    "size": int(m["size"]),
                    "mass": round(float(m["mass"]), 6),
                    "mass_share": round(m["mass"] / total, 4)
                    if (total > 0 and not m["joining"]) else 0.0,
                    "ingested": int(m["ingested"]),
                    "stale_rejected": int(m["stale_rejected"]),
                    "renews": m["renews"],
                    "endpoint": m["endpoint"],
                }
            expected = max(1, int(self.params.shards))
            n = max(1, len(sampling))
            skew = (max(m["mass"] for m in sampling) / (total / n)
                    if (sampling and total > 0) else 0.0)
            return {
                "expected": expected,
                "members": members,
                "degraded": len(members) < expected,
                "generation": self._gen,
                "mass_total": round(total, 6),
                "mass_skew": round(skew, 4),
                "counters": {
                    "leases_granted": self.leases_granted,
                    "leases_expired": self.leases_expired,
                    "leases_released": self.leases_released,
                    "lease_fenced": self.lease_fenced,
                    "shard_lost_rows": self.shard_lost_rows,
                    "stale_writeback_rejected":
                        self.stale_writeback_rejected,
                    "route_dropped": self.route_dropped,
                    "rebalances": self.rebalances,
                    "joins_completed": self.joins_completed,
                    "joins_timed_out": self.joins_timed_out,
                },
            }

    # -- wire dispatch (T_SMASS on the coordinator gateway) ------------------

    def handle_smass(self, msg: dict) -> dict:
        action = str(msg.get("action", ""))
        if action == "status":
            return {"status": "ok", "shards": self.status_block(),
                    "members": self.live_members(include_joining=True)}
        if action == "stale":
            self.note_stale_writeback(int(msg.get("shard", -1)),
                                      int(msg.get("rows", 0)))
            return {"status": "ok"}
        try:
            sid = int(msg.get("shard"))
        except (TypeError, ValueError):
            return {"status": "error", "error": "bad shard id"}
        if action == "acquire":
            return self.acquire(sid, int(msg.get("incarnation", 0)),
                                endpoint=str(msg.get("endpoint", "")),
                                capacity=int(msg.get("capacity", 0)))
        gen = int(msg.get("generation", -1))
        if action == "renew":
            return self.renew(sid, gen, msg.get("report"))
        if action == "release":
            return self.release(sid, gen)
        if action == "activate":
            return self.activate(sid, gen)
        return {"status": "error", "error": f"unknown action {action!r}"}


# ---------------------------------------------------------------------------
# channels: one surface whether the shard is in-process or across the wire
# ---------------------------------------------------------------------------

class LoopbackShardChannel:
    """In-process channel to a LocalShard — the tier-1/bench path and
    the co-located shard of a learner host.  Every answered call renews
    the shard's lease through ``registry.touch`` (served traffic is
    proof of life, the wire analog of renew-on-ack), so a drill that
    flips ``shard.alive`` sees the lease expire within one window with
    no thread machinery at all."""

    def __init__(self, shard: LocalShard, registry: ShardRegistry):
        self.shard = shard
        self.registry = registry

    def _report(self) -> dict:
        m = self.shard.mass()
        # the registry's renew report names the priority-mass leg
        # "mass" (the status/skew vocabulary); the sampler's poll keeps
        # the tree vocabulary ("total")
        m["mass"] = m["total"]
        m["fill"] = (m["size"] / self.shard.per.capacity
                     if self.shard.per.capacity else 0.0)
        return m

    def poll(self) -> Optional[dict]:
        """Mass report + generation, None when the shard is dead."""
        if not self.shard.alive:
            return None
        rep = self._report()
        self.registry.touch(self.shard.shard_id, self.shard.generation,
                            rep)
        return {"generation": self.shard.generation, **rep}

    def sample_rows(self, values: np.ndarray) -> Optional[dict]:
        if not self.shard.alive:
            return None
        return self.shard.find_rows(values)

    def write_prio(self, indices: np.ndarray, priorities: np.ndarray,
                   generation: int) -> bool:
        if not self.shard.alive:
            return False
        return self.shard.write_prio(indices, priorities, generation)

    def feed(self, transition: Transition,
             priority: Optional[float]) -> bool:
        if not self.shard.feed(transition, priority):
            return False
        self.registry.touch(self.shard.shard_id, self.shard.generation,
                            self._report())
        return True


class RemoteShardChannel:
    """Wire channel to a shard host's gateway over the sessionless-
    adjacent shard verbs (one persistent connection; errors mark the
    channel dead and the caller falls back to membership).  Ingest does
    NOT ride this channel in production — actors stream T_EXP chunks at
    the shard host directly (experience samples where it LANDS; that is
    the point of INES) — but ``feed`` exists for completeness and
    drills, shipping a one-row chunk through the same gateway ingest
    path."""

    def __init__(self, address: Tuple[str, int], shard: int,
                 generation: int, timeout: float = 5.0):
        self.address = tuple(address)
        self.shard = int(shard)
        self.generation = int(generation)
        self.timeout = timeout
        self._sock = None
        self.dead = False

    def _conn(self):
        import socket as _socket

        from pytorch_distributed_tpu.utils import bandwidth

        if self._sock is None:
            self._sock = _socket.create_connection(self.address,
                                                   timeout=self.timeout)
            self._sock.settimeout(self.timeout)
            bandwidth.register_socket(self._sock, "shard-client")
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _rpc(self, ftype: int, payload: bytes) -> bytes:
        from pytorch_distributed_tpu.parallel import dcn

        try:
            sock = self._conn()
            dcn._send_frame(sock, ftype, payload)
            rtype, reply = dcn._recv_frame(sock)
            if rtype != ftype:
                raise ConnectionError(f"expected {ftype}, got {rtype}")
            return reply
        except (ConnectionError, OSError):
            self.close()
            self.dead = True
            raise

    def poll(self) -> Optional[dict]:
        from pytorch_distributed_tpu.parallel import dcn

        try:
            rep = _unpack_ssample_reply(self._rpc(
                dcn.T_SSAMPLE, _pack_ssample(self.shard,
                                             self.generation)))
        except (ConnectionError, OSError):
            return None
        if rep["status"] != SSTAT_OK or "mass" not in rep:
            return None
        self.generation = rep["generation"]
        m = rep["mass"]
        m["fill"] = 0.0
        return {"generation": rep["generation"], **m}

    def sample_rows(self, values: np.ndarray) -> Optional[dict]:
        from pytorch_distributed_tpu.parallel import dcn

        try:
            rep = _unpack_ssample_reply(self._rpc(
                dcn.T_SSAMPLE, _pack_ssample(self.shard, self.generation,
                                             values)))
        except (ConnectionError, OSError):
            return None
        if rep["status"] != SSTAT_OK or "rows" not in rep:
            return None
        return rep["rows"]

    def write_prio(self, indices: np.ndarray, priorities: np.ndarray,
                   generation: int) -> bool:
        import json as _json

        from pytorch_distributed_tpu.parallel import dcn

        try:
            reply = self._rpc(dcn.T_SPRIO,
                              _pack_sprio(self.shard, generation,
                                          np.asarray(indices, np.int32),
                                          np.asarray(priorities,
                                                     np.float32)))
            return _json.loads(reply.decode()).get("status") == "ok"
        except (ConnectionError, OSError, ValueError):
            return False

    def feed(self, transition: Transition,
             priority: Optional[float]) -> bool:
        from pytorch_distributed_tpu.parallel import dcn

        try:
            sock = self._conn()
            dcn._send_frame(sock, dcn.T_EXP,
                            dcn.encode_chunk([(transition, priority)]))
            # the gateway acks EXP with its clock frame (the normal
            # ingest contract) — the ack is what makes renew-before-ack
            # exact: by the time we see T_CLOCK the shard host has fed
            # the row AND renewed its lease with the updated count
            rtype, _ = dcn._recv_frame(sock)
            if rtype != dcn.T_CLOCK:
                raise ConnectionError(
                    f"expected clock ack for EXP, got {rtype}")
            return True
        except (ConnectionError, OSError):
            self.close()
            self.dead = True
            return False


class ShardLease:
    """Client-side lease maintenance for a shard HOST against the
    coordinator gateway (sessionless T_SMASS round-trips — the PR-14
    lease verbs, replay flavour).  The host renews on its own cadence
    AND on every ingest ack (so the registry's per-shard ingested leg is
    exact at every quiescent point: a crash between acks loses only
    unacked — hence actor-counted — rows)."""

    def __init__(self, coordinator: Tuple[str, int], shard: int,
                 incarnation: int, endpoint: str = "",
                 capacity: int = 0, timeout: float = 5.0):
        self.coordinator = tuple(coordinator)
        self.shard = int(shard)
        self.incarnation = int(incarnation)
        self.endpoint = endpoint
        self.capacity = int(capacity)
        self.timeout = timeout
        self.generation = -1
        self.joining = False

    def _rpc(self, msg: dict) -> dict:
        import json as _json

        from pytorch_distributed_tpu.parallel import dcn

        return dcn._sessionless_rpc(
            self.coordinator, dcn.T_SMASS,
            _json.dumps(msg).encode(), self.timeout, "T_SMASS")

    def acquire(self) -> dict:
        rep = self._rpc({"action": "acquire", "shard": self.shard,
                         "incarnation": self.incarnation,
                         "endpoint": self.endpoint,
                         "capacity": self.capacity})
        if rep.get("status") != "ok":
            raise ConnectionError(f"shard lease refused: {rep}")
        self.generation = int(rep["generation"])
        self.joining = bool(rep.get("joining"))
        return rep

    def renew(self, report: Optional[dict] = None) -> bool:
        rep = self._rpc({"action": "renew", "shard": self.shard,
                         "generation": self.generation,
                         "report": report})
        return rep.get("status") == "ok"

    def activate(self) -> bool:
        rep = self._rpc({"action": "activate", "shard": self.shard,
                         "generation": self.generation})
        self.joining = False
        return rep.get("status") == "ok"

    def release(self) -> None:
        try:
            self._rpc({"action": "release", "shard": self.shard,
                       "generation": self.generation})
        except (ConnectionError, OSError):
            pass


# ---------------------------------------------------------------------------
# level 1: the learner-side two-level sampler
# ---------------------------------------------------------------------------

class ShardedReplayPlane:
    """Learner-side drop-in for ``PrioritizedReplay`` over N shard
    channels: the same ``Memory`` surface (feed/sample/
    update_priorities/snapshot/restore + the provenance and leaf reads),
    so ``QueueOwner`` wraps it unchanged and the learner loop never
    learns sharding exists.

    **Bit-parity contract** (the degraded-trust anchor): with ONE live
    shard, ``sample`` consumes the RNG identically to the single-host
    path (one ``rng.uniform`` over the same ``linspace`` strata of the
    same total mass), routes every value to that shard's unmodified
    ``SumTree.find``, and computes IS weights from the same
    size/min/total floats — so indices, weights, priorities, and
    write-backs are bit-identical to ``PrioritizedReplay`` (the
    tests/test_shard_plane.py oracle).  Global row ids are
    ``shard_id * shard_capacity + local_row`` (shard 0 = the identity),
    decoded back for the fenced write-back merge.

    **Fencing**: each sample stamps the per-shard generations it drew
    under; ``update_priorities`` groups rows by ascending shard id and
    applies each group only where the generation still stands — a group
    aimed at a died/rejoined shard is a counted reject
    (``stale_writeback_rejected``), never applied."""

    # single-owner declaration (apexlint single-owner rule): ingest and
    # priority write-back mutate N rings through one routed boundary —
    # the learner's QueueOwner drain and the learner step own it
    __apex_mutators__ = ("feed", "update_priorities", "restore")
    __apex_owner__ = ("memory.shard_plane", "memory.feeder",
                      "agents.learner", "tools.chaos_soak")

    def __init__(self, channels: Dict[int, Any], registry: ShardRegistry,
                 shard_capacity: int,
                 state_shape: Tuple[int, ...],
                 action_shape: Tuple[int, ...] = (),
                 state_dtype=np.uint8, action_dtype=np.int32,
                 importance_weight: float = 0.4,
                 importance_anneal_steps: int = 500000):
        self.channels = dict(channels)
        self.registry = registry
        self.shard_capacity = int(shard_capacity)
        expected = max(1, int(registry.params.shards))
        assert expected * self.shard_capacity < 2 ** 31, \
            "global row ids must fit the Batch.index int32 contract"
        self.state_shape = tuple(state_shape)
        self.action_shape = tuple(action_shape)
        self.state_dtype = np.dtype(state_dtype)
        self.action_dtype = np.dtype(action_dtype)
        self.beta0 = importance_weight
        self.beta_steps = importance_anneal_steps
        self._samples_drawn = 0
        self._feed_seq = 0
        self._mass: List[dict] = []       # ascending sid mass entries
        self._mass_at = 0.0
        self._sample_gens: Dict[int, int] = {}
        self._route: List[int] = []
        self._route_epoch = -1

    # -- membership-reactive plumbing ---------------------------------------

    def attach_channel(self, sid: int, channel) -> None:
        """Wire a (re)joined shard's channel — the loopback builder and
        the drill's rejoin leg call this; wire planes rebuild channels
        from membership endpoints instead."""
        self.channels[int(sid)] = channel

    def _refresh_route(self) -> None:
        # snapshot the epoch BEFORE listing members: a membership event
        # that lands between the two would otherwise be stamped as
        # already-routed (stale route, current epoch) and a rejoiner
        # could be starved of ingest forever — if the epoch moves while
        # we read, the stale stamp forces another refresh next feed
        epoch = self.registry.route_epoch
        if self._route_epoch == epoch:
            return
        live = self.registry.live_members(include_joining=True)
        self._route = [m["shard"] for m in live
                       if m["shard"] in self.channels]
        self._route_epoch = epoch

    def _refresh_mass(self, force: bool = False) -> None:
        """Rebuild the level-1 mass vector from the live members' polls.
        ``mass_refresh_s`` 0 (the default) refreshes at EVERY sample —
        exact priority proportions, and what the parity oracle needs;
        wire fleets may trade staleness for fewer round-trips."""
        now = time.monotonic()
        every = float(self.registry.params.mass_refresh_s)
        if not force and self._mass and every > 0 \
                and now - self._mass_at < every:
            return
        self._mass_at = now
        entries: List[dict] = []
        for m in self.registry.live_members():
            ch = self.channels.get(m["shard"])
            if ch is None:
                continue
            rep = ch.poll()
            if rep is None:
                # dead-but-not-yet-expired: excluded from THIS vector;
                # the lease window owns the actual membership verdict
                continue
            entries.append({"shard": m["shard"],
                            "generation": rep["generation"],
                            "total": rep["total"], "size": rep["size"],
                            "min_leaf": rep["min_leaf"]})
        self._mass = entries

    # -- Memory surface ------------------------------------------------------

    @property
    def size(self) -> int:
        self._refresh_mass(force=True)
        return int(sum(e["size"] for e in self._mass))

    @property
    def capacity(self) -> int:
        return self.shard_capacity * max(1, int(
            self.registry.params.shards))

    @property
    def beta(self) -> float:
        frac = min(1.0, self._samples_drawn / max(1, self.beta_steps))
        return self.beta0 + (1.0 - self.beta0) * frac

    def feed(self, transition: Transition,
             priority: Optional[float] = None) -> None:
        """Slot-routed ingest: the actor slot (provenance column 0, or
        an arrival counter for unattributed rows) picks a live shard
        from the route table, which rebuilds on every membership change
        (the rebalance leg).  Rows routed at a shard that died inside
        its lease window are counted ``route_dropped`` — the loopback
        analog of an unacked wire chunk."""
        self._refresh_route()
        seq = self._feed_seq
        self._feed_seq += 1
        if not self._route:
            self.registry.note_route_dropped(-1, 1)
            return
        prov = getattr(transition, "prov", None)
        slot = int(prov[0]) if prov is not None and int(prov[0]) >= 0 \
            else seq
        sid = self._route[slot % len(self._route)]
        ch = self.channels.get(sid)
        if ch is None or not ch.feed(transition, priority):
            self.registry.note_route_dropped(sid, 1)
            # the failed channel is stale until the registry notices:
            # force a route re-check on the next feed
            self._route_epoch = -1

    def sample(self, batch_size: int, rng: np.random.Generator) -> Batch:
        self._refresh_mass()
        live = self._mass
        totals = [e["total"] for e in live]
        global_total = totals[0] if len(totals) == 1 \
            else float(np.sum(np.asarray(totals, np.float64)))
        assert global_total > 0, \
            "cannot sample from an empty shard plane"
        # ONE stratified uniform draw over the global mass — the exact
        # RNG consumption of the single-host SumTree.sample, so a
        # 1-shard plane replays its stream bit-for-bit
        bounds = np.linspace(0.0, global_total, batch_size + 1)
        values = rng.uniform(bounds[:-1], bounds[1:])
        self._samples_drawn += 1
        offsets = np.concatenate(
            [[0.0], np.cumsum(np.asarray(totals, np.float64))])
        pos = np.searchsorted(offsets[1:], values, side="right")
        pos = np.minimum(pos, len(live) - 1)
        local_values = values - offsets[pos]
        idx = np.empty(batch_size, np.int64)
        leaves = np.empty(batch_size, np.float64)
        cols: Dict[str, Optional[np.ndarray]] = {
            f: None for f in REPLAY_FIELDS}
        prov = np.tile(PROV_NONE, (batch_size, 1))
        gens: Dict[int, int] = {}
        for k, entry in enumerate(live):
            mask = pos == k
            if not mask.any():
                continue
            ch = self.channels.get(entry["shard"])
            rep = None if ch is None else ch.sample_rows(
                local_values[mask])
            if rep is None:
                # the shard died between the mass poll and the row
                # fetch (sub-lease-window race): fall back to a fresh
                # vector — sampling must degrade, never deadlock
                self._refresh_mass(force=True)
                assert self._mass, "all shards lost mid-sample"
                return self.sample(batch_size, rng)
            gens[entry["shard"]] = entry["generation"]
            idx[mask] = (entry["shard"] * self.shard_capacity
                         + rep["idx"])
            leaves[mask] = rep["leaves"]
            prov[mask] = rep["prov"]
            for f in REPLAY_FIELDS:
                if cols[f] is None:
                    arr = np.asarray(rep[f])
                    cols[f] = np.empty((batch_size,) + arr.shape[1:],
                                       dtype=arr.dtype)
                cols[f][mask] = rep[f]
        probs = leaves / global_total
        size = int(sum(e["size"] for e in live))
        beta = self.beta
        weights = (size * probs) ** (-beta)
        min_prob = min(e["min_leaf"] for e in live) / global_total
        max_weight = (size * min_prob) ** (-beta)
        weights = (weights / max_weight).astype(np.float32)
        self._sample_gens = gens
        self._last_prov = prov
        return Batch(
            state0=cols["state0"], action=cols["action"],
            reward=cols["reward"], gamma_n=cols["gamma_n"],
            state1=cols["state1"], terminal1=cols["terminal1"],
            weight=weights, index=idx.astype(np.int32))

    def update_priorities(self, indices: np.ndarray,
                          priorities: np.ndarray) -> None:
        """Deterministic cross-shard |TD| write-back merge: rows decode
        to (shard, local) and apply in ascending shard id (a fixed
        order, so every replayer of this write-back sequence converges);
        groups aimed at a generation that moved are counted rejects."""
        indices = np.asarray(indices)
        priorities = np.asarray(priorities)
        sids = indices // self.shard_capacity
        local = indices % self.shard_capacity
        live = {m["shard"]: m["generation"]
                for m in self.registry.live_members(
                    include_joining=True)}
        for sid in np.unique(sids):
            mask = sids == sid
            rows = int(mask.sum())
            gen = self._sample_gens.get(int(sid))
            if gen is None or live.get(int(sid)) != gen:
                # fenced: the shard died or rejoined since this batch
                # was sampled — its rows belong to a dead incarnation
                self.registry.note_stale_writeback(int(sid), rows)
                continue
            ch = self.channels.get(int(sid))
            if ch is None or not ch.write_prio(
                    local[mask], priorities[mask], gen):
                self.registry.note_stale_writeback(int(sid), rows)

    def provenance_of(self, indices: np.ndarray) -> np.ndarray:
        """(B, 4) provenance of the LAST sampled batch's rows (the
        learner's telemetry gathers right after sample; a cross-shard
        random gather would need another round-trip for no consumer)."""
        prov = getattr(self, "_last_prov", None)
        if prov is not None and len(prov) == len(np.asarray(indices)):
            return prov
        return np.tile(PROV_NONE, (len(np.asarray(indices)), 1))

    def priority_leaves(self) -> np.ndarray:
        """Live shards' valid leaves, ascending shard id — the priority
        X-ray's input; reduces to the single ring's leaves at 1 shard."""
        out = []
        for e in self._mass or []:
            ch = self.channels.get(e["shard"])
            if isinstance(ch, LoopbackShardChannel):
                out.append(ch.shard.per.priority_leaves())
        return (np.concatenate(out) if out
                else np.zeros(0, np.float64))

    # -- checkpoint / oracle plumbing ---------------------------------------

    def snapshot(self) -> dict:
        self._refresh_mass(force=True)
        shards = {}
        for e in self._mass:
            ch = self.channels.get(e["shard"])
            if isinstance(ch, LoopbackShardChannel):
                shards[str(e["shard"])] = ch.shard.snapshot()
        return {"sharded": np.int64(1),
                "samples_drawn": np.int64(self._samples_drawn),
                "shards": shards}

    def restore(self, data: dict) -> None:
        self._samples_drawn = int(data.get("samples_drawn", 0))
        for key, snap in data.get("shards", {}).items():
            ch = self.channels.get(int(key))
            if isinstance(ch, LoopbackShardChannel):
                ch.shard.restore(snap)


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------

def build_loopback_plane(params=None, capacity: int = 1024,
                         state_shape: Tuple[int, ...] = (4,),
                         action_shape: Tuple[int, ...] = (),
                         state_dtype=np.float32, action_dtype=np.int32,
                         priority_exponent: float = 0.6,
                         importance_weight: float = 0.4,
                         importance_anneal_steps: int = 500000,
                         shard_ids: Optional[List[int]] = None,
                         writer=None):
    """N in-process shards + registry + plane — the tier-1/bench/
    co-located topology (and the substrate the wire drill's shard hosts
    reuse one shard at a time).  ``capacity`` is the GLOBAL transition
    budget, split evenly across the expected shard count; at shards=1
    the single shard gets all of it, which is what makes the plane
    bit-identical to a ``PrioritizedReplay(capacity)``."""
    sp = resolve_shard(params)
    n = max(1, int(sp.shards))
    ids = list(shard_ids) if shard_ids is not None else list(range(n))
    shard_capacity = max(1, -(-int(capacity) // n))
    registry = ShardRegistry(sp, writer=writer)
    channels: Dict[int, LoopbackShardChannel] = {}
    shards: Dict[int, LocalShard] = {}
    for sid in ids:
        per = PrioritizedReplay(
            capacity=shard_capacity, state_shape=state_shape,
            action_shape=action_shape, state_dtype=state_dtype,
            action_dtype=action_dtype,
            priority_exponent=priority_exponent,
            importance_weight=importance_weight,
            importance_anneal_steps=importance_anneal_steps)
        shard = LocalShard(sid, per)
        grant = registry.acquire(sid, incarnation=1,
                                 capacity=shard_capacity)
        shard.generation = int(grant["generation"])
        channels[sid] = LoopbackShardChannel(shard, registry)
        shards[sid] = shard
    plane = ShardedReplayPlane(
        channels, registry, shard_capacity,
        state_shape=state_shape, action_shape=action_shape,
        state_dtype=state_dtype, action_dtype=action_dtype,
        importance_weight=importance_weight,
        importance_anneal_steps=importance_anneal_steps)
    return plane, shards, registry
