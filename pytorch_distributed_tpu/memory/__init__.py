from pytorch_distributed_tpu.memory.base import Memory
from pytorch_distributed_tpu.memory.shared_replay import SharedReplay
from pytorch_distributed_tpu.memory.prioritized import PrioritizedReplay
from pytorch_distributed_tpu.memory.device_replay import DeviceReplay

__all__ = ["Memory", "SharedReplay", "PrioritizedReplay", "DeviceReplay"]
