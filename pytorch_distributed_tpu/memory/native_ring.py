"""Native lock-free shared replay (C++ ring + ctypes).

Drop-in alternative to ``SharedReplay`` backed by native/ring_buffer.cpp:
same cross-process six-array transition plane as the reference
(core/memories/shared_memory.py), but the coarse global lock the reference
holds around every feed/sample (reference :37,69-75) is replaced by an
atomic write cursor + per-row seqlocks — writers never block each other or
readers, so actor fan-out stops serialising on the replay.  Rows are packed
into one structured-dtype record so a feed is a single memcpy.

Shared pages come from a spawn-context ``mp.Array`` exactly like the Python
ring, so handles pickle across process spawns; the C++ side only ever sees
a raw pointer into the region.
"""

from __future__ import annotations

import ctypes
import multiprocessing as mp
from typing import Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.memory.base import Memory
from pytorch_distributed_tpu.utils.experience import (
    REPLAY_FIELDS, Batch, Transition,
)

_CTX = mp.get_context("spawn")


def _load():
    # the native/ package sits at the repo root next to this package
    import os
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from native.build import load_library

    lib = load_library("ring_buffer")
    u64, p = ctypes.c_uint64, ctypes.c_void_p
    lib.rb_region_bytes.argtypes = [u64, u64]
    lib.rb_region_bytes.restype = u64
    lib.rb_init.argtypes = [p, u64, u64]
    lib.rb_check.argtypes = [p, u64, u64]
    lib.rb_check.restype = ctypes.c_int
    lib.rb_total.argtypes = [p]
    lib.rb_total.restype = u64
    lib.rb_size.argtypes = [p]
    lib.rb_size.restype = u64
    lib.rb_feed.argtypes = [p, p, u64]
    lib.rb_sample.argtypes = [p, p, u64, p]
    lib.rb_sample.restype = u64
    return lib


_LIB = None


def get_lib():
    global _LIB
    if _LIB is None:
        _LIB = _load()
    return _LIB


class NativeRingReplay(Memory):
    def __init__(self, capacity: int, state_shape: Tuple[int, ...],
                 action_shape: Tuple[int, ...] = (),
                 state_dtype=np.uint8, action_dtype=np.int32):
        super().__init__(capacity, state_shape, action_shape,
                         state_dtype, action_dtype)
        lib = get_lib()  # raises NativeBuildError without a toolchain
        self.row_dtype = np.dtype([
            ("state0", self.state_dtype, self.state_shape),
            ("action", self.action_dtype, self.action_shape),
            ("reward", np.float32),
            ("gamma_n", np.float32),
            ("state1", self.state_dtype, self.state_shape),
            ("terminal1", np.float32),
        ])
        nbytes = int(lib.rb_region_bytes(capacity, self.row_dtype.itemsize))
        self._region = _CTX.Array(ctypes.c_char, nbytes, lock=False)
        lib.rb_init(self._base(), capacity, self.row_dtype.itemsize)
        self.sample_retries = 0  # torn-read retry diagnostic

    def _base(self) -> int:
        return ctypes.addressof(self._region)

    # pickles through spawn: mp.Array carries the shared pages; the child
    # re-checks the header instead of re-initialising
    def __setstate__(self, d):
        self.__dict__.update(d)
        assert get_lib().rb_check(self._base(), self.capacity,
                                  self.row_dtype.itemsize), \
            "attached region does not match ring geometry"

    # -- Memory interface ---------------------------------------------------

    @property
    def size(self) -> int:
        return int(get_lib().rb_size(self._base()))

    @property
    def total_feeds(self) -> int:
        return int(get_lib().rb_total(self._base()))

    def feed(self, transition: Transition,
             priority: Optional[float] = None) -> None:
        row = np.empty(1, dtype=self.row_dtype)
        for f in REPLAY_FIELDS:
            row[0][f] = getattr(transition, f)
        get_lib().rb_feed(self._base(), row.ctypes.data, 1)

    def feed_batch(self, ts: Transition) -> None:
        n = len(np.atleast_1d(ts.reward))
        rows = np.empty(n, dtype=self.row_dtype)
        for f in REPLAY_FIELDS:
            rows[f] = getattr(ts, f)
        get_lib().rb_feed(self._base(), rows.ctypes.data, n)

    def sample(self, batch_size: int, rng: np.random.Generator) -> Batch:
        size = self.size
        assert size > 0, "sampling from empty replay"
        idx = rng.integers(0, size, size=batch_size).astype(np.uint64)
        out = np.empty(batch_size, dtype=self.row_dtype)
        self.sample_retries += int(get_lib().rb_sample(
            self._base(), idx.ctypes.data, batch_size, out.ctypes.data))
        return Batch(
            state0=np.ascontiguousarray(out["state0"]),
            action=np.ascontiguousarray(out["action"]),
            reward=np.ascontiguousarray(out["reward"]),
            gamma_n=np.ascontiguousarray(out["gamma_n"]),
            state1=np.ascontiguousarray(out["state1"]),
            terminal1=np.ascontiguousarray(out["terminal1"]),
            weight=np.ones(batch_size, dtype=np.float32),
            index=idx.astype(np.int32),
        )
