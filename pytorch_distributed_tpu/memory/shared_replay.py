"""Cross-process shared-memory replay ring buffer (uniform sampling).

TPU-host equivalent of the reference's inter-process data plane
(reference core/memories/shared_memory.py): six preallocated flat arrays of
capacity ``memory_size`` — state0/state1 (uint8 for images, float32 for
low-dim; reference :19-24), action/reward/gamma_n/terminal (reference
:25-28) — that all actor and learner processes address directly.  Where the
reference shares torch tensors via ``.share_memory_()`` (reference :30-35),
here the backing store is ``multiprocessing.Array`` pages wrapped as numpy
views, which survive ``spawn`` pickling; the write cursor and full flag are
``mp.Value``s and one global ``mp.Lock`` serialises every feed/sample
(reference :16-17, 37, 69-75).

This is the "shared" memory_type.  The prioritized variant lives in
prioritized.py; the HBM-resident variant in device_replay.py.
"""

from __future__ import annotations

import ctypes
import multiprocessing as mp
from typing import Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.memory.base import Memory
from pytorch_distributed_tpu.utils.experience import Batch, Transition

_CTYPES = {
    np.dtype(np.uint8): ctypes.c_uint8,
    np.dtype(np.float32): ctypes.c_float,
    np.dtype(np.int32): ctypes.c_int32,
    np.dtype(np.int64): ctypes.c_int64,
}

# all shared primitives come from the spawn context — the start method the
# whole framework uses (reference main.py:13 mp.set_start_method('spawn'))
_CTX = mp.get_context("spawn")


def _shared_array(shape: Tuple[int, ...], dtype: np.dtype):
    n = int(np.prod(shape)) if shape else 1
    raw = _CTX.Array(_CTYPES[np.dtype(dtype)], n, lock=False)
    return raw


def _view(raw, shape: Tuple[int, ...], dtype: np.dtype) -> np.ndarray:
    return np.frombuffer(raw, dtype=dtype).reshape(shape)


class SharedReplay(Memory):
    def __init__(self, capacity: int, state_shape: Tuple[int, ...],
                 action_shape: Tuple[int, ...] = (),
                 state_dtype=np.uint8, action_dtype=np.int32):
        super().__init__(capacity, state_shape, action_shape,
                         state_dtype, action_dtype)
        N = capacity
        # the six-array layout (reference shared_memory.py:19-28)
        self._raw = dict(
            state0=_shared_array((N, *self.state_shape), self.state_dtype),
            action=_shared_array((N, *self.action_shape), self.action_dtype),
            reward=_shared_array((N,), np.float32),
            gamma_n=_shared_array((N,), np.float32),
            state1=_shared_array((N, *self.state_shape), self.state_dtype),
            terminal1=_shared_array((N,), np.float32),
            # provenance sidecar (ISSUE 8), -1 rows = unknown
            prov=_shared_array((N, 4), np.int64),
        )
        self._pos = _CTX.Value("l", 0, lock=False)     # reference :16
        self._full = _CTX.Value("b", 0, lock=False)    # reference :17
        self._count = _CTX.Value("l", 0, lock=False)   # total feeds (stats)
        self._lock = _CTX.Lock()                       # reference :37
        self._bind_views()
        # unwritten provenance must read as the explicit -1 sentinel
        # (mp.Array pages come zeroed, and (0, 0, 0, 0) is a VALID
        # vector); __init__ only — spawned children share these pages
        # and must never re-wipe them
        self._np_prov[:] = -1

    # -- pickling across spawn ---------------------------------------------

    def __getstate__(self):
        d = self.__dict__.copy()
        for k in list(d):
            if k.startswith("_np_"):
                del d[k]
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._bind_views()

    def _bind_views(self) -> None:
        N = self.capacity
        shapes = dict(
            state0=(N, *self.state_shape), action=(N, *self.action_shape),
            reward=(N,), gamma_n=(N,), state1=(N, *self.state_shape),
            terminal1=(N,), prov=(N, 4),
        )
        dtypes = dict(
            state0=self.state_dtype, action=self.action_dtype,
            reward=np.float32, gamma_n=np.float32,
            state1=self.state_dtype, terminal1=np.float32,
            prov=np.int64,
        )
        for k, raw in self._raw.items():
            setattr(self, f"_np_{k}", _view(raw, shapes[k], dtypes[k]))

    # -- Memory interface ---------------------------------------------------

    @property
    def size(self) -> int:
        # circular accounting (reference core/memory.py:22-26)
        return self.capacity if self._full.value else self._pos.value

    @property
    def total_feeds(self) -> int:
        return self._count.value

    def feed(self, transition: Transition,
             priority: Optional[float] = None) -> None:
        # one write at the cursor, circular (reference shared_memory.py:45-57);
        # priority accepted for interface parity and ignored — uniform replay
        with self._lock:
            i = self._pos.value
            self._np_state0[i] = transition.state0
            self._np_action[i] = transition.action
            self._np_reward[i] = transition.reward
            self._np_gamma_n[i] = transition.gamma_n
            self._np_state1[i] = transition.state1
            self._np_terminal1[i] = transition.terminal1
            self._np_prov[i] = (-1 if getattr(transition, "prov", None)
                                is None else transition.prov)
            nxt = i + 1
            if nxt >= self.capacity:
                self._full.value = 1
                nxt = 0
            self._pos.value = nxt
            self._count.value += 1

    # -- checkpoint (utils/checkpoint.py save_replay/load_replay) -----------

    def snapshot(self) -> dict:
        """Valid rows in AGE order (oldest first), atomically vs concurrent
        feeds — restore's keep-the-newest truncation depends on it.  The
        reference never checkpoints replay (SURVEY.md §5); this is the
        resume tier's replay leg."""
        with self._lock:
            n = self.size
            # when full, the cursor points at the oldest slot: roll so
            # row 0 is oldest; when not full, [0:pos) is already age order
            shift = -self._pos.value if self._full.value else 0
            out = {k: np.roll(getattr(self, f"_np_{k}"), shift, axis=0)[:n]
                   .copy() for k in self._raw}
            out["count"] = np.int64(self._count.value)
            return out

    def restore(self, data: dict) -> None:
        """Refill from a snapshot; tolerates a different capacity (keeps
        the newest rows that fit)."""
        with self._lock:
            rows = np.asarray(data["reward"])
            n = min(len(rows), self.capacity)
            for k in self._raw:
                if k == "prov" and k not in data:
                    self._np_prov[:n] = -1  # pre-provenance snapshot
                    continue
                getattr(self, f"_np_{k}")[:n] = data[k][-n:]
            # rows beyond the restored region are dead until rewritten:
            # their provenance must read unknown, not a stale vector
            self._np_prov[n:] = -1
            self._pos.value = n % self.capacity
            self._full.value = int(n == self.capacity)
            self._count.value = int(data.get("count", n))

    def sample(self, batch_size: int, rng: np.random.Generator) -> Batch:
        # uniform indices + float cast of states (reference
        # shared_memory.py:59-67); copies so the learner batch is stable
        # even while actors keep writing
        with self._lock:
            size = self.size
            assert size > 0, "sampling from empty replay"
            idx = rng.integers(0, size, size=batch_size)
            return Batch(
                state0=self._np_state0[idx].copy(),
                action=self._np_action[idx].copy(),
                reward=self._np_reward[idx].copy(),
                gamma_n=self._np_gamma_n[idx].copy(),
                state1=self._np_state1[idx].copy(),
                terminal1=self._np_terminal1[idx].copy(),
                weight=np.ones(batch_size, dtype=np.float32),
                index=idx.astype(np.int32),
            )

    def provenance_of(self, indices: np.ndarray) -> np.ndarray:
        """(B, 4) int64 provenance of the given rows; -1 rows = unknown
        (the learner's data-plane telemetry masks on ``[:, 0] >= 0``)."""
        return self._np_prov[np.asarray(indices)].copy()
