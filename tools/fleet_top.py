#!/usr/bin/env python
"""Live fleet health viewer over the DCN gateway's STATUS verb.

``top`` for the Ape-X fleet: polls the learner host's gateway
(parallel/dcn.py ``fetch_status`` — sessionless, no actor slot consumed)
and renders slot states, incarnations, heartbeat ages, restart-budget
remaining, replay fill / ingest-queue depth, and the learner step rate.

Usage:
    python tools/fleet_top.py HOST:PORT            # refresh loop (humans)
    python tools/fleet_top.py HOST:PORT --json     # one snapshot (CI)
    python tools/fleet_top.py HOST:PORT --interval 1

One-shot ``--json`` prints the raw snapshot and exits 0 (nonzero when the
gateway is unreachable) so orchestrators/CI can assert fleet health with
``fleet_top ... --json | jq``.  The refresh loop reconnects every poll,
so it keeps reporting across the gateway restarts it exists to observe.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from pytorch_distributed_tpu.parallel.dcn import fetch_status  # noqa: E402


def _fmt_age(age: Optional[float]) -> str:
    if age is None:
        return "-"
    if age < 120:
        return f"{age:.1f}s"
    return f"{age / 60:.1f}m"


def render(status: dict) -> str:
    """One snapshot as a plain-text panel (no curses: works in any
    terminal, and the --once output is diffable in CI logs)."""
    lines: List[str] = []
    step = status.get("learner_step", 0)
    rate = status.get("learner_steps_per_sec")
    lines.append(
        f"fleet @ {time.strftime('%H:%M:%S', time.localtime(status.get('wall', time.time())))}"
        f"   learner step {step}"
        + (f" ({rate:g}/s)" if rate is not None else "")
        + f"   actor steps {status.get('actor_step', 0)}"
        + ("   [STOPPING]" if status.get("stop") else ""))
    fill = status.get("replay_fill")
    parts = []
    if "replay_size" in status:
        parts.append(f"replay {status['replay_size']}"
                     + (f"/{status['replay_capacity']}"
                        if "replay_capacity" in status else "")
                     + (f" ({fill:.0%})" if fill is not None else ""))
    if "ingest_queue_depth" in status:
        parts.append(f"ingest queue {status['ingest_queue_depth']}"
                     + (f"/{status['ingest_queue_bound']}"
                        if status.get("ingest_queue_bound") else ""))
    parts.append(f"gateway up {_fmt_age(status.get('uptime'))}"
                 f" · conns {status.get('connections', 0)}"
                 f" · chunks {status.get('chunks_in', 0)}"
                 f" · fenced {status.get('fenced', 0)}")
    lines.append("  " + "   ".join(parts))
    # health sentinel (utils/health.py): guard skips / rollbacks / hang
    # kills from the learner host, quarantine counts split by boundary —
    # the gateway's per-slot counts name WHICH remote actor is poisoning
    sentinel = status.get("health_sentinel") or {}
    quarantined = status.get("quarantined") or {}
    q_local = sentinel.get("quarantined_local") or {}
    if sentinel or quarantined or status.get("frames_rejected"):
        bits = [f"skipped {sentinel.get('skipped_steps', 0)}",
                f"rollbacks {sentinel.get('rollbacks', 0)}",
                f"hang kills {sentinel.get('hang_kills', 0)}",
                f"frames rejected {status.get('frames_rejected', 0)}"]
        q_all = {**{f"local:{k}": v for k, v in q_local.items()},
                 **{f"dcn:{k}": v for k, v in quarantined.items()}}
        bits.append("quarantined "
                    + (", ".join(f"{k}={v}" for k, v in sorted(
                        q_all.items())) if q_all else "0"))
        lines.append("  health: " + " · ".join(bits))
    slots = status.get("slots", {})
    lines.append("")
    lines.append(f"  {'slot':>6} {'incarnation':>16} {'heartbeat':>10}")
    for slot in sorted(slots, key=lambda s: int(s)):
        info = slots[slot]
        lines.append(
            f"  {slot:>6} {info.get('incarnation', 0):>16} "
            f"{_fmt_age(info.get('heartbeat_age')):>10}")
    if not slots:
        lines.append("  (no remote slots connected)")
    local = status.get("local_actors", 0)
    if local:
        # remote slots' restart budgets live on their own actor hosts;
        # the gateway only sees the learner host's local supervision
        budget = status.get("local_restart_budget_remaining")
        lines.append(f"  + {local} local actor(s) on the learner host "
                     "(not DCN-attached)"
                     + (f", restart budget {budget}" if budget else ""))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/fleet_top.py",
        description="live fleet health over the DCN STATUS verb")
    ap.add_argument("gateway", help="learner host gateway as host:port")
    ap.add_argument("--json", action="store_true",
                    help="print one raw snapshot as JSON and exit "
                         "(nonzero if the gateway is unreachable)")
    ap.add_argument("--once", action="store_true",
                    help="render one panel and exit (no screen clearing)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period, seconds")
    ap.add_argument("--timeout", type=float, default=5.0,
                    help="per-probe connect/reply timeout, seconds")
    args = ap.parse_args(argv)

    host, _, port = args.gateway.rpartition(":")
    if not host or not port.isdigit():
        ap.error(f"--gateway must be host:port (got {args.gateway!r})")
    addr = (host, int(port))

    if args.json or args.once:
        try:
            status = fetch_status(addr, timeout=args.timeout)
        except (ConnectionError, OSError) as e:
            print(f"fleet_top: gateway {args.gateway} unreachable: {e}",
                  file=sys.stderr)
            return 1
        print(json.dumps(status, indent=2, sort_keys=True) if args.json
              else render(status))
        return 0

    try:
        while True:
            try:
                panel = render(fetch_status(addr, timeout=args.timeout))
            except (ConnectionError, OSError) as e:
                panel = (f"gateway {args.gateway} unreachable: {e}\n"
                         f"  (retrying every {args.interval:g}s — a "
                         f"restarting gateway comes back on its own)")
            sys.stdout.write("\x1b[2J\x1b[H" + panel + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
