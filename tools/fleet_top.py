#!/usr/bin/env python
"""Live fleet health viewer over the DCN gateway's STATUS verb.

``top`` for the Ape-X fleet: polls the learner host's gateway
(parallel/dcn.py ``fetch_status`` — sessionless, no actor slot consumed)
and renders slot states, incarnations, heartbeat ages, restart-budget
remaining, replay fill / ingest-queue depth, the learner step rate, and
— with ``TPU_APEX_PERF=1`` on the fleet — the live perf plane (MFU,
updates/s, env frames/s, memory watermarks, retrace count).

Usage:
    python tools/fleet_top.py HOST:PORT            # refresh loop (humans)
    python tools/fleet_top.py HOST:PORT --json     # one snapshot (CI)
    python tools/fleet_top.py HOST:PORT --interval 1
    python tools/fleet_top.py HOST:PORT --metrics logs/<refs>
    python tools/fleet_top.py HOST:PORT --profile learner --seconds 5

One-shot ``--json`` prints the raw snapshot and exits 0 (nonzero when the
gateway is unreachable) so orchestrators/CI can assert fleet health with
``fleet_top ... --json | jq``.  The refresh loop reconnects every poll,
so it keeps reporting across the gateway restarts it exists to observe.

``--metrics LOG_DIR`` overlays the newest perf/phase scalar rows from the
run's ``scalars.jsonl`` using an INCREMENTAL tail reader
(utils/metrics.ScalarsTail): the file is read once from the remembered
offset per refresh, so a days-long run's metrics stream never turns the
monitor into the I/O hog (re-reading the whole JSONL per refresh is
O(run)).

``--profile ROLE`` triggers one bounded XLA profiler window on the
running fleet over the sessionless ``T_PROFILE`` verb and prints the
trace directory — a real device trace without restarting anything.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from pytorch_distributed_tpu.parallel.dcn import (  # noqa: E402
    fetch_profile, fetch_status,
)
from pytorch_distributed_tpu.utils.metrics import ScalarsTail  # noqa: E402


def _fmt_age(age: Optional[float]) -> str:
    if age is None:
        return "-"
    if age < 120:
        return f"{age:.1f}s"
    return f"{age / 60:.1f}m"


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return "?"


# scalar tags the --metrics overlay keeps current (exact tags + the
# watermark prefix); everything else in the JSONL stays for plot_run/TB
_METRIC_TAGS = ("learner/mfu", "learner/updates_per_s",
                "learner/replay_ratio", "learner/ingest_queue_util",
                "actor/env_frames_per_s",
                "replay/priority_ess", "replay/priority_ess_frac")


def perf_line(status: dict,
              metrics_latest: Optional[Dict[str, float]] = None
              ) -> Optional[str]:
    """One panel line for the perf plane: STATUS ``perf`` block (the
    learner process's monitors) merged with --metrics overlay rows
    (which also cover process-separated actors)."""
    vals: Dict[str, float] = {}
    for snap in (status.get("perf") or {}).values():
        for k, v in snap.items():
            if isinstance(v, (int, float)):
                vals.setdefault(k, v)
    for k, v in (metrics_latest or {}).items():
        vals[k] = v  # JSONL rows are fresher for cross-process roles
    ups = vals.get("learner/updates_per_s",
                   status.get("learner_steps_per_sec"))
    fps = vals.get("actor/env_frames_per_s",
                   status.get("actor_frames_per_sec"))
    mfu = vals.get("learner/mfu")
    bits = []
    if mfu is not None:
        bits.append(f"mfu {mfu:.4f}")
    if ups is not None:
        bits.append(f"learner {ups:.1f} up/s")
    if fps is not None:
        bits.append(f"actors {fps:.1f} frames/s")
    rr = vals.get("learner/replay_ratio")
    if rr is not None:
        bits.append(f"replay-ratio {rr:.2f}")
    qu = vals.get("learner/ingest_queue_util")
    if qu is not None:
        bits.append(f"ingest {qu:.0%}")
    live = vals.get("perf/learner/device_live_bytes")
    peak = vals.get("perf/learner/device_peak_bytes")
    if live is None:
        live, peak = (vals.get("perf/learner/rss_bytes"),
                      vals.get("perf/learner/rss_peak_bytes"))
    if live is not None:
        bits.append(f"mem {_fmt_bytes(live)}"
                    + (f" (peak {_fmt_bytes(peak)})" if peak is not None
                       else ""))
    retr = vals.get("perf/learner/retraces", 0) + vals.get(
        "perf/actor/retraces", 0)
    if retr:
        bits.append(f"RETRACES {int(retr)}")
    tf = vals.get("perf/learner/transfers_flagged")
    if tf:
        bits.append(f"TRANSFERS {int(tf)}")
    return "  perf: " + " · ".join(bits) if bits else None


def data_values(status: dict,
                metrics_latest: Optional[Dict[str, float]] = None
                ) -> Dict[str, float]:
    """The ISSUE-8 data-plane readings (``data/*`` gauges from the
    STATUS perf block merged with the --metrics overlay) — the
    machine-readable form ``--json`` includes as a ``data`` block."""
    vals: Dict[str, float] = {}
    for snap in (status.get("perf") or {}).values():
        for k, v in snap.items():
            if isinstance(v, (int, float)):
                vals.setdefault(k, v)
    for k, v in (metrics_latest or {}).items():
        vals[k] = v
    out = {k: v for k, v in vals.items() if k.startswith("data/")}
    ess = vals.get("data/priority_ess",
                   vals.get("replay/priority_ess_frac"))
    if ess is not None:
        out.setdefault("data/priority_ess", ess)
    return out


def data_line(status: dict,
              metrics_latest: Optional[Dict[str, float]] = None
              ) -> Optional[str]:
    """One panel line for the ISSUE-8 data plane: how stale is the
    experience the learner is consuming, and is the priority
    distribution still doing useful work.  Sourced from the learner
    monitor's ``data/*`` gauges in the STATUS perf block (present with
    TPU_APEX_PERF=1) merged with the --metrics overlay."""
    vals = dict(data_values(status, metrics_latest))
    bits = []
    st = vals.get("data/staleness_p50")
    if st is not None:
        bits.append(f"staleness p50 {st:g}v")
    age = vals.get("data/sample_age_p95")
    if age is not None:
        bits.append(f"sample age p95 {age:g} steps")
    ess = vals.get("data/priority_ess")
    if ess is not None:
        bits.append(f"priority ESS {ess:.0%}")
    share = vals.get("data/top_actor_share")
    if share is not None:
        bits.append(f"top actor {share:.0%}")
    return "  data: " + " · ".join(bits) if bits else None


_SPARK = "▁▂▃▄▅▆▇█"


def spark(values: List[float]) -> str:
    """One unicode sparkline from a value list (min-max normalized; a
    constant series renders mid-band so 'flat' and 'empty' differ)."""
    if not values:
        return ""
    lo, hi = min(values), max(values)
    if hi <= lo:
        return _SPARK[3] * len(values)
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   int((v - lo) / (hi - lo) * (len(_SPARK) - 1)))]
        for v in values)


def alerts_line(status: dict) -> Optional[str]:
    """One panel line for the mission-control alert engine (ISSUE 10):
    the STATUS ``alerts`` block's per-rule states.  Firing/pending
    rules are named with their detail; an all-clear shows the rule
    count so 'no alerts configured' and 'all ok' stay distinct."""
    alerts = status.get("alerts")
    if alerts is None:
        return None
    loud = [a for a in alerts if a.get("state") in ("pending", "firing",
                                                    "resolved")]
    if not loud:
        fired = sum(a.get("fired_total", 0) for a in alerts)
        return (f"  alerts: ok ({len(alerts)} rule(s)"
                + (f", {fired} fired lifetime" if fired else "") + ")")
    bits = []
    for a in sorted(loud, key=lambda a: a.get("state") != "firing"):
        bits.append(f"{a['rule']} {a['state'].upper()} "
                    f"{_fmt_age(a.get('age'))}"
                    + (f" ({a['detail']})" if a.get("detail") else ""))
    return "  alerts: " + " · ".join(bits)


def series_lines(status: dict, max_rows: int = 5) -> List[str]:
    """Sparkline trend rows from the STATUS ``series`` block — history
    comes from the gateway-side aggregator's ring buffers, not from
    this probe re-fetching and remembering values itself (a fresh
    fleet_top shows the same trends a long-running one does)."""
    series = status.get("series") or {}
    out = []
    for tag in sorted(series)[:max_rows]:
        blk = series[tag] or {}
        vals = [p[1] for p in blk.get("points") or []
                if isinstance(p, (list, tuple)) and len(p) == 2]
        if not vals:
            continue
        latest = blk.get("latest")
        out.append(f"  ~ {tag:<28} {spark(vals):<32} "
                   + (f"{latest:g}" if isinstance(latest, (int, float))
                      else "-"))
    return out


def actor_line(status: dict) -> Optional[str]:
    """Per-actor slot line: env frames/s attributed to each LOCAL
    actor slot plus the schedule it actually runs (device / pipelined
    / batched / inline, post-downgrade) — the ISSUE-7 read of whether
    the fleet's actor plane is on the device env fleet and which slot
    is lagging.  Remote actor hosts report through their own metrics
    streams (--metrics overlay), not this block."""
    actors = status.get("actors") or {}
    if not actors:
        return None
    backends = {a.get("backend", "?") for a in actors.values()}
    backend = backends.pop() if len(backends) == 1 else "mixed"
    bits = [f"a{slot} {info.get('env_frames_per_sec', 0.0):g} f/s"
            for slot, info in sorted(actors.items(),
                                     key=lambda kv: int(kv[0]))]
    return f"  actors[{backend}]: " + " · ".join(bits)


def anakin_line(status: dict) -> Optional[str]:
    """One panel line for the ISSUE-12 co-located loop: the STATUS
    ``anakin`` block (FleetTopology._health_snapshot) — duty cycle
    (rollout share of busy time), rollout frames/s, ring fill and the
    combined-MFU read.  Present only on anakin topologies; the
    ``actors`` block is absent there by construction (no actor worker
    exists), so this line replaces the actor panel."""
    a = status.get("anakin")
    if not a:
        return None
    bits = []
    duty = a.get("duty_cycle")
    bits.append(f"duty {duty:.0%}" if duty is not None else "duty ?")
    fps = a.get("rollout_frames_per_s")
    if fps is not None:
        bits.append(f"rollout {fps:g} f/s")
    fill = a.get("replay_fill")
    if fill is not None:
        bits.append(f"ring {fill:.0%}")
    mfu = a.get("mfu")
    if mfu is not None:
        bits.append(f"mfu {mfu:.2%}")
    return "  anakin: " + " · ".join(bits)


def replicas_line(status: dict) -> Optional[str]:
    """One panel line for the ISSUE-15 multi-learner plane: the STATUS
    ``replicas`` block (gateway ReplicaRegistry.status_block) — live
    member count vs configured, generation counter, per-replica lease
    age / round / updates-per-s, and the fencing ledger.  DEGRADED is
    loud when the live membership is below the configured N."""
    r = status.get("replicas")
    if not r:
        return None
    members = r.get("members") or {}
    expected = r.get("expected", len(members))
    head = f"{len(members)}/{expected}"
    if r.get("degraded"):
        head += " DEGRADED"
    bits = [head, f"gen {r.get('generation', 0)}"]
    for rid, m in sorted(members.items(), key=lambda kv: int(kv[0])):
        piece = (f"r{rid} gen{m.get('generation')} "
                 f"lease {_fmt_age(m.get('lease_age'))} "
                 f"rnd {m.get('round', -1)}")
        ups = m.get("updates_per_s")
        if ups is not None:
            piece += f" {ups:g} up/s"
        if m.get("joining"):
            piece += " JOINING"
        bits.append(piece)
    c = r.get("counters") or {}
    fenced = (c.get("stale_grad_rejected", 0)
              + c.get("stale_prio_rejected", 0))
    bits.append(f"rounds {r.get('rounds_completed', 0)}"
                + (f" ({r.get('degraded_completions', 0)} degraded)"
                   if r.get("degraded_completions") else ""))
    if fenced or c.get("lease_fenced") or c.get("leases_expired"):
        bits.append(f"fenced writes {fenced} · expired "
                    f"{c.get('leases_expired', 0)} · evicted "
                    f"{c.get('lease_fenced', 0)}")
    return "  replicas: " + " · ".join(bits)


def shards_line(status: dict) -> Optional[str]:
    """One panel line for the ISSUE-20 sharded replay plane: the STATUS
    ``shards`` block (coordinator ShardRegistry.status_block) — live
    member count vs configured with DEGRADED loud, total priority mass
    + skew, per-shard fill / mass share / rejected-stale ledger, and
    the degradation counters (rows lost to dead shards are COUNTED,
    never silent)."""
    s = status.get("shards")
    if not s:
        return None
    members = s.get("members") or {}
    expected = s.get("expected", len(members))
    head = f"{len(members)}/{expected}"
    if s.get("degraded"):
        head += " DEGRADED"
    bits = [head,
            f"mass {s.get('mass_total', 0.0):g} "
            f"(skew {s.get('mass_skew', 0.0):g})"]
    for sid, m in sorted(members.items(), key=lambda kv: int(kv[0])):
        piece = (f"s{sid} gen{m.get('generation')} "
                 f"fill {m.get('fill', 0.0):.0%} "
                 f"share {m.get('mass_share', 0.0):.0%}")
        if m.get("stale_rejected"):
            piece += f" stale {m['stale_rejected']}"
        if m.get("joining"):
            piece += " JOINING"
        bits.append(piece)
    c = s.get("counters") or {}
    if c.get("shard_lost_rows") or c.get("leases_expired") \
            or c.get("stale_writeback_rejected") \
            or c.get("route_dropped"):
        bits.append(f"lost {c.get('shard_lost_rows', 0)} rows · "
                    f"expired {c.get('leases_expired', 0)} · "
                    f"fenced writes "
                    f"{c.get('stale_writeback_rejected', 0)} · "
                    f"route-dropped {c.get('route_dropped', 0)}")
    return "  shards: " + " · ".join(bits)


def gateway_line(status: dict) -> Optional[str]:
    """One panel line for the ISSUE-16 gateway HA plane: the STATUS
    ``gateway`` block (only present on HA-enabled fleets) — role and
    fenced term, the standby's sync offset + lag, the journal ledger,
    and the failover counters.  FENCED is loud: a fenced gateway is
    refusing every session write by design and an operator staring at
    a stalled fleet needs to see WHY at a glance."""
    g = status.get("gateway")
    if not g:
        return None
    head = f"{g.get('role', '?')} term {g.get('term', 0)}"
    if not g.get("serving", True):
        head += " (warm — sessions refused until promotion)"
    if g.get("fenced"):
        head += " FENCED"
    bits = [head]
    if g.get("role") == "standby" or not g.get("serving", True):
        bits.append(f"sync seq {g.get('sync_seq', 0)} "
                    f"lag {_fmt_age(g.get('sync_age'))}")
    bits.append(f"journal seq {g.get('journal_seq', 0)} "
                f"(+{g.get('journal_appends', 0)} this term)")
    if g.get("promotions"):
        bits.append(f"promotions {g['promotions']}")
    if g.get("failover_lost"):
        bits.append(f"failover lost {g['failover_lost']} rows (counted)")
    if g.get("term_fenced") or g.get("standby_refused"):
        bits.append(f"refused {g.get('term_fenced', 0)} stale-term · "
                    f"{g.get('standby_refused', 0)} pre-promotion")
    if g.get("recover_warnings"):
        bits.append(f"recover warnings {g['recover_warnings']}")
    return "  gateway: " + " · ".join(bits)


def flow_line(status: dict) -> Optional[str]:
    """One panel line for the ISSUE-11 flow-control plane: the STATUS
    ``flow`` block (gateway GatewayFlow.status_block) — overload state
    + brownout tier, per-slot credit grants, counted drops with each
    slot's share of the overload cost (next to the data X-ray's
    ``replay/actor_share``), and the conservation-ledger verdict."""
    f = status.get("flow")
    if not f:
        return None
    state = str(f.get("state", "?"))
    head = state.upper() if state != "healthy" else "healthy"
    if f.get("tier"):
        head += f" tier {f['tier']}"
    bits = [head, f"pressure {f.get('pressure', 0.0):g}"]
    credits = f.get("credits") or {}
    if credits:
        bits.append("credits " + " ".join(
            f"s{s}={c}" for s, c in sorted(credits.items(),
                                           key=lambda kv: int(kv[0]))))
    drops: Dict[str, int] = {}
    for s, r in (f.get("client") or {}).items():
        drops[s] = drops.get(s, 0) + int(r.get("dropped", 0))
    for s, n in (f.get("shed_rows") or {}).items():
        drops[s] = drops.get(s, 0) + int(n)
    total = sum(drops.values())
    if total:
        share = f.get("drop_share") or {}
        bits.append("dropped " + " ".join(
            f"s{s}={n}" + (f" ({share[s]:.0%})" if s in share else "")
            for s, n in sorted(drops.items(), key=lambda kv: int(kv[0]))
            if n))
    else:
        bits.append("0 dropped")
    cons = f.get("conservation") or {}
    if "balanced" in cons:
        bits.append("ledger " + ("ok" if cons["balanced"] else
                                 f"IMBALANCED ({cons.get('minted')} "
                                 f"minted vs {cons.get('accounted')} "
                                 f"accounted)"))
    return "  flow: " + " · ".join(bits)


def wire_line(status: dict) -> Optional[str]:
    """One panel line for the ISSUE-18 bandwidth X-ray: the STATUS
    ``wire`` block — per-link cumulative bytes (with tx/rx split where
    both flow), bytes/transition and bytes/round, the replay/ckpt
    gauges, and the byte-ledger verdict (IMBALANCED loud: acked bytes
    no counted gateway bucket can explain)."""
    w = status.get("wire")
    if not w:
        return None
    bits: List[str] = []
    links = w.get("links") or {}
    for lk in sorted(links):
        d = links[lk]
        bits.append(f"{lk} {_fmt_bytes(d.get('bytes', 0))}"
                    f"/{d.get('frames', 0)}f")
    bpt = w.get("bytes_per_transition")
    if bpt:
        bits.append(f"{bpt:g} B/transition")
    bpr = w.get("replica_bytes_per_round")
    if bpr:
        bits.append(f"{bpr:g} B/round")
    gauges = w.get("gauges") or {}
    if gauges.get("replay/hbm_bytes"):
        bits.append(f"replay {_fmt_bytes(gauges['replay/hbm_bytes'])}")
    if gauges.get("ckpt/epoch_bytes"):
        bits.append(f"ckpt {_fmt_bytes(gauges['ckpt/epoch_bytes'])}")
    led = w.get("ledger") or {}
    if "bytes_balanced" in led:
        bits.append("ledger " + (
            "ok" if led["bytes_balanced"] else
            f"IMBALANCED ({led.get('acked_bytes')} acked vs "
            f"{led.get('accounted_bytes')} accounted bytes)"))
    if not bits:
        return None
    return "  wire: " + " · ".join(bits)


def render(status: dict,
           metrics_latest: Optional[Dict[str, float]] = None) -> str:
    """One snapshot as a plain-text panel (no curses: works in any
    terminal, and the --once output is diffable in CI logs)."""
    lines: List[str] = []
    step = status.get("learner_step", 0)
    rate = status.get("learner_steps_per_sec")
    lines.append(
        f"fleet @ {time.strftime('%H:%M:%S', time.localtime(status.get('wall', time.time())))}"
        f"   learner step {step}"
        + (f" ({rate:g}/s)" if rate is not None else "")
        + f"   actor steps {status.get('actor_step', 0)}"
        + ("   [STOPPING]" if status.get("stop") else ""))
    fill = status.get("replay_fill")
    parts = []
    if "replay_size" in status:
        parts.append(f"replay {status['replay_size']}"
                     + (f"/{status['replay_capacity']}"
                        if "replay_capacity" in status else "")
                     + (f" ({fill:.0%})" if fill is not None else ""))
    if "ingest_queue_depth" in status:
        parts.append(f"ingest queue {status['ingest_queue_depth']}"
                     + (f"/{status['ingest_queue_bound']}"
                        if status.get("ingest_queue_bound") else ""))
    parts.append(f"gateway up {_fmt_age(status.get('uptime'))}"
                 f" · conns {status.get('connections', 0)}"
                 f" · chunks {status.get('chunks_in', 0)}"
                 f" · fenced {status.get('fenced', 0)}")
    lines.append("  " + "   ".join(parts))
    pline = perf_line(status, metrics_latest)
    if pline:
        lines.append(pline)
    dline = data_line(status, metrics_latest)
    if dline:
        lines.append(dline)
    aline = actor_line(status)
    if aline:
        lines.append(aline)
    kline = anakin_line(status)
    if kline:
        lines.append(kline)
    rline = replicas_line(status)
    if rline:
        lines.append(rline)
    sline = shards_line(status)
    if sline:
        lines.append(sline)
    gline = gateway_line(status)
    if gline:
        lines.append(gline)
    alline = alerts_line(status)
    if alline:
        lines.append(alline)
    fline = flow_line(status)
    if fline:
        lines.append(fline)
    wline = wire_line(status)
    if wline:
        lines.append(wline)
    lines.extend(series_lines(status))
    # health sentinel (utils/health.py): guard skips / rollbacks / hang
    # kills from the learner host, quarantine counts split by boundary —
    # the gateway's per-slot counts name WHICH remote actor is poisoning
    sentinel = status.get("health_sentinel") or {}
    quarantined = status.get("quarantined") or {}
    q_local = sentinel.get("quarantined_local") or {}
    if sentinel or quarantined or status.get("frames_rejected"):
        bits = [f"skipped {sentinel.get('skipped_steps', 0)}",
                f"rollbacks {sentinel.get('rollbacks', 0)}",
                f"hang kills {sentinel.get('hang_kills', 0)}",
                f"frames rejected {status.get('frames_rejected', 0)}"]
        q_all = {**{f"local:{k}": v for k, v in q_local.items()},
                 **{f"dcn:{k}": v for k, v in quarantined.items()}}
        bits.append("quarantined "
                    + (", ".join(f"{k}={v}" for k, v in sorted(
                        q_all.items())) if q_all else "0"))
        lines.append("  health: " + " · ".join(bits))
    slots = status.get("slots", {})
    lines.append("")
    lines.append(f"  {'slot':>6} {'incarnation':>16} {'heartbeat':>10}")
    for slot in sorted(slots, key=lambda s: int(s)):
        info = slots[slot]
        lines.append(
            f"  {slot:>6} {info.get('incarnation', 0):>16} "
            f"{_fmt_age(info.get('heartbeat_age')):>10}")
    if not slots:
        lines.append("  (no remote slots connected)")
    local = status.get("local_actors", 0)
    if local:
        # remote slots' restart budgets live on their own actor hosts;
        # the gateway only sees the learner host's local supervision
        budget = status.get("local_restart_budget_remaining")
        lines.append(f"  + {local} local actor(s) on the learner host "
                     "(not DCN-attached)"
                     + (f", restart budget {budget}" if budget else ""))
    return "\n".join(lines)


def _absorb_rows(latest: Dict[str, float], rows: List[dict]) -> None:
    """Keep the newest value per tag of interest (perf plane scalars +
    memory watermarks); non-scalar rows (histograms, spans) skipped."""
    for r in rows:
        tag = r.get("tag")
        if not tag or "value" not in r:
            continue
        if tag in _METRIC_TAGS or tag.startswith(("perf/", "data/")):
            latest[tag] = r["value"]


def selftest() -> int:
    """The pre-PR-gate smoke (tools/check.sh): a synthetic in-process
    gateway + mission control, probed over the REAL wire path — a
    T_METRICS push lands a series, the absence rule walks
    pending→firing, and the ``--json`` blocks (``alerts``/``series``)
    round-trip through ``fetch_status``.  No jax, seconds-scale."""
    import time as _t

    from pytorch_distributed_tpu.agents.clocks import (
        ActorStats, GlobalClock,
    )
    from pytorch_distributed_tpu.agents.param_store import ParamStore
    from pytorch_distributed_tpu.config import AlertParams, MetricsParams
    from pytorch_distributed_tpu.parallel.dcn import (
        DcnGateway, push_metrics,
    )
    from pytorch_distributed_tpu.utils import telemetry

    mission = telemetry.MissionControl(
        None, MetricsParams(enabled=True),
        AlertParams(rules="stall: learner/updates_per_s absent 0.5s"))
    gw = DcnGateway(ParamStore(4), GlobalClock(), ActorStats(),
                    put_chunk=lambda items: None, host="127.0.0.1",
                    port=0, health=lambda: mission.status_block(),
                    metrics_sink=mission.ingest_remote)
    try:
        reply = push_metrics(
            ("127.0.0.1", gw.port),
            [{"tag": "learner/updates_per_s", "value": 42.0,
              "wall": _t.time(), "step": 1, "role": "learner"}])
        assert reply.get("accepted") == 1, f"push not absorbed: {reply}"
        mission.poll()
        status = fetch_status(("127.0.0.1", gw.port))
        assert "alerts" in status and "series" in status, \
            f"STATUS missing mission blocks: {sorted(status)}"
        assert "learner/updates_per_s" in status["series"], \
            f"pushed series missing: {status['series']}"
        assert status["alerts"][0]["state"] == "ok", status["alerts"]
        json.dumps(status)  # the --json path must stay serializable
        assert alerts_line(status) and series_lines(status), \
            "panel lines did not render"
        _t.sleep(0.7)  # starve the series past the absence window
        mission.poll()
        status = fetch_status(("127.0.0.1", gw.port))
        assert status["alerts"][0]["state"] == "firing", status["alerts"]
        assert "FIRING" in (alerts_line(status) or ""), status["alerts"]
        # gateway HA panel (ISSUE 16): absent on a non-HA fleet (the
        # byte-compat contract — no new STATUS key unless enabled),
        # rendered from the block an HA gateway would publish
        assert "gateway" not in status, \
            "non-HA STATUS leaked a 'gateway' block"
        assert gateway_line(status) is None
        # bandwidth X-ray (ISSUE 18): the STATUS probe itself moved
        # frames, so a real gateway must publish a non-empty wire
        # block and the panel line must render from it
        wire = status.get("wire") or {}
        assert wire.get("links"), \
            f"STATUS missing/empty wire block: {sorted(status)}"
        assert "gateway" in wire["links"], \
            f"gateway link unaccounted: {sorted(wire['links'])}"
        wl = wire_line(status) or ""
        assert wl.startswith("  wire:"), \
            f"wire panel line did not render: {wl!r}"
        imb = dict(status, wire=dict(
            wire, ledger={"acked_bytes": 100, "accounted_bytes": 60,
                          "bytes_balanced": False}))
        assert "IMBALANCED" in (wire_line(imb) or ""), \
            "imbalanced byte ledger not loud in the wire panel line"
        ha = dict(status, gateway={
            "role": "standby", "term": 3, "serving": False,
            "fenced": False, "sync_seq": 17, "sync_age": 0.2,
            "journal_seq": 17, "journal_appends": 0, "promotions": 0,
            "failover_lost": 5, "term_fenced": 1, "standby_refused": 2,
            "recover_warnings": 0})
        gl = gateway_line(ha) or ""
        assert "standby" in gl and "term 3" in gl and "lag" in gl, \
            f"gateway panel line did not render: {gl!r}"
        json.dumps(ha)  # the --json gateway block stays serializable
        # sharded replay panel (ISSUE 20): absent on an unsharded
        # fleet (same byte-compat contract), rendered from the block
        # a sharded coordinator would publish
        assert "shards" not in status, \
            "unsharded STATUS leaked a 'shards' block"
        assert shards_line(status) is None
        sh = dict(status, shards={
            "expected": 3, "degraded": True, "generation": 4,
            "mass_total": 12.5, "mass_skew": 0.4,
            "members": {
                "0": {"generation": 2, "lease_age": 0.1,
                      "joining": False, "fill": 0.5, "size": 512,
                      "mass": 8.0, "mass_share": 0.64,
                      "ingested": 512, "stale_rejected": 3,
                      "renews": 9, "endpoint": ""},
                "2": {"generation": 4, "lease_age": 0.0,
                      "joining": True, "fill": 0.0, "size": 0,
                      "mass": 0.0, "mass_share": 0.0, "ingested": 0,
                      "stale_rejected": 0, "renews": 1,
                      "endpoint": ""}},
            "counters": {"leases_granted": 4, "leases_expired": 1,
                         "leases_released": 0, "lease_fenced": 0,
                         "shard_lost_rows": 256,
                         "stale_writeback_rejected": 3,
                         "route_dropped": 2, "rebalances": 1,
                         "joins_completed": 0, "joins_timed_out": 0}})
        shl = shards_line(sh) or ""
        assert "2/3 DEGRADED" in shl and "JOINING" in shl \
            and "lost 256 rows" in shl, \
            f"shards panel line did not render: {shl!r}"
        json.dumps(sh)  # the --json shards block stays serializable
    except AssertionError as e:
        print(f"fleet_top --selftest: FAIL: {e}", file=sys.stderr)
        return 1
    finally:
        gw.close()
    print("fleet_top --selftest: PASS (push -> aggregate -> alert -> "
          "--json blocks)", file=sys.stderr)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/fleet_top.py",
        description="live fleet health over the DCN STATUS verb")
    ap.add_argument("gateway", nargs="?", default=None,
                    help="learner host gateway as host:port")
    ap.add_argument("--selftest", action="store_true",
                    help="run the in-process alert-plane smoke "
                         "(synthetic gateway + mission control; the "
                         "tools/check.sh stage) and exit 0/1")
    ap.add_argument("--json", action="store_true",
                    help="print one raw snapshot as JSON and exit "
                         "(nonzero if the gateway is unreachable)")
    ap.add_argument("--once", action="store_true",
                    help="render one panel and exit (no screen clearing)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period, seconds")
    ap.add_argument("--timeout", type=float, default=5.0,
                    help="per-probe connect/reply timeout, seconds")
    ap.add_argument("--metrics", type=str, default=None, metavar="LOG_DIR",
                    help="overlay the newest perf scalars from this run "
                         "dir's scalars.jsonl (incremental tail reads — "
                         "O(new rows) per refresh, not O(run))")
    ap.add_argument("--profile", type=str, default=None, metavar="ROLE",
                    const="learner", nargs="?",
                    help="trigger one bounded XLA profiler window on the "
                         "running fleet (T_PROFILE verb) and print the "
                         "trace directory; ROLE defaults to learner — "
                         "the only role the gateway process can trace")
    ap.add_argument("--seconds", type=float, default=3.0,
                    help="profile window length for --profile "
                         "(server-clamped by PerfParams."
                         "profile_window_max)")
    ap.add_argument("--label", type=str, default=None,
                    help="trace label for --profile (sanitized "
                         "server-side)")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if args.gateway is None:
        ap.error("gateway (host:port) required unless --selftest")
    host, _, port = args.gateway.rpartition(":")
    if not host or not port.isdigit():
        ap.error(f"--gateway must be host:port (got {args.gateway!r})")
    addr = (host, int(port))

    if args.profile is not None:
        # the one-window lock makes "busy" a TRANSIENT reply (another
        # probe's window, or the startup prewarm still crawling through
        # the profiler's one-time init on a saturated host) — retry it
        # for the operator instead of bailing
        deadline = time.monotonic() + args.seconds + 180.0
        while True:
            try:
                reply = fetch_profile(addr, seconds=args.seconds,
                                      label=args.label,
                                      role=args.profile)
            except (ConnectionError, OSError) as e:
                print(f"fleet_top: gateway {args.gateway} unreachable: "
                      f"{e}", file=sys.stderr)
                return 1
            err = reply.get("error", "")
            transient = ("already active" in err
                         or "unavailable" in err)
            if not transient or time.monotonic() > deadline:
                break
            print(f"fleet_top: {err}; retrying...", file=sys.stderr)
            time.sleep(2.0)
        print(json.dumps(reply, indent=2, sort_keys=True))
        if "error" in reply:
            print(f"fleet_top: profile failed: {reply['error']}",
                  file=sys.stderr)
            return 1
        return 0

    tail = ScalarsTail(args.metrics) if args.metrics else None
    latest: Dict[str, float] = {}

    if args.json or args.once:
        try:
            status = fetch_status(addr, timeout=args.timeout)
        except (ConnectionError, OSError) as e:
            print(f"fleet_top: gateway {args.gateway} unreachable: {e}",
                  file=sys.stderr)
            return 1
        if tail is not None:
            _absorb_rows(latest, tail.poll())
            if args.json and latest:
                status = dict(status, metrics_latest=latest)
        if args.json:
            dvals = data_values(status, latest)
            if dvals:  # the data-plane block, CI-assertable
                status = dict(status, data=dvals)
        print(json.dumps(status, indent=2, sort_keys=True) if args.json
              else render(status, latest))
        return 0

    try:
        while True:
            if tail is not None:
                _absorb_rows(latest, tail.poll())
            try:
                panel = render(fetch_status(addr, timeout=args.timeout),
                               latest)
            except (ConnectionError, OSError) as e:
                panel = (f"gateway {args.gateway} unreachable "
                         f"(retrying): {e}\n"
                         f"  (refreshing every {args.interval:g}s — a "
                         f"restarting gateway comes back on its own; "
                         f"on an HA fleet point this monitor at the "
                         f"standby too: after failover the promoted "
                         f"standby is the one answering STATUS)")
            sys.stdout.write("\x1b[2J\x1b[H" + panel + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
