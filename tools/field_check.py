#!/usr/bin/env python
"""Field validation for the environment-gated paths.

This image ships no ALE wheel and no gym/MuJoCo, so the real-Atari and
gym env adapters (envs/atari.py, envs/gym_adapter.py — re-designs of
reference core/envs/atari_env.py:19-28 and the gym path the reference's
DDPG configs target) are contract-tested against fake modules only.  On
any machine that DOES have the wheels, this one command retires that
risk in minutes:

    python tools/field_check.py              # everything detected
    python tools/field_check.py --smoke-steps 200

For each gated CONFIGS row (0/5/7/9/10/11) whose backend is installed it

1. constructs the full Options + env via the factory,
2. resets and steps the real env for a handful of transitions, checking
   the observation contract (shape/dtype/reward/terminal types), and
3. runs a bounded-step live topology smoke (thread backend, tiny replay)
   so actor -> memory -> learner -> publish all execute against the real
   env.

Rows whose backend is missing are reported as SKIP (that is this image's
expected output); any detected backend that then fails its check exits
nonzero.  The summary is one line per row plus a final JSON line for
scripting.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import tempfile
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# gated rows: CONFIGS index -> (human label, backend probe)
GATED_ROWS = {
    0: ("dqn/atari/pong (reference row 0)", "ale"),
    5: ("dqn/atari/breakout", "ale"),
    7: ("dqn/atari/pong + host PER", "ale"),
    9: ("ddpg/gym/halfcheetah (BASELINE cfg 4)", "mujoco"),
    10: ("ddpg/gym/humanoid (BASELINE cfg 5)", "mujoco"),
    11: ("dqn/atari/breakout + HBM replay", "ale"),
}


def _has(mod: str) -> bool:
    return importlib.util.find_spec(mod) is not None


# ---------------------------------------------------------------------------
# snapshot/restore contract (ungated: runs on every machine)
# ---------------------------------------------------------------------------

def check_snapshot_restore_contract() -> dict:
    """Every memory class exposing ``snapshot`` must expose ``restore``,
    and the pair must round-trip a small buffer — the invariant the
    checkpoint-epoch subsystem (utils/checkpoint.py) builds on.  Two
    passes:

    1. reflective: walk every class defined under
       ``pytorch_distributed_tpu.memory`` and reject any that has one
       half of the surface without the other;
    2. dynamic: feed/snapshot/restore each concrete replay family and
       check size + contents survive.
    """
    import importlib
    import pkgutil

    import numpy as np

    import pytorch_distributed_tpu.memory as mempkg
    from pytorch_distributed_tpu.utils.experience import Transition

    one_sided = []
    scanned = 0
    for m in pkgutil.iter_modules(mempkg.__path__):
        mod = importlib.import_module(f"{mempkg.__name__}.{m.name}")
        for name in dir(mod):
            cls = getattr(mod, name)
            if not isinstance(cls, type) \
                    or getattr(cls, "__module__", "") != mod.__name__:
                continue
            has_snap = callable(getattr(cls, "snapshot", None))
            has_rest = callable(getattr(cls, "restore", None))
            if has_snap or has_rest:
                scanned += 1
            if has_snap != has_rest:
                one_sided.append(f"{mod.__name__}.{name}")
    assert not one_sided, (
        f"memory classes with a one-sided snapshot/restore surface "
        f"(checkpoints written there could never be read back): "
        f"{one_sided}")

    def geom(cap):
        return dict(capacity=cap, state_shape=(4,), action_shape=(),
                    state_dtype=np.uint8, action_dtype=np.int32)

    def fill(mem, n):
        rng = np.random.default_rng(0)
        for i in range(n):
            mem.feed(Transition(
                state0=rng.integers(0, 255, (4,)).astype(np.uint8),
                action=np.int32(i % 3), reward=np.float32(i),
                gamma_n=np.float32(0.99),
                state1=rng.integers(0, 255, (4,)).astype(np.uint8),
                terminal1=np.float32(0.0)), float(i % 5))

    def roundtrip(make, feed, rows_of):
        a, b = make(), make()
        feed(a)
        b.restore(a.snapshot())
        assert b.size == a.size, (type(a).__name__, b.size, a.size)
        np.testing.assert_allclose(np.sort(rows_of(b)), np.sort(rows_of(a)))

    from pytorch_distributed_tpu.memory.feeder import QueueOwner
    from pytorch_distributed_tpu.memory.prioritized import PrioritizedReplay
    from pytorch_distributed_tpu.memory.sequence_replay import (
        Segment, SequenceReplay,
    )
    from pytorch_distributed_tpu.memory.shared_replay import SharedReplay

    checked = []
    host_reward = lambda m: np.asarray(m.reward if hasattr(m, "reward")
                                       else m._np_reward)[:m.size].copy()
    for ctor in (SharedReplay, PrioritizedReplay):
        roundtrip(lambda c=ctor: c(**geom(32)), lambda m: fill(m, 20),
                  host_reward)
        checked.append(ctor.__name__)

    def feed_segments(mem, n):
        for i in range(n):
            mem.feed(Segment(
                obs=np.full((9, 4), i, np.float32),
                action=np.zeros(8, np.int32),
                reward=np.full(8, i, np.float32),
                terminal=np.zeros(8, np.float32),
                mask=np.ones(8, np.float32),
                c0=np.zeros(3, np.float32), h0=np.zeros(3, np.float32)))

    roundtrip(
        lambda: SequenceReplay(capacity=16, seq_len=8, state_shape=(4,),
                               lstm_dim=3, state_dtype=np.float32),
        lambda m: feed_segments(m, 10),
        lambda m: np.asarray(m.reward)[:m.size, 0].copy())
    checked.append("SequenceReplay")

    # drain-then-delegate: rows still queued by feeders must land in the
    # snapshot (the coordinated-epoch guarantee for single-owner
    # memories).  mp.Queue delivers through a background feeder thread,
    # so poll briefly for the pipe — in the learner the per-step drain
    # cadence absorbs this latency.
    owner = QueueOwner(SharedReplay(**geom(32)))
    feeder = owner.make_feeder(chunk=4)
    fill(feeder, 8)
    deadline = time.monotonic() + 10
    snap = owner.snapshot()
    while len(snap["reward"]) < 8 and time.monotonic() < deadline:
        time.sleep(0.05)
        snap = owner.snapshot()
    assert len(snap["reward"]) == 8, len(snap["reward"])
    owner.close()
    checked.append("QueueOwner")

    # HBM families (CPU backend here; same code path as on-device)
    from pytorch_distributed_tpu.memory.device_per import DevicePerReplay
    from pytorch_distributed_tpu.memory.device_replay import DeviceReplay
    from pytorch_distributed_tpu.memory.device_sequence import (
        DeviceSequenceReplay, SegmentChunk,
    )

    def feed_dev(mem, n):
        rng = np.random.default_rng(0)
        mem.feed_chunk(Transition(
            state0=rng.integers(0, 255, (n, 4)).astype(np.uint8),
            action=np.zeros(n, np.int32),
            reward=np.arange(n, dtype=np.float32),
            gamma_n=np.full(n, 0.99, np.float32),
            state1=rng.integers(0, 255, (n, 4)).astype(np.uint8),
            terminal1=np.zeros(n, np.float32)))

    import jax

    dev_reward = lambda m: np.asarray(
        jax.device_get(m.state.reward))[:m.size].copy()
    for ctor in (DeviceReplay, DevicePerReplay):
        roundtrip(lambda c=ctor: c(**geom(32)), lambda m: feed_dev(m, 20),
                  dev_reward)
        checked.append(ctor.__name__)

    roundtrip(
        lambda: DeviceSequenceReplay(capacity=16, seq_len=8,
                                     state_shape=(4,), lstm_dim=3,
                                     state_dtype=np.float32),
        lambda m: m.feed_chunk(SegmentChunk(
            obs=np.zeros((10, 9, 4), np.float32),
            action=np.zeros((10, 8), np.int32),
            reward=np.tile(np.arange(10, dtype=np.float32)[:, None], 8),
            terminal=np.zeros((10, 8), np.float32),
            mask=np.ones((10, 8), np.float32),
            c0=np.zeros((10, 3), np.float32),
            h0=np.zeros((10, 3), np.float32))),
        lambda m: np.asarray(
            jax.device_get(m.state.reward))[:m.size, 0].copy())
    checked.append("DeviceSequenceReplay")

    return {"scanned": scanned, "round_tripped": checked}


def detect_backends() -> dict:
    """Which gated backends exist on THIS machine."""
    out = {
        "ale": _has("ale_py") or _has("atari_py"),
        "gym": _has("gymnasium") or _has("gym"),
        "mujoco": False,
    }
    if out["gym"]:
        # MuJoCo rows additionally need the physics wheel
        out["mujoco"] = _has("mujoco") or _has("mujoco_py")
    return out


def check_env_contract(opt, steps: int = 32) -> dict:
    """Reset + step the real env; verify the observation contract the
    models are built against (factory.probe_env does the same probe at
    topology start — this goes further and actually steps)."""
    import numpy as np

    from pytorch_distributed_tpu.factory import build_env, probe_env

    spec = probe_env(opt)
    env = build_env(opt, process_ind=0)
    env.train()
    obs = env.reset()
    assert obs.shape == spec.state_shape, (obs.shape, spec.state_shape)
    rng = np.random.default_rng(0)
    reward_seen = 0.0
    terminals = 0
    for _ in range(steps):
        if spec.discrete:
            a = int(rng.integers(spec.num_actions))
        else:
            a = rng.uniform(-1, 1, size=spec.action_dim).astype(np.float32)
        obs, r, t, info = env.step(a)
        assert obs.shape == spec.state_shape
        assert np.isscalar(r) or np.ndim(r) == 0, f"reward not scalar: {r!r}"
        reward_seen += abs(float(r))
        if t:
            terminals += 1
            obs = env.reset()
    if hasattr(env, "close"):
        env.close()
    return {"state_shape": list(spec.state_shape),
            "actions": spec.num_actions if spec.discrete
            else spec.action_dim,
            "abs_reward_sum": round(reward_seen, 3),
            "terminals": terminals}


def run_topology_smoke(config: int, smoke_steps: int) -> dict:
    """Bounded live topology on the real env: thread backend (cheapest on
    a shared box), tiny replay, learner capped at ``smoke_steps``."""
    from pytorch_distributed_tpu import runtime
    from pytorch_distributed_tpu.config import build_options

    root = tempfile.mkdtemp(prefix=f"field_check_cfg{config}_")
    opt = build_options(
        config, root_dir=root, refs=f"field{config}", num_actors=1,
        num_envs_per_actor=1, steps=smoke_steps, batch_size=16,
        memory_size=2048, learn_start=64, visualize=False,
        evaluator_nepisodes=0, max_seconds=180.0, logger_freq=5)
    t0 = time.perf_counter()
    topo = runtime.train(opt, backend="thread")
    done = int(topo.clock.learner_step.value)
    # the smoke must not pass vacuously: a loaded box hitting max_seconds
    # before learn_start would otherwise report OK with zero updates
    assert done > 0, (
        f"topology smoke ran {smoke_steps} steps budget but the learner "
        f"never updated (stalled before learn_start?)")
    return {"smoke_steps": done,
            "smoke_seconds": round(time.perf_counter() - t0, 1)}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke-steps", type=int, default=100,
                    help="learner steps for the live-topology smoke")
    ap.add_argument("--rows", type=int, nargs="*", default=None,
                    help="restrict to specific CONFIGS rows")
    args = ap.parse_args()

    from pytorch_distributed_tpu.config import build_options

    backends = detect_backends()
    print(f"[field_check] detected backends: {backends}")

    results = {}
    failed = False
    # ungated: the snapshot/restore contract must hold on every machine
    try:
        snap_contract = check_snapshot_restore_contract()
        print(f"[field_check] snapshot/restore contract: OK "
              f"{snap_contract}")
    except Exception as e:  # noqa: BLE001 - report and fail the run
        failed = True
        snap_contract = {"status": "fail", "error": repr(e)}
        print(f"[field_check] snapshot/restore contract: FAIL {e!r}")
        traceback.print_exc()
    for row, (label, backend) in sorted(GATED_ROWS.items()):
        if args.rows is not None and row not in args.rows:
            continue
        if not backends.get(backend):
            print(f"[field_check] row {row:>2} {label}: SKIP "
                  f"(no {backend} backend installed)")
            results[row] = {"status": "skip", "missing": backend}
            continue
        try:
            opt = build_options(row)
            contract = check_env_contract(opt)
            smoke = run_topology_smoke(row, args.smoke_steps)
            results[row] = {"status": "ok", **contract, **smoke}
            print(f"[field_check] row {row:>2} {label}: OK {contract}")
        except Exception as e:  # noqa: BLE001 - report every row
            failed = True
            results[row] = {"status": "fail", "error": repr(e)}
            print(f"[field_check] row {row:>2} {label}: FAIL {e!r}")
            traceback.print_exc()

    print(json.dumps({"backends": backends,
                      "snapshot_contract": snap_contract,
                      "rows": results}))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
