#!/usr/bin/env python
"""Field validation for the environment-gated paths.

This image ships no ALE wheel and no gym/MuJoCo, so the real-Atari and
gym env adapters (envs/atari.py, envs/gym_adapter.py — re-designs of
reference core/envs/atari_env.py:19-28 and the gym path the reference's
DDPG configs target) are contract-tested against fake modules only.  On
any machine that DOES have the wheels, this one command retires that
risk in minutes:

    python tools/field_check.py              # everything detected
    python tools/field_check.py --smoke-steps 200

For each gated CONFIGS row (0/5/7/9/10/11) whose backend is installed it

1. constructs the full Options + env via the factory,
2. resets and steps the real env for a handful of transitions, checking
   the observation contract (shape/dtype/reward/terminal types), and
3. runs a bounded-step live topology smoke (thread backend, tiny replay)
   so actor -> memory -> learner -> publish all execute against the real
   env.

Rows whose backend is missing are reported as SKIP (that is this image's
expected output); any detected backend that then fails its check exits
nonzero.  The summary is one line per row plus a final JSON line for
scripting.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import tempfile
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# gated rows: CONFIGS index -> (human label, backend probe)
GATED_ROWS = {
    0: ("dqn/atari/pong (reference row 0)", "ale"),
    5: ("dqn/atari/breakout", "ale"),
    7: ("dqn/atari/pong + host PER", "ale"),
    9: ("ddpg/gym/halfcheetah (BASELINE cfg 4)", "mujoco"),
    10: ("ddpg/gym/humanoid (BASELINE cfg 5)", "mujoco"),
    11: ("dqn/atari/breakout + HBM replay", "ale"),
}


def _has(mod: str) -> bool:
    return importlib.util.find_spec(mod) is not None


def detect_backends() -> dict:
    """Which gated backends exist on THIS machine."""
    out = {
        "ale": _has("ale_py") or _has("atari_py"),
        "gym": _has("gymnasium") or _has("gym"),
        "mujoco": False,
    }
    if out["gym"]:
        # MuJoCo rows additionally need the physics wheel
        out["mujoco"] = _has("mujoco") or _has("mujoco_py")
    return out


def check_env_contract(opt, steps: int = 32) -> dict:
    """Reset + step the real env; verify the observation contract the
    models are built against (factory.probe_env does the same probe at
    topology start — this goes further and actually steps)."""
    import numpy as np

    from pytorch_distributed_tpu.factory import build_env, probe_env

    spec = probe_env(opt)
    env = build_env(opt, process_ind=0)
    env.train()
    obs = env.reset()
    assert obs.shape == spec.state_shape, (obs.shape, spec.state_shape)
    rng = np.random.default_rng(0)
    reward_seen = 0.0
    terminals = 0
    for _ in range(steps):
        if spec.discrete:
            a = int(rng.integers(spec.num_actions))
        else:
            a = rng.uniform(-1, 1, size=spec.action_dim).astype(np.float32)
        obs, r, t, info = env.step(a)
        assert obs.shape == spec.state_shape
        assert np.isscalar(r) or np.ndim(r) == 0, f"reward not scalar: {r!r}"
        reward_seen += abs(float(r))
        if t:
            terminals += 1
            obs = env.reset()
    if hasattr(env, "close"):
        env.close()
    return {"state_shape": list(spec.state_shape),
            "actions": spec.num_actions if spec.discrete
            else spec.action_dim,
            "abs_reward_sum": round(reward_seen, 3),
            "terminals": terminals}


def run_topology_smoke(config: int, smoke_steps: int) -> dict:
    """Bounded live topology on the real env: thread backend (cheapest on
    a shared box), tiny replay, learner capped at ``smoke_steps``."""
    from pytorch_distributed_tpu import runtime
    from pytorch_distributed_tpu.config import build_options

    root = tempfile.mkdtemp(prefix=f"field_check_cfg{config}_")
    opt = build_options(
        config, root_dir=root, refs=f"field{config}", num_actors=1,
        num_envs_per_actor=1, steps=smoke_steps, batch_size=16,
        memory_size=2048, learn_start=64, visualize=False,
        evaluator_nepisodes=0, max_seconds=180.0, logger_freq=5)
    t0 = time.perf_counter()
    topo = runtime.train(opt, backend="thread")
    done = int(topo.clock.learner_step.value)
    # the smoke must not pass vacuously: a loaded box hitting max_seconds
    # before learn_start would otherwise report OK with zero updates
    assert done > 0, (
        f"topology smoke ran {smoke_steps} steps budget but the learner "
        f"never updated (stalled before learn_start?)")
    return {"smoke_steps": done,
            "smoke_seconds": round(time.perf_counter() - t0, 1)}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke-steps", type=int, default=100,
                    help="learner steps for the live-topology smoke")
    ap.add_argument("--rows", type=int, nargs="*", default=None,
                    help="restrict to specific CONFIGS rows")
    args = ap.parse_args()

    from pytorch_distributed_tpu.config import build_options

    backends = detect_backends()
    print(f"[field_check] detected backends: {backends}")

    results = {}
    failed = False
    for row, (label, backend) in sorted(GATED_ROWS.items()):
        if args.rows is not None and row not in args.rows:
            continue
        if not backends.get(backend):
            print(f"[field_check] row {row:>2} {label}: SKIP "
                  f"(no {backend} backend installed)")
            results[row] = {"status": "skip", "missing": backend}
            continue
        try:
            opt = build_options(row)
            contract = check_env_contract(opt)
            smoke = run_topology_smoke(row, args.smoke_steps)
            results[row] = {"status": "ok", **contract, **smoke}
            print(f"[field_check] row {row:>2} {label}: OK {contract}")
        except Exception as e:  # noqa: BLE001 - report every row
            failed = True
            results[row] = {"status": "fail", "error": repr(e)}
            print(f"[field_check] row {row:>2} {label}: FAIL {e!r}")
            traceback.print_exc()

    print(json.dumps({"backends": backends, "rows": results}))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
