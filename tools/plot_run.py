#!/usr/bin/env python
"""Plot scalar series from a run's JSONL metrics stream.

The headless quick-look replacement for TensorBoard: reads
``<log_dir>/scalars.jsonl`` (utils/metrics.py format) and renders the
requested tags, one panel per tag, sharing the x-axis.

Usage:
    python tools/plot_run.py <log_dir> [--tags evaluator/avg_reward ...] \
        [--x wall|step] [--out run.png]
    python tools/plot_run.py <log_dir> --phase-breakdown actor

Defaults: the three headline tags, x = wall-clock minutes,
out = <log_dir>/run.png.

``--phase-breakdown ROLE`` renders a stacked per-phase wall-time plot
from the role's ``<role>/time_<phase>_total_ms`` rows (StepTimer drain
totals): each drain window's phase TOTALS stack to the role's busy time
in that window, so "where does the tick go" is one picture — means
can't stack (they hide call-count asymmetry), totals can, which is why
StepTimer exports them.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from pytorch_distributed_tpu.utils.metrics import read_scalars  # noqa: E402

DEFAULT_TAGS = ("evaluator/avg_reward", "learner/critic_loss",
                "actor/total_nframes")

# thin marks, recessive grid, neutral ink; blue = categorical slot 1
INK, MUTED, GRID, BLUE = "#1a1a1a", "#6b6b6b", "#e5e5e5", "#2a78d6"
# categorical fills for the stacked phase plot (same muted family as
# the line color; order = stack order)
PHASE_COLORS = ("#2a78d6", "#d6762a", "#3aa76d", "#a04bd1", "#c9365a",
                "#7a7a7a", "#b8a12e", "#2ab5c9")


def load_series(log_dir: str, tags):
    rows = read_scalars(log_dir)
    series = {t: [] for t in tags}
    t0 = min((r["wall"] for r in rows), default=None)
    for r in rows:
        # histogram rows carry p50/p95/max instead of a value — plot the
        # p95 when a histogram tag is requested, skip span rows
        if r.get("kind") == "span" or r["tag"] not in series:
            continue
        val = r["value"] if "value" in r else r.get("p95")
        if val is None:
            continue
        series[r["tag"]].append((r["wall"], r.get("step", 0), val))
    return series, t0


def load_phase_windows(log_dir: str, role: str):
    """Per-drain-window phase totals for ONE process:
    ``(walls, {phase: [total_ms per window]})``, windows keyed by the
    row wall-clock (one StepTimer drain writes all its phases with one
    wall stamp).  ``role`` may be a process role stamp (``actor-0``)
    or a bare tag prefix (``actor``) — but StepTimer tags share the
    prefix across all of a role's processes, so when several processes
    contributed rows, the bare prefix is ambiguous (their windows
    would interleave into a meaningless sawtooth) and the caller must
    name one."""
    prefix = role.split("-")[0]
    pat = re.compile(rf"^{re.escape(prefix)}/time_(\w+?)_total_ms$")
    matched = [(r, m) for r in read_scalars(log_dir) if "value" in r
               for m in (pat.match(r.get("tag", "")),) if m]
    roles = sorted({r.get("role", prefix) for r, _m in matched})
    if role in roles:
        matched = [(r, m) for r, m in matched
                   if r.get("role", prefix) == role]
    elif len(roles) > 1:
        raise SystemExit(
            f"--phase-breakdown {role!r} matches rows from "
            f"{len(roles)} processes ({', '.join(roles)}); their drain "
            f"windows don't align — pass one exact role")
    windows = defaultdict(dict)  # wall -> {phase: ms}
    for r, m in matched:
        windows[r["wall"]][m.group(1)] = r["value"]
    walls = sorted(windows)
    phases = sorted({p for w in windows.values() for p in w},
                    key=lambda p: -sum(w.get(p, 0.0)
                                       for w in windows.values()))
    return walls, {p: [windows[w].get(p, 0.0) for w in walls]
                   for p in phases}


def _style_axis(ax):
    ax.set_facecolor("white")
    ax.grid(True, color=GRID, lw=0.7, zorder=0)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)
    for s in ("left", "bottom"):
        ax.spines[s].set_color(GRID)
    ax.tick_params(colors=MUTED, labelsize=8)


def plot_phase_breakdown(log_dir: str, role: str, out: str) -> str:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    walls, phases = load_phase_windows(log_dir, role)
    if len(walls) < 2 or not phases:
        raise SystemExit(
            f"no {role}/time_*_total_ms rows (or <2 drain windows) in "
            f"{log_dir}/scalars.jsonl — is the role's StepTimer "
            f"draining?")
    t0 = walls[0]
    xs = [(w - t0) / 60.0 for w in walls]
    fig, ax = plt.subplots(figsize=(7.2, 3.2), dpi=150)
    fig.patch.set_facecolor("white")
    ax.stackplot(xs, *(phases[p] for p in phases),
                 labels=list(phases),
                 colors=[PHASE_COLORS[i % len(PHASE_COLORS)]
                         for i in range(len(phases))],
                 alpha=0.85, lw=0.0, zorder=3)
    _style_axis(ax)
    ax.set_title(f"{role}: per-phase wall time per drain window "
                 f"(StepTimer totals)", fontsize=9.5, color=INK,
                 loc="left")
    ax.set_xlabel("wall-clock (minutes)", fontsize=9, color=MUTED)
    ax.set_ylabel("ms per window", fontsize=9, color=MUTED)
    ax.legend(loc="upper right", fontsize=7, frameon=False,
              labelcolor=INK)
    fig.tight_layout()
    fig.savefig(out, bbox_inches="tight")
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("log_dir")
    ap.add_argument("--tags", nargs="+", default=list(DEFAULT_TAGS))
    ap.add_argument("--x", choices=("wall", "step"), default="wall")
    ap.add_argument("--out", default=None)
    ap.add_argument("--phase-breakdown", type=str, default=None,
                    metavar="ROLE",
                    help="stacked per-phase wall-time plot from the "
                         "role's StepTimer *_total_ms rows (e.g. actor, "
                         "learner) instead of scalar panels")
    args = ap.parse_args()

    if args.phase_breakdown:
        out = args.out or os.path.join(
            args.log_dir, f"phases_{args.phase_breakdown}.png")
        print(plot_phase_breakdown(args.log_dir, args.phase_breakdown,
                                   out))
        return

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    series, t0 = load_series(args.log_dir, args.tags)
    tags = [t for t in args.tags if series[t]]
    if not tags or t0 is None:
        raise SystemExit(f"none of {args.tags} found in "
                         f"{args.log_dir}/scalars.jsonl")

    fig, axes = plt.subplots(len(tags), 1, figsize=(7.2, 2.4 * len(tags)),
                             dpi=150, sharex=True, squeeze=False)
    fig.patch.set_facecolor("white")
    for ax, tag in zip(axes[:, 0], tags):
        pts = series[tag]
        xs = [(w - t0) / 60.0 if args.x == "wall" else s
              for w, s, _ in pts]
        ax.plot(xs, [v for _, _, v in pts], color=BLUE, lw=2.0,
                solid_capstyle="round", zorder=3)
        _style_axis(ax)
        ax.set_title(tag, fontsize=9.5, color=INK, loc="left")
    axes[-1, 0].set_xlabel(
        "wall-clock (minutes)" if args.x == "wall" else "learner step",
        fontsize=9, color=MUTED)
    fig.tight_layout()
    out = args.out or os.path.join(args.log_dir, "run.png")
    fig.savefig(out, bbox_inches="tight")
    print(out)


if __name__ == "__main__":
    main()
