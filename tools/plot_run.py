#!/usr/bin/env python
"""Plot scalar series from a run's JSONL metrics stream.

The headless quick-look replacement for TensorBoard: reads
``<log_dir>/scalars.jsonl`` (utils/metrics.py format) and renders the
requested tags, one panel per tag, sharing the x-axis.

Usage:
    python tools/plot_run.py <log_dir> [--tags evaluator/avg_reward ...] \
        [--x wall|step] [--out run.png]

Defaults: the three headline tags, x = wall-clock minutes,
out = <log_dir>/run.png.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from pytorch_distributed_tpu.utils.metrics import read_scalars  # noqa: E402

DEFAULT_TAGS = ("evaluator/avg_reward", "learner/critic_loss",
                "actor/total_nframes")

# thin marks, recessive grid, neutral ink; blue = categorical slot 1
INK, MUTED, GRID, BLUE = "#1a1a1a", "#6b6b6b", "#e5e5e5", "#2a78d6"


def load_series(log_dir: str, tags):
    rows = read_scalars(log_dir)
    series = {t: [] for t in tags}
    t0 = min((r["wall"] for r in rows), default=None)
    for r in rows:
        # histogram rows carry p50/p95/max instead of a value — plot the
        # p95 when a histogram tag is requested, skip span rows
        if r.get("kind") == "span" or r["tag"] not in series:
            continue
        val = r["value"] if "value" in r else r.get("p95")
        if val is None:
            continue
        series[r["tag"]].append((r["wall"], r.get("step", 0), val))
    return series, t0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("log_dir")
    ap.add_argument("--tags", nargs="+", default=list(DEFAULT_TAGS))
    ap.add_argument("--x", choices=("wall", "step"), default="wall")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    series, t0 = load_series(args.log_dir, args.tags)
    tags = [t for t in args.tags if series[t]]
    if not tags or t0 is None:
        raise SystemExit(f"none of {args.tags} found in "
                         f"{args.log_dir}/scalars.jsonl")

    fig, axes = plt.subplots(len(tags), 1, figsize=(7.2, 2.4 * len(tags)),
                             dpi=150, sharex=True, squeeze=False)
    fig.patch.set_facecolor("white")
    for ax, tag in zip(axes[:, 0], tags):
        pts = series[tag]
        xs = [(w - t0) / 60.0 if args.x == "wall" else s
              for w, s, _ in pts]
        ax.plot(xs, [v for _, _, v in pts], color=BLUE, lw=2.0,
                solid_capstyle="round", zorder=3)
        ax.set_facecolor("white")
        ax.set_title(tag, fontsize=9.5, color=INK, loc="left")
        ax.grid(True, color=GRID, lw=0.7, zorder=0)
        for s in ("top", "right"):
            ax.spines[s].set_visible(False)
        for s in ("left", "bottom"):
            ax.spines[s].set_color(GRID)
        ax.tick_params(colors=MUTED, labelsize=8)
    axes[-1, 0].set_xlabel(
        "wall-clock (minutes)" if args.x == "wall" else "learner step",
        fontsize=9, color=MUTED)
    fig.tight_layout()
    out = args.out or os.path.join(args.log_dir, "run.png")
    fig.savefig(out, bbox_inches="tight")
    print(out)


if __name__ == "__main__":
    main()
