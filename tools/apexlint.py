#!/usr/bin/env python
"""apexlint: invariant-aware static analysis for the tpu-apex fleet.

The repo's hard-won invariants — the tick_keys PRNG stream contract
(ISSUE 4/7), donated-buffer discipline in the fused scans, single-owner
drain boundaries (ISSUE 5), the REPLAY_FIELDS/provenance wire schema
(ISSUE 8), and the TPU_APEX_* knob surface — are enforced at *runtime*
by the RetraceDetector, TransferAudit, ingest quarantine and the parity
oracles.  A violation therefore costs a full fleet run to surface.
This tool is the *diff-time* twin: a pure-stdlib ``ast`` rule engine
(no jax import — it must run inside tier-1's budget on the 2-vCPU
image) that catches the same bug classes before they ship.

Rules (``--list-rules`` prints this catalog):

- ``donation-after-use`` — a buffer passed at a donated position of a
  ``jax.jit(..., donate_argnums=...)`` program is referenced again
  after the dispatch.  Donated buffers are *invalidated*: the reference
  silently aliases freed device memory (or raises on TPU).
- ``rng-key-reuse`` — the same PRNG key reaches two consuming draws
  (``jax.random.<sampler>`` or ``split``) without an interleaving
  rebind, or a ``PRNGKey`` is minted from a literal constant seed
  outside ``utils/rngs.py`` — both break the root-seed / tick_keys
  stream contract (streams must derive from the run seed via stable
  folds, and a key is use-once).
- ``retrace-hazard`` — a Python scalar that changes per iteration (the
  loop induction variable, or a host counter bumped in the loop) flows
  into a registered jitted program, or a non-hashable literal is passed
  at a ``static_argnums`` position: the static twin of the runtime
  RetraceDetector (every such call retraces = recompiles on the hot
  path).
- ``single-owner`` — a mutating method of a single-owner class
  (``drain``/``ring_write*``/quarantine ``put``) is invoked from a
  module that is not in the owner set the class declares via its
  ``__apex_mutators__``/``__apex_owner__`` annotations.
- ``schema-contract`` — positional indexing into ``Transition``/
  ``Segment`` rows, re-typed copies of the REPLAY_FIELDS tuple
  (shadow schemas drift silently), ``._fields`` used where the
  six-column replay schema is meant, and savez wire columns that
  drift from the module's declared ``WIRE_COLUMNS``.
- ``knob-registry`` — every ``TPU_APEX_*``/``*_FAULTS`` env read must
  be declared in ``config.KNOBS`` and documented in README.md and
  TESTING.md; declared knobs must still be read somewhere.  Drift in
  either direction is a finding.

Generic pass (same runner, ``--rules gen`` selects just these):

- ``unused-import`` / ``undefined-name`` / ``shadowed-builtin`` — the
  pyflakes-class hygiene checks, scope-aware.

Findings print as ``file:line · RULE_ID · message · hint: ...``; known
findings live in a checked-in baseline (``tools/apexlint_baseline.json``
by convention) where every entry carries a written justification —
an empty justification is a hard error, and entries that no longer
match anything are ``baseline-stale`` findings so the file is pruned
forward.  Suppress a single line in code with
``# apexlint: ignore[rule-id]`` (bare ``ignore`` silences all rules).

Exit codes (bench_gate-compatible): 0 clean, 1 findings (or stale
baseline entries), 2 usage/config error.

Usage:
    python tools/apexlint.py pytorch_distributed_tpu tools
    python tools/apexlint.py --json --baseline tools/apexlint_baseline.json
    python tools/apexlint.py --write-baseline   # then fill justifications
"""

from __future__ import annotations

import argparse
import ast
import builtins
import fnmatch
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# rule catalog
# ---------------------------------------------------------------------------

RULES: Dict[str, str] = {
    "donation-after-use":
        "buffer referenced after being donated to a jitted dispatch",
    "rng-key-reuse":
        "PRNG key consumed twice / literal-seed key outside utils.rngs",
    "retrace-hazard":
        "per-iteration Python scalar or unhashable static arg into a "
        "jitted program",
    "single-owner":
        "single-owner mutation invoked outside the declared owner set",
    "schema-contract":
        "positional/shadow replay schema access or wire-column drift",
    "knob-registry":
        "env knob not declared in config.KNOBS or missing from docs",
    "unused-import": "imported name is never used",
    "undefined-name": "name is not defined in any enclosing scope",
    "shadowed-builtin": "binding shadows a Python builtin",
    "parse-error": "file failed to parse",
}

GENERIC_RULES = ("unused-import", "undefined-name", "shadowed-builtin")

# Replay schema fallback when utils/experience.py is outside the scanned
# tree (e.g. linting tools/ alone); the scanned value wins when present.
DEFAULT_REPLAY_FIELDS = (  # apexlint: ignore[schema-contract]
    "state0", "action", "reward", "gamma_n", "state1", "terminal1")

# env knob name-space this repo owns (the knob-registry rule's scope)
KNOB_SCOPE = re.compile(r"(^TPU_APEX)|(_FAULTS($|_))")

_PRAGMA = re.compile(r"#\s*apexlint:\s*ignore(?:\[([a-z0-9_,\- ]+)\])?")

# PRNG derivation calls that do NOT consume a key (the tick_keys
# contract: the base key may be re-folded forever), vs consuming draws.
_KEY_PURE = {"fold_in", "tick_keys", "PRNGKey", "key", "key_data",
             "wrap_key_data", "asarray", "device_put", "array",
             "process_key", "clone"}
_KEY_PARAM = re.compile(r"(^|_)key$")

_SHADOW_BUILTINS = frozenset({
    "list", "dict", "set", "tuple", "str", "int", "float", "bool",
    "bytes", "type", "id", "input", "filter", "map", "sum", "min",
    "max", "len", "range", "object", "print", "vars", "next", "iter",
    "hash", "dir", "abs", "all", "any", "round", "sorted", "zip",
    "open", "eval", "exec", "compile", "format", "pow", "repr",
    "super", "property", "enumerate", "reversed", "slice", "frozenset",
    "bytearray", "complex", "divmod", "callable", "isinstance",
    "issubclass", "bin", "hex", "oct",
})

_BUILTIN_NAMES = frozenset(dir(builtins)) | {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__path__", "__class__",
}


# ---------------------------------------------------------------------------
# findings + baseline
# ---------------------------------------------------------------------------

@dataclass
class Finding:
    path: str          # root-relative, forward slashes
    line: int
    rule: str
    message: str
    hint: str
    context: str = ""  # dotted enclosing class/def scope — line-stable key

    def key(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.path, self.context, self.message)

    def format(self) -> str:
        return (f"{self.path}:{self.line} · {self.rule} · {self.message}"
                f" · hint: {self.hint}")

    def to_json(self) -> dict:
        return {"path": self.path, "line": self.line, "rule": self.rule,
                "message": self.message, "hint": self.hint,
                "context": self.context}


class BaselineError(Exception):
    """Malformed baseline file — exit 2, never silently ignored."""


def load_baseline(path: str) -> List[dict]:
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        raise BaselineError(f"cannot read baseline {path}: {e}")
    entries = data.get("entries")
    if not isinstance(entries, list):
        raise BaselineError(f"{path}: top-level 'entries' list required")
    for i, e in enumerate(entries):
        for k in ("rule", "path", "context", "message", "justification"):
            if k not in e:
                raise BaselineError(f"{path}: entry {i} missing '{k}'")
        if not str(e["justification"]).strip() or \
                "TODO" in str(e["justification"]):
            raise BaselineError(
                f"{path}: entry {i} ({e['rule']} at {e['path']}) has an "
                f"empty/TODO justification — every baselined finding "
                f"needs a written reason it is acceptable")
    return entries


# ---------------------------------------------------------------------------
# module model: parse once, share alias/symbol resolution across rules
# ---------------------------------------------------------------------------

class Module:
    def __init__(self, abspath: str, relpath: str, text: str):
        self.abspath = abspath
        self.path = relpath.replace(os.sep, "/")
        self.tree = ast.parse(text, filename=relpath)
        self.lines = text.splitlines()
        # dotted module name, e.g. pytorch_distributed_tpu.agents.actor
        mod = self.path[:-3] if self.path.endswith(".py") else self.path
        if mod.endswith("/__init__"):
            mod = mod[: -len("/__init__")]
        self.dotted = mod.replace("/", ".")
        self.is_init = self.path.endswith("__init__.py")
        # per-line pragma suppressions: line -> set of rules ({"*"} = all)
        self.pragmas: Dict[int, Set[str]] = {}
        for i, ln in enumerate(self.lines, 1):
            m = _PRAGMA.search(ln)
            if m:
                rules = m.group(1)
                self.pragmas[i] = (
                    {r.strip() for r in rules.split(",")} if rules
                    else {"*"})
        # import alias map: local name -> dotted origin
        self.imports: Dict[str, str] = {}
        # module-level constants: name -> literal value (str / str-tuple)
        self.constants: Dict[str, Any] = {}
        self._collect_top_level()

    def _collect_top_level(self) -> None:
        pkg = self.dotted.rsplit(".", 1)[0] if "." in self.dotted else ""
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    self.imports[local] = a.asname and a.name or \
                        a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:  # relative: resolve against our package
                    parts = self.dotted.split(".")
                    parts = parts[: len(parts) - node.level] or [pkg]
                    base = ".".join(parts + ([node.module]
                                             if node.module else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.imports[a.asname or a.name] = f"{base}.{a.name}"
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                val = _literal(node.value)
                if val is not None:
                    self.constants[node.targets[0].id] = val

    def resolve(self, node: ast.AST) -> str:
        """Dotted origin of a Name/Attribute chain (''  when opaque)."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return ""
        head = self.imports.get(cur.id, cur.id)
        return ".".join([head] + list(reversed(parts)))

    def suppressed(self, line: int, rule: str) -> bool:
        tags = self.pragmas.get(line)
        return bool(tags) and ("*" in tags or rule in tags)


def _literal(node: ast.AST) -> Any:
    """Constant str/int/float, or tuple of constant strs, else None."""
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (str, int, float)):
        return node.value
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    return None


def _context_of(module: Module, target: ast.AST) -> str:
    """Dotted class/def scope containing ``target`` (line-stable
    baseline key)."""
    best: List[str] = []

    def walk(node: ast.AST, stack: List[str]) -> bool:
        if node is target:
            best[:] = stack
            return True
        for child in ast.iter_child_nodes(node):
            s = stack
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                s = stack + [child.name]
            if walk(child, s):
                return True
        return False

    walk(module.tree, [])
    return ".".join(best)


# ---------------------------------------------------------------------------
# ordered event stream: loads/stores/calls in approximate execution
# order, loop bodies twice (so iteration-crossing hazards surface)
# ---------------------------------------------------------------------------

def iter_events(body: List[ast.stmt]) -> List[Tuple[str, Any, int]]:
    events: List[Tuple[str, Any, int]] = []

    def expr(node: Optional[ast.AST]) -> None:
        if node is None:
            return
        if isinstance(node, ast.Name):
            kind = "store" if isinstance(node.ctx, ast.Store) else "load"
            events.append((kind, node.id, node.lineno))
            return
        if isinstance(node, ast.Call):
            expr(node.func)
            for a in node.args:
                expr(a)
            for kw in node.keywords:
                expr(kw.value)
            events.append(("call", node, node.lineno))
            return
        if isinstance(node, ast.Lambda):
            # closure loads happen "at" the def site, conservatively —
            # but only of FREE names: the lambda's own params are not
            # reads of the enclosing scope
            a = node.args
            params = {x.arg for x in (a.posonlyargs + a.args +
                                      a.kwonlyargs +
                                      ([a.vararg] if a.vararg else []) +
                                      ([a.kwarg] if a.kwarg else []))}
            for inner in ast.walk(node.body):
                if isinstance(inner, ast.Name) and isinstance(
                        inner.ctx, ast.Load) and inner.id not in params:
                    events.append(("load", inner.id, inner.lineno))
            return
        for child in ast.iter_child_nodes(node):
            expr(child)

    def assign_target(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            events.append(("store", t.id, t.lineno))
        else:
            expr(t)

    def stmt(s: ast.stmt) -> None:
        if isinstance(s, ast.Assign):
            expr(s.value)
            for t in s.targets:
                assign_target(t)
        elif isinstance(s, ast.AnnAssign):
            expr(s.value)
            if s.value is not None:
                assign_target(s.target)
        elif isinstance(s, ast.AugAssign):
            if isinstance(s.target, ast.Name):
                events.append(("load", s.target.id, s.lineno))
            expr(s.value)
            assign_target(s.target)
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            expr(s.iter)
            # loop body twice: a use "before" the donating call in the
            # source still runs after it on the next iteration
            for _ in range(2):
                assign_target(s.target)
                for b in s.body:
                    stmt(b)
            for b in s.orelse:
                stmt(b)
        elif isinstance(s, ast.While):
            for _ in range(2):
                expr(s.test)
                for b in s.body:
                    stmt(b)
            for b in s.orelse:
                stmt(b)
        elif isinstance(s, ast.If):
            # branch markers let flow-sensitive rules (donation) fork
            # their state: the else-branch never observes the
            # if-branch's effects
            expr(s.test)
            events.append(("branch", "start", s.lineno))
            for b in s.body:
                stmt(b)
            events.append(("branch", "alt", s.lineno))
            for b in s.orelse:
                stmt(b)
            events.append(("branch", "end", s.lineno))
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                expr(item.context_expr)
                if item.optional_vars is not None:
                    assign_target(item.optional_vars)
            for b in s.body:
                stmt(b)
        elif isinstance(s, ast.Try):
            for b in s.body:
                stmt(b)
            for h in s.handlers:
                expr(h.type)
                for b in h.body:
                    stmt(b)
            for b in s.orelse + s.finalbody:
                stmt(b)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: FREE-variable loads count at the def site (a
            # closure reading a donated buffer later is still a
            # hazard); names the nested def binds itself — args,
            # stores, inner defs — are its own locals, not reads of
            # the enclosing scope
            a = s.args
            local = {x.arg for x in (a.posonlyargs + a.args +
                                     a.kwonlyargs +
                                     ([a.vararg] if a.vararg else []) +
                                     ([a.kwarg] if a.kwarg else []))}
            for inner in ast.walk(s):
                if isinstance(inner, ast.Name) and isinstance(
                        inner.ctx, ast.Store):
                    local.add(inner.id)
                elif isinstance(inner, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.ClassDef)) and inner is not s:
                    local.add(inner.name)
            for inner in ast.walk(s):
                if isinstance(inner, ast.Name) and isinstance(
                        inner.ctx, ast.Load) and inner.id not in local:
                    events.append(("load", inner.id, inner.lineno))
        elif isinstance(s, ast.ClassDef):
            pass
        elif isinstance(s, (ast.Return, ast.Expr, ast.Raise, ast.Assert,
                            ast.Delete)):
            for child in ast.iter_child_nodes(s):
                expr(child)
        else:
            for child in ast.iter_child_nodes(s):
                expr(child)

    for s in body:
        stmt(s)
    return events


def _functions(module: Module):
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# ---------------------------------------------------------------------------
# project: cross-module registries collected in pass 1
# ---------------------------------------------------------------------------

@dataclass
class OwnerClass:
    name: str
    module: str                 # dotted defining module
    mutators: Tuple[str, ...]
    owners: Tuple[str, ...]     # substrings of allowed dotted modules


@dataclass
class Project:
    root: str
    modules: List[Module] = field(default_factory=list)
    replay_fields: Tuple[str, ...] = DEFAULT_REPLAY_FIELDS
    owner_classes: Dict[str, OwnerClass] = field(default_factory=dict)
    fn_owners: Dict[str, Tuple[str, Tuple[str, ...]]] = \
        field(default_factory=dict)     # fn name -> (module, owners)
    factories: Dict[str, str] = field(default_factory=dict)
    knobs: List[Tuple[str, str, str]] = field(default_factory=list)
    knobs_at: Tuple[str, int] = ("", 0)  # (path, line) of KNOBS literal
    doc_text: Dict[str, str] = field(default_factory=dict)

    def collect(self) -> None:
        for m in self.modules:
            for node in m.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    name = node.targets[0].id
                    if name == "REPLAY_FIELDS":
                        val = _literal(node.value)
                        if isinstance(val, tuple):
                            self.replay_fields = val
                    elif name == "KNOBS":
                        knobs = _knob_literal(node.value)
                        if knobs is not None:
                            self.knobs = knobs
                            self.knobs_at = (m.path, node.lineno)
                    elif name == "__apex_fn_owners__":
                        for fn, owners in _dict_literal(node.value).items():
                            self.fn_owners[fn] = (m.dotted, owners)
                    elif name == "__apex_factories__":
                        for fac, cls in _dict_literal(node.value).items():
                            if isinstance(cls, str):
                                self.factories[fac] = cls
                            elif isinstance(cls, tuple) and cls:
                                self.factories[fac] = cls[0]
                elif isinstance(node, ast.ClassDef):
                    muts = owners = None
                    for st in node.body:
                        if isinstance(st, ast.Assign) and \
                                len(st.targets) == 1 and \
                                isinstance(st.targets[0], ast.Name):
                            v = _literal(st.value)
                            if st.targets[0].id == "__apex_mutators__" \
                                    and isinstance(v, tuple):
                                muts = v
                            elif st.targets[0].id == "__apex_owner__" \
                                    and isinstance(v, tuple):
                                owners = v
                    if muts:
                        self.owner_classes[node.name] = OwnerClass(
                            node.name, m.dotted, muts, owners or ())
        for doc in ("README.md", "TESTING.md"):
            p = os.path.join(self.root, doc)
            try:
                with open(p) as f:
                    self.doc_text[doc] = f.read()
            except OSError:
                self.doc_text[doc] = ""


def _dict_literal(node: ast.AST) -> Dict[str, Tuple[str, ...]]:
    out: Dict[str, Tuple[str, ...]] = {}
    if isinstance(node, ast.Dict):
        for k, v in zip(node.keys, node.values):
            kk, vv = _literal(k) if k is not None else None, _literal(v)
            if isinstance(kk, str) and vv is not None:
                out[kk] = vv if isinstance(vv, tuple) else (vv,)
    return out


def _knob_literal(node: ast.AST) -> Optional[List[Tuple[str, str, str]]]:
    """Parse ``KNOBS = ((name, where, doc), ...)`` without importing."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    rows: List[Tuple[str, str, str]] = []
    for e in node.elts:
        row = _literal(e)
        if not (isinstance(row, tuple) and len(row) == 3):
            return None
        rows.append(row)  # type: ignore[arg-type]
    return rows


# ---------------------------------------------------------------------------
# shared: jit registries (donating + static positions) per module
# ---------------------------------------------------------------------------

def _donate_positions(call: ast.Call) -> Set[int]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _int_set(kw.value)
    return set()


def _static_positions(call: ast.Call) -> Set[int]:
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            return _int_set(kw.value)
    return set()


def _int_set(node: ast.AST) -> Set[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.add(e.value)
        return out
    if isinstance(node, ast.IfExp):  # (0,) if donate else () — union
        return _int_set(node.body) | _int_set(node.orelse)
    return set()


def _target_key(t: ast.AST) -> Optional[str]:
    """'name' or 'self.attr' binding key for jit/instance registries."""
    if isinstance(t, ast.Name):
        return t.id
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
            and t.value.id == "self":
        return f"self.{t.attr}"
    return None


def _callee_key(node: ast.Call) -> Optional[str]:
    return _target_key(node.func)


def _jit_registry(module: Module) -> Tuple[Dict[str, Set[int]],
                                           Dict[str, Set[int]]]:
    """Maps of var/'self.attr' -> donated / static positions, for every
    ``x = jax.jit(...)`` binding in the module."""
    donating: Dict[str, Set[int]] = {}
    static: Dict[str, Set[int]] = {}
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        callee = module.resolve(node.value.func)
        if not callee.endswith("jax.jit") and callee != "jit":
            continue
        for t in node.targets:
            key = _target_key(t)
            if key is None:
                continue
            d = _donate_positions(node.value)
            if d:
                donating[key] = d
            static.setdefault(key, _static_positions(node.value))
    return donating, static


# ---------------------------------------------------------------------------
# rule: donation-after-use
# ---------------------------------------------------------------------------

def check_donation(module: Module) -> List[Finding]:
    donating, _ = _jit_registry(module)
    if not donating:
        return []
    out: List[Finding] = []
    for fn in _functions(module):
        pending: Dict[str, Tuple[int, str]] = {}  # name -> (line, callee)
        flagged: Set[Tuple[str, int]] = set()
        # if/else fork stack: (snapshot-at-test, if-branch result)
        branches: List[Tuple[dict, Optional[dict]]] = []
        for kind, payload, line in iter_events(fn.body):
            if kind == "branch":
                if payload == "start":
                    branches.append((dict(pending), None))
                elif payload == "alt" and branches:
                    snap, _ = branches[-1]
                    branches[-1] = (snap, dict(pending))
                    pending.clear()
                    pending.update(snap)
                elif payload == "end" and branches:
                    _snap, body_result = branches.pop()
                    if body_result:
                        # after the if: either branch may have donated
                        pending.update(body_result)
            elif kind == "call":
                key = _callee_key(payload)
                if key in donating:
                    for pos in donating[key]:
                        if pos < len(payload.args) and isinstance(
                                payload.args[pos], ast.Name):
                            pending[payload.args[pos].id] = (line, key)
            elif kind == "store":
                pending.pop(payload, None)
            elif kind == "load" and payload in pending:
                dline, callee = pending[payload]
                if (payload, line) in flagged or line == dline:
                    continue
                flagged.add((payload, line))
                out.append(Finding(
                    module.path, line, "donation-after-use",
                    f"'{payload}' is read after being donated to "
                    f"'{callee}'",
                    f"rebind the variable from the dispatch's result "
                    f"(donation at line {dline}), or drop donate_argnums "
                    f"for this argument",
                    _context_of(module, fn)))
    return out


# ---------------------------------------------------------------------------
# rule: rng-key-reuse
# ---------------------------------------------------------------------------

def _is_key_derivation(callee: str) -> bool:
    last = callee.rsplit(".", 1)[-1]
    return last in _KEY_PURE


def _is_key_consumer(callee: str) -> bool:
    if callee.rsplit(".", 1)[-1] == "split":
        return True  # split invalidates its operand: use the outputs
    return "jax.random." in callee and not _is_key_derivation(callee)


def check_rng(module: Module) -> List[Finding]:
    out: List[Finding] = []
    allow_literal = module.dotted.endswith("utils.rngs")
    for fn in _functions(module):
        key_vars: Set[str] = set()
        for a in (fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs):
            if _KEY_PARAM.search(a.arg):
                key_vars.add(a.arg)
        # vars bound from a derivation call are keys whatever their name
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                last = module.resolve(
                    node.value.func).rsplit(".", 1)[-1]
                if last in ("split", "fold_in", "PRNGKey", "tick_keys",
                            "process_key"):
                    for t in node.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name) and isinstance(
                                    n.ctx, ast.Store):
                                key_vars.add(n.id)
        consumed: Dict[str, Tuple[int, str]] = {}
        # if/else fork stack, mirroring check_donation: a consumption
        # in the if-branch is never visible to the else-branch
        branches: List[Tuple[dict, Optional[dict]]] = []
        for kind, payload, line in iter_events(fn.body):
            if kind == "branch":
                if payload == "start":
                    branches.append((dict(consumed), None))
                elif payload == "alt" and branches:
                    snap, _ = branches[-1]
                    branches[-1] = (snap, dict(consumed))
                    consumed.clear()
                    consumed.update(snap)
                elif payload == "end" and branches:
                    _snap, body_result = branches.pop()
                    if body_result:
                        consumed.update(body_result)
                continue
            if kind == "store":
                consumed.pop(payload, None)
                continue
            if kind != "call":
                continue
            callee = module.resolve(payload.func)
            # literal-seed PRNGKey: streams must fold from the run seed
            if callee.rsplit(".", 1)[-1] == "PRNGKey" and payload.args \
                    and isinstance(payload.args[0], ast.Constant) \
                    and not allow_literal \
                    and not module.suppressed(line, "rng-key-reuse"):
                out.append(Finding(
                    module.path, line, "rng-key-reuse",
                    f"PRNGKey({payload.args[0].value!r}) minted from a "
                    f"literal seed — a fixed stream colliding with every "
                    f"other literal-seed stream",
                    "derive the key from the run seed "
                    "(utils.rngs.process_key / fold_in of an existing "
                    "stream)",
                    _context_of(module, fn)))
            # track derived keys as they are bound elsewhere (store
            # events already clear consumption)
            if not _is_key_consumer(callee):
                continue
            for arg in list(payload.args) + \
                    [kw.value for kw in payload.keywords]:
                if not isinstance(arg, ast.Name) or \
                        arg.id not in key_vars and \
                        not _KEY_PARAM.search(arg.id):
                    continue
                name = arg.id
                if name in consumed:
                    first_line, first_callee = consumed[name]
                    if line != first_line and not module.suppressed(
                            line, "rng-key-reuse"):
                        out.append(Finding(
                            module.path, line, "rng-key-reuse",
                            f"PRNG key '{name}' consumed by "
                            f"'{callee}' after already being consumed "
                            f"by '{first_callee}' with no rebind "
                            f"between",
                            f"split/fold_in a fresh key per consumer "
                            f"(first consumption at line {first_line}; "
                            f"tick_keys stream contract)",
                            _context_of(module, fn)))
                        consumed.pop(name, None)
                else:
                    consumed[name] = (line, callee)
    return out


# ---------------------------------------------------------------------------
# rule: retrace-hazard
# ---------------------------------------------------------------------------

def check_retrace(module: Module) -> List[Finding]:
    donating, static = _jit_registry(module)
    jitted = set(donating) | set(static)
    out: List[Finding] = []
    for fn in _functions(module):
        # python scalar counters: assigned from an int/float literal
        scalar_consts: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Constant) and isinstance(
                    node.value.value, (int, float)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        scalar_consts.add(t.id)
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            induction: Set[str] = set()
            if isinstance(loop, ast.For):
                it = loop.iter
                callee = module.resolve(it.func) if isinstance(
                    it, ast.Call) else ""
                if callee in ("range", "enumerate"):
                    tgt = loop.target
                    if isinstance(tgt, ast.Name):
                        induction.add(tgt.id)
                    elif isinstance(tgt, ast.Tuple) and callee == \
                            "enumerate" and tgt.elts and isinstance(
                            tgt.elts[0], ast.Name):
                        induction.add(tgt.elts[0].id)
            bumped: Set[str] = set()
            for node in ast.walk(loop):
                if isinstance(node, ast.AugAssign) and isinstance(
                        node.target, ast.Name) and \
                        node.target.id in scalar_consts:
                    bumped.add(node.target.id)
                elif isinstance(node, ast.Assign) and isinstance(
                        node.value, ast.BinOp):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and \
                                t.id in scalar_consts and any(
                                isinstance(n, ast.Name) and n.id == t.id
                                for n in ast.walk(node.value)):
                            bumped.add(t.id)
            hazards = induction | bumped
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                key = _callee_key(node)
                if key not in jitted:
                    continue
                for i, arg in enumerate(node.args):
                    if isinstance(arg, ast.Name) and arg.id in hazards \
                            and not module.suppressed(node.lineno,
                                                      "retrace-hazard"):
                        out.append(Finding(
                            module.path, node.lineno, "retrace-hazard",
                            f"python scalar '{arg.id}' varies per "
                            f"iteration and flows into jitted "
                            f"'{key}' — every call retraces",
                            "keep the counter device-resident "
                            "(jnp.int32 carry advanced on device) or "
                            "fold it into the traced key stream",
                            _context_of(module, fn)))
                    if isinstance(arg, (ast.List, ast.Dict, ast.Set)) \
                            and i in static.get(key, set()) \
                            and not module.suppressed(node.lineno,
                                                      "retrace-hazard"):
                        out.append(Finding(
                            module.path, node.lineno, "retrace-hazard",
                            f"unhashable {type(arg).__name__.lower()} "
                            f"literal at static_argnums position {i} of "
                            f"jitted '{key}'",
                            "static args must be hashable — pass a "
                            "tuple or hoist to a closure constant",
                            _context_of(module, fn)))
    return out


# ---------------------------------------------------------------------------
# rule: single-owner
# ---------------------------------------------------------------------------

def _owned(dotted_module: str, defining: str,
           owners: Tuple[str, ...]) -> bool:
    if dotted_module == defining:
        return True
    return any(o in dotted_module for o in owners)


def check_single_owner(module: Module, project: Project) -> List[Finding]:
    if not project.owner_classes and not project.fn_owners:
        return []
    out: List[Finding] = []
    # provenance: var/'self.attr' -> owning class name
    instances: Dict[str, str] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call):
            cls = module.resolve(node.value.func).rsplit(".", 1)[-1]
            if cls in project.owner_classes:
                for t in node.targets:
                    key = _target_key(t)
                    if key:
                        instances[key] = cls

    def class_of_receiver(recv: ast.AST) -> Optional[str]:
        key = _target_key(recv)
        if key and key in instances:
            return instances[key]
        if isinstance(recv, ast.Call):  # factory(...).mutator(...)
            fac = module.resolve(recv.func).rsplit(".", 1)[-1]
            return project.factories.get(fac)
        return None

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            cls = class_of_receiver(f.value)
            oc = project.owner_classes.get(cls) if cls else None
            if oc and f.attr in oc.mutators and not _owned(
                    module.dotted, oc.module, oc.owners) and \
                    not module.suppressed(node.lineno, "single-owner"):
                out.append(Finding(
                    module.path, node.lineno, "single-owner",
                    f"{cls}.{f.attr}() invoked outside its declared "
                    f"owner set {oc.owners}",
                    "route the mutation through the owning role (or "
                    "extend __apex_owner__ if this module truly owns "
                    "the boundary)",
                    _context_of(module, node)))
        else:
            fname = module.resolve(f).rsplit(".", 1)[-1]
            if fname in project.fn_owners:
                defining, owners = project.fn_owners[fname]
                if not _owned(module.dotted, defining, owners) and \
                        not module.suppressed(node.lineno,
                                              "single-owner"):
                    out.append(Finding(
                        module.path, node.lineno, "single-owner",
                        f"{fname}() invoked outside its declared owner "
                        f"set {owners}",
                        "single-owner ring mutations belong to the "
                        "replay/rollout planes — route through them",
                        _context_of(module, node)))
    return out


# ---------------------------------------------------------------------------
# rule: schema-contract
# ---------------------------------------------------------------------------

_SCHEMA_CLASSES = ("Transition", "Segment")


def check_schema(module: Module, project: Project) -> List[Finding]:
    out: List[Finding] = []
    is_schema_home = module.dotted.endswith("utils.experience")
    rf = project.replay_fields

    # (a) positional subscript on provable Transition/Segment values
    rows: Dict[str, str] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call):
            cls = module.resolve(node.value.func).rsplit(".", 1)[-1]
            if cls in _SCHEMA_CLASSES:
                for t in node.targets:
                    key = _target_key(t)
                    if key:
                        rows[key] = cls
    for fn in _functions(module):
        for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
            ann = a.annotation
            if ann is not None:
                nm = module.resolve(ann).rsplit(".", 1)[-1] if isinstance(
                    ann, (ast.Name, ast.Attribute)) else ""
                if nm in _SCHEMA_CLASSES:
                    rows[a.arg] = nm
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Subscript) and isinstance(
                node.value, ast.Name) and node.value.id in rows:
            idx = node.slice
            if isinstance(idx, ast.Constant) and isinstance(
                    idx.value, int) and not module.suppressed(
                    node.lineno, "schema-contract"):
                cls = rows[node.value.id]
                fname = (rf[idx.value] if cls == "Transition"
                         and 0 <= idx.value < len(rf)
                         else f"field {idx.value}")
                out.append(Finding(
                    module.path, node.lineno, "schema-contract",
                    f"positional index [{idx.value}] into a {cls} row",
                    f"use the named field (.{fname}) — positional "
                    f"offsets break silently when the schema grows",
                    _context_of(module, node)))

    # (b) ._fields where the replay schema is meant
    if not is_schema_home:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr == "_fields" and isinstance(
                    node.value, (ast.Name, ast.Attribute)):
                cls = module.resolve(node.value).rsplit(".", 1)[-1]
                if cls in _SCHEMA_CLASSES and not module.suppressed(
                        node.lineno, "schema-contract"):
                    out.append(Finding(
                        module.path, node.lineno, "schema-contract",
                        f"{cls}._fields used for the replay schema — "
                        f"it now also carries the provenance sidecar",
                        "iterate REPLAY_FIELDS (utils.experience) when "
                        "you mean the six replay columns",
                        _context_of(module, node)))

    # (c) shadow replay-schema tuples (re-typed copies drift silently)
    if not is_schema_home:
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.Tuple, ast.List)):
                val = _literal(node)
                if isinstance(val, tuple) and len(val) >= 4 and \
                        val == rf[: len(val)] and not module.suppressed(
                        node.lineno, "schema-contract"):
                    out.append(Finding(
                        module.path, node.lineno, "schema-contract",
                        "re-typed copy of the replay schema tuple "
                        f"{val[:3] + ('...',)}",
                        "import REPLAY_FIELDS from utils.experience — "
                        "a shadow schema drifts silently when a column "
                        "is added",
                        _context_of(module, node)))

    # (d) wire columns must stay inside the declared WIRE_COLUMNS
    wire = module.constants.get("WIRE_COLUMNS")
    if wire is None:
        # WIRE_COLUMNS may be REPLAY_FIELDS + (...,): resolve the concat
        for node in module.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id == "WIRE_COLUMNS" and \
                    isinstance(node.value, ast.BinOp) and isinstance(
                    node.value.op, ast.Add):
                left = module.resolve(node.value.left).rsplit(".", 1)[-1]
                right = _literal(node.value.right)
                if left in ("REPLAY_FIELDS", "_FIELDS") and isinstance(
                        right, tuple):
                    wire = rf + right
    if wire:
        allowed = set(wire) | set(rf)
        for fn in _functions(module):
            if not (fn.name.startswith("encode")
                    or fn.name.startswith("decode")):
                continue
            for node in ast.walk(fn):
                key = None
                if isinstance(node, ast.Subscript) and isinstance(
                        node.slice, ast.Constant) and isinstance(
                        node.slice.value, str):
                    key = node.slice.value
                elif isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) and \
                        node.func.attr == "get" and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    key = node.args[0].value
                if key is not None and key not in allowed and \
                        not module.suppressed(node.lineno,
                                              "schema-contract"):
                    out.append(Finding(
                        module.path, node.lineno, "schema-contract",
                        f"wire column '{key}' is not in the declared "
                        f"WIRE_COLUMNS schema",
                        "add it to WIRE_COLUMNS (and bump peers) or "
                        "drop the stray column",
                        _context_of(module, fn)))
    return out


# ---------------------------------------------------------------------------
# rule: knob-registry
# ---------------------------------------------------------------------------

def _string_patterns(node: ast.AST, module: Module,
                     fn: Optional[ast.AST],
                     depth: int = 0) -> Optional[List[str]]:
    """Glob patterns an expression may evaluate to, or None if opaque."""
    if depth > 6:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.IfExp):
        a = _string_patterns(node.body, module, fn, depth + 1)
        b = _string_patterns(node.orelse, module, fn, depth + 1)
        if a is None or b is None:
            return None
        return a + b
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        pat = "".join(parts)
        return [pat] if pat.strip("*") else None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _string_patterns(node.left, module, fn, depth + 1)
        right = _string_patterns(node.right, module, fn, depth + 1)
        if left is None:
            return None
        rights = right if right is not None else ["*"]
        return [a + b for a in left for b in rights]
    if isinstance(node, ast.Name):
        if node.id in module.constants and isinstance(
                module.constants[node.id], str):
            return [module.constants[node.id]]
        pats: List[str] = []
        if fn is not None:
            for st in ast.walk(fn):
                if isinstance(st, ast.Assign):
                    for t in st.targets:
                        if isinstance(t, ast.Name) and t.id == node.id:
                            p = _string_patterns(st.value, module, fn,
                                                 depth + 1)
                            if p:
                                pats.extend(p)
        return pats or None
    if isinstance(node, ast.Call):
        return ["*"]  # role.upper() etc. — a wildcard segment
    return None


def _covers(read_pat: str, knob_name: str) -> bool:
    if read_pat == knob_name:
        return True
    # a concrete read against a family declaration (or vice versa);
    # identical families compare equal above
    return fnmatch.fnmatchcase(read_pat, knob_name) or \
        fnmatch.fnmatchcase(knob_name, read_pat)


def _enclosing_function(module: Module, target: ast.AST
                        ) -> Optional[ast.AST]:
    best: Optional[ast.AST] = None

    def walk(node: ast.AST, cur: Optional[ast.AST]) -> bool:
        nonlocal best
        if node is target:
            best = cur
            return True
        for child in ast.iter_child_nodes(node):
            nxt = child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)) else cur
            if walk(child, nxt):
                return True
        return False

    walk(module.tree, None)
    return best


def _env_read_sites(module: Module) -> List[Tuple[ast.AST, ast.AST]]:
    """(arg-expression, site-node) for every env READ in the module."""
    sites: List[Tuple[ast.AST, ast.AST]] = []
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            callee = module.resolve(node.func)
            if callee.endswith("os.environ.get") or \
                    callee.endswith("os.getenv") or callee == "getenv":
                if node.args:
                    sites.append((node.args[0], node))
        elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load):
            if module.resolve(node.value).endswith("os.environ"):
                sites.append((node.slice, node))
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.In, ast.NotIn)):
            if module.resolve(node.comparators[0]).endswith("os.environ"):
                sites.append((node.left, node))
    return sites


def _resolve_read(module: Module, arg: ast.AST, site: ast.AST,
                  ) -> Optional[List[str]]:
    """Patterns for one env-read argument; follows one level of
    call-site propagation when the arg is a parameter of the enclosing
    helper (``_env_flag(name, ...)`` style)."""
    fn = _enclosing_function(module, site)
    pats = _string_patterns(arg, module, fn)
    if pats:
        return pats
    if isinstance(arg, ast.Name) and fn is not None and isinstance(
            fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        params = [a.arg for a in fn.args.args]
        if arg.id in params:
            pos = params.index(arg.id)
            collected: List[str] = []
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Call) and \
                        module.resolve(node.func).rsplit(
                            ".", 1)[-1] == fn.name and node is not site:
                    cand: Optional[ast.AST] = None
                    if pos < len(node.args):
                        cand = node.args[pos]
                    for kw in node.keywords:
                        if kw.arg == arg.id:
                            cand = kw.value
                    if cand is not None:
                        p = _string_patterns(
                            cand, module,
                            _enclosing_function(module, node))
                        if p:
                            collected.extend(p)
            if collected:
                return collected
    return None


def check_knobs(module: Module, project: Project,
                read_patterns: List[str]) -> List[Finding]:
    """Code-side half: every in-scope env read must be declared.
    ``read_patterns`` accumulates resolved patterns for the reverse
    (registry-side) half run once per project."""
    out: List[Finding] = []
    declared = [k[0] for k in project.knobs]
    for arg, site in _env_read_sites(module):
        pats = _resolve_read(module, arg, site)
        if pats is None:
            # opaque dynamic read: only a finding when the expression
            # carries an in-scope fragment (f"TPU_APEX_{x}" etc.)
            frag = ast.dump(arg)
            if ("TPU_APEX" in frag or "_FAULTS" in frag) and \
                    not module.suppressed(site.lineno, "knob-registry"):
                out.append(Finding(
                    module.path, site.lineno, "knob-registry",
                    "dynamic env knob read is not statically resolvable",
                    "build the name from a declared prefix constant so "
                    "the registry rule can see it",
                    _context_of(module, site)))
            continue
        for pat in pats:
            if pat.strip("*"):
                # pure-wildcard patterns (opaque call args) carry no
                # name information: appending them would fnmatch every
                # declared knob and silently disable the declared-but-
                # never-read check
                read_patterns.append(pat)
            if not KNOB_SCOPE.search(pat.replace("*", "X")) and \
                    not KNOB_SCOPE.search(pat):
                continue
            if not any(_covers(pat, name) for name in declared) and \
                    not module.suppressed(site.lineno, "knob-registry"):
                out.append(Finding(
                    module.path, site.lineno, "knob-registry",
                    f"env knob '{pat}' read here is not declared in "
                    f"config.KNOBS",
                    "add a (name, where, doc) row to config.KNOBS and "
                    "document it in README.md + TESTING.md",
                    _context_of(module, site)))
    return out


def check_knob_registry_side(project: Project,
                             read_patterns: List[str]) -> List[Finding]:
    out: List[Finding] = []
    path, line = project.knobs_at
    if not project.knobs:
        if any(KNOB_SCOPE.search(p.replace("*", "X"))
               for p in read_patterns):
            out.append(Finding(
                path or "config.py", line or 1, "knob-registry",
                "no KNOBS declaration table found but TPU_APEX_*/"
                "*_FAULTS knobs are read in code",
                "declare the table: KNOBS = ((name, where, doc), ...)",
                "KNOBS"))
        return out
    for name, _where, _doc in project.knobs:
        if not any(_covers(p, name) or _covers(name, p)
                   for p in read_patterns):
            out.append(Finding(
                path, line, "knob-registry",
                f"knob '{name}' is declared in config.KNOBS but never "
                f"read in the scanned code",
                "delete the dead declaration (and its doc rows) or "
                "wire the knob up",
                "KNOBS"))
        token = name.rstrip("*").rstrip("_") if name != "*_FAULTS" \
            else "_FAULTS"
        for doc in ("README.md", "TESTING.md"):
            if token and token not in project.doc_text.get(doc, ""):
                out.append(Finding(
                    path, line, "knob-registry",
                    f"knob '{name}' is declared but undocumented in "
                    f"{doc}",
                    f"add it to the knob table in {doc}",
                    "KNOBS"))
    return out


# ---------------------------------------------------------------------------
# generic pass: scopes
# ---------------------------------------------------------------------------

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.Lambda, ast.ListComp, ast.SetComp, ast.DictComp,
                ast.GeneratorExp)


class _Scope:
    __slots__ = ("node", "parent", "kind", "bindings", "used",
                 "has_star", "globals")

    def __init__(self, node, parent, kind):
        self.node = node
        self.parent = parent
        self.kind = kind  # module | class | function
        self.bindings: Dict[str, Tuple[int, str]] = {}
        self.used: Set[str] = set()
        self.has_star = False
        self.globals: Set[str] = set()


def _bind(scope: _Scope, name: str, line: int, kind: str) -> None:
    scope.bindings.setdefault(name, (line, kind))


def _build_scopes(module: Module, parents: Dict[ast.AST, ast.AST]
                  ) -> Tuple[_Scope, Dict[ast.AST, _Scope]]:
    """Scope tree with AST-true parent chains (so nested
    comprehensions/lambdas resolve through every enclosing scope)."""
    module_scope = _Scope(module.tree, None, "module")
    by_node: Dict[ast.AST, _Scope] = {module.tree: module_scope}

    def scope_of(node: ast.AST) -> _Scope:
        cur: Optional[ast.AST] = node
        while cur is not None:
            if cur in by_node:
                return by_node[cur]
            cur = parents.get(cur)
        return module_scope

    # create scopes top-down (ast.walk is BFS: parents come first)
    for node in ast.walk(module.tree):
        if isinstance(node, _SCOPE_NODES):
            parent = scope_of(parents.get(node, module.tree))
            kind = "class" if isinstance(node, ast.ClassDef) \
                else "function"
            by_node[node] = _Scope(node, parent, kind)

    # collect bindings into their owning scope
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            _bind(scope_of(node), node.id, node.lineno, "assign")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            # the def's NAME binds in the enclosing scope; its args in
            # its own
            _bind(by_node[node].parent, node.name, node.lineno, "def")
            if not isinstance(node, ast.ClassDef):
                a = node.args
                for arg in (a.posonlyargs + a.args + a.kwonlyargs +
                            ([a.vararg] if a.vararg else []) +
                            ([a.kwarg] if a.kwarg else [])):
                    _bind(by_node[node], arg.arg, arg.lineno, "arg")
        elif isinstance(node, ast.Lambda):
            a = node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs +
                        ([a.vararg] if a.vararg else []) +
                        ([a.kwarg] if a.kwarg else [])):
                _bind(by_node[node], arg.arg, node.lineno, "arg")
        elif isinstance(node, ast.Import):
            s = scope_of(node)
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                _bind(s, local, node.lineno,
                      "import-reexport" if a.asname == a.name
                      else "import")
        elif isinstance(node, ast.ImportFrom):
            s = scope_of(node)
            for a in node.names:
                if a.name == "*":
                    s.has_star = True
                    continue
                kind = "import"
                if node.module == "__future__":
                    kind = "import-future"
                elif a.asname == a.name:
                    kind = "import-reexport"
                _bind(s, a.asname or a.name, node.lineno, kind)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            _bind(scope_of(node), node.name, node.lineno, "except")
        elif isinstance(node, ast.Global):
            s = scope_of(node)
            s.globals.update(node.names)
            for n in node.names:
                _bind(s, n, node.lineno, "global")
                _bind(module_scope, n, node.lineno, "global")
        elif isinstance(node, ast.Nonlocal):
            for n in node.names:
                _bind(scope_of(node), n, node.lineno, "nonlocal")
        elif isinstance(node, (ast.MatchAs, ast.MatchStar)) and \
                getattr(node, "name", None):
            _bind(scope_of(node), node.name, node.lineno, "assign")
    return module_scope, by_node


def check_generic(module: Module) -> List[Finding]:
    out: List[Finding] = []
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(module.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    module_scope, by_node = _build_scopes(module, parents)
    scopes = list(by_node.values())

    def scope_of(node: ast.AST) -> _Scope:
        cur: Optional[ast.AST] = node
        while cur is not None:
            if cur in by_node:
                return by_node[cur]
            cur = parents.get(cur)
        return module_scope

    # annotation subtrees: loads there count as usage, never undefined
    ann_nodes: Set[ast.AST] = set()
    for node in ast.walk(module.tree):
        anns: List[Optional[ast.AST]] = []
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            anns.append(node.returns)
            args = node.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs +
                      ([args.vararg] if args.vararg else []) +
                      ([args.kwarg] if args.kwarg else [])):
                anns.append(a.annotation)
        elif isinstance(node, ast.AnnAssign):
            anns.append(node.annotation)
        for ann in anns:
            if ann is not None:
                for n in ast.walk(ann):
                    ann_nodes.add(n)

    star_anywhere = any(s.has_star for s in scopes)
    all_names: Set[str] = set()
    for node in module.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    v = _literal(node.value)
                    if isinstance(v, tuple):
                        all_names.update(v)

    # pass 2: resolve loads
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Name) or not isinstance(
                node.ctx, ast.Load):
            continue
        name = node.id
        s: Optional[_Scope] = scope_of(node)
        found = False
        first = True
        while s is not None:
            if (s.kind != "class" or first) and name in s.bindings:
                s.used.add(name)
                found = True
                break
            first = False
            s = s.parent
        if not found and name not in _BUILTIN_NAMES and \
                not star_anywhere and node not in ann_nodes and \
                not module.suppressed(node.lineno, "undefined-name"):
            out.append(Finding(
                module.path, node.lineno, "undefined-name",
                f"name '{name}' is not defined in any enclosing scope",
                "define/import it (or gate the branch that uses it)",
                _context_of(module, node)))

    # docstring/doctest references don't count; __all__ does
    for name in all_names:
        if name in module_scope.bindings:
            module_scope.used.add(name)

    # unused imports (module API files re-export by design)
    if not module.is_init:
        for s in scopes:
            for name, (line, kind) in s.bindings.items():
                if kind != "import" or name in s.used:
                    continue
                if name == "_" or name.startswith("__"):
                    continue
                if module.suppressed(line, "unused-import"):
                    continue
                out.append(Finding(
                    module.path, line, "unused-import",
                    f"'{name}' is imported but never used",
                    "drop the import",
                    ""))

    # shadowed builtins (function/module scopes; class attrs are fine)
    for s in scopes:
        if s.kind == "class":
            continue
        for name, (line, kind) in s.bindings.items():
            if name in _SHADOW_BUILTINS and kind in (
                    "assign", "arg", "for", "def", "with", "except"):
                if not module.suppressed(line, "shadowed-builtin"):
                    out.append(Finding(
                        module.path, line, "shadowed-builtin",
                        f"'{name}' shadows the builtin of the same "
                        f"name",
                        "rename the binding",
                        ""))
    return out


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    stale: List[dict] = field(default_factory=list)
    files: int = 0
    # baseline entries that matched a finding this run (justifications
    # preserved by --write-baseline), and entries outside this run's
    # rule/path scope (carried, neither matched nor stale: a subset
    # invocation must not strand or destroy them)
    matched_entries: List[dict] = field(default_factory=list)
    carried_entries: List[dict] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings and not self.stale

    def to_json(self) -> dict:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "files": self.files,
            "findings": [f.to_json() for f in self.findings],
            "baselined": self.suppressed,
            "stale_baseline": self.stale,
            "counts": counts,
            "clean": self.clean,
        }


def _iter_py_files(paths: List[str], root: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            out.append((ap, os.path.relpath(ap, root)))
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    fp = os.path.join(dirpath, fn)
                    out.append((fp, os.path.relpath(fp, root)))
    return out


def run(paths: List[str], root: Optional[str] = None,
        baseline: Optional[str] = None,
        rules: Optional[Set[str]] = None) -> Report:
    root = os.path.abspath(root or os.getcwd())
    report = Report()
    project = Project(root=root)
    for abspath, relpath in _iter_py_files(paths, root):
        try:
            with open(abspath) as f:
                text = f.read()
            project.modules.append(Module(abspath, relpath, text))
        except SyntaxError as e:
            report.findings.append(Finding(
                relpath.replace(os.sep, "/"), e.lineno or 1,
                "parse-error", f"syntax error: {e.msg}",
                "fix the syntax", ""))
        except ValueError as e:
            # e.g. NUL bytes: ast.parse raises ValueError, not
            # SyntaxError — still a per-file finding, never a crash
            report.findings.append(Finding(
                relpath.replace(os.sep, "/"), 1, "parse-error",
                f"unparseable source: {e}", "fix the file", ""))
        except OSError as e:
            report.findings.append(Finding(
                relpath.replace(os.sep, "/"), 1, "parse-error",
                f"unreadable: {e}", "fix the file", ""))
    report.files = len(project.modules)
    project.collect()

    def want(rule: str) -> bool:
        return rules is None or rule in rules

    read_patterns: List[str] = []
    for m in project.modules:
        if want("donation-after-use"):
            report.findings.extend(
                f for f in check_donation(m)
                if not m.suppressed(f.line, f.rule))
        if want("rng-key-reuse"):
            report.findings.extend(check_rng(m))
        if want("retrace-hazard"):
            report.findings.extend(check_retrace(m))
        if want("single-owner"):
            report.findings.extend(check_single_owner(m, project))
        if want("schema-contract"):
            report.findings.extend(check_schema(m, project))
        if want("knob-registry"):
            report.findings.extend(check_knobs(m, project, read_patterns))
        if any(want(r) for r in GENERIC_RULES):
            report.findings.extend(
                f for f in check_generic(m) if want(f.rule))
    if want("knob-registry"):
        report.findings.extend(
            check_knob_registry_side(project, read_patterns))

    seen: Set[Tuple] = set()
    deduped: List[Finding] = []
    for f in report.findings:
        k = f.key() + (f.line,)
        if k not in seen:
            seen.add(k)
            deduped.append(f)
    report.findings = deduped

    if baseline:
        entries = load_baseline(baseline)
        # path scope = the scan ROOTS, not just files that still exist:
        # an entry for a deleted file under a scanned directory must go
        # stale (so the baseline shrinks), while entries outside a
        # subset invocation's roots are merely carried
        scan_roots: List[str] = []
        for p in paths:
            ap = p if os.path.isabs(p) else os.path.join(root, p)
            rp = os.path.relpath(ap, root).replace(os.sep, "/")
            scan_roots.append(rp + "/" if os.path.isdir(ap) else rp)

        def path_in_scope(ep: str) -> bool:
            return any(ep == r or (r.endswith("/") and ep.startswith(r))
                       for r in scan_roots)

        in_scope = [(rules is None or e["rule"] in rules)
                    and path_in_scope(e["path"]) for e in entries]
        matched = [False] * len(entries)
        kept: List[Finding] = []
        for f in report.findings:
            hit = False
            for i, e in enumerate(entries):
                # one entry suppresses at most ONE finding: a second
                # identical violation added later must surface, not
                # ride an existing justification
                if not matched[i] and (
                        e["rule"], e["path"], e["context"],
                        e["message"]) == f.key():
                    matched[i] = True
                    hit = True
                    break
            if hit:
                report.suppressed += 1
            else:
                kept.append(f)
        report.findings = kept
        # an entry is stale only when this run could have matched it:
        # its rule ran and its file was scanned.  Out-of-scope entries
        # are carried so subset invocations (--rules gen, single files)
        # neither fail on them nor destroy them on --write-baseline.
        report.matched_entries = [e for e, ok in zip(entries, matched)
                                  if ok]
        report.carried_entries = [e for e, sc in zip(entries, in_scope)
                                  if not sc]
        report.stale = [e for e, ok, sc in zip(entries, matched,
                                               in_scope)
                        if sc and not ok]
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return report


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="apexlint",
        description="invariant-aware static analysis for the tpu-apex "
                    "fleet (pure stdlib ast, no jax import)")
    ap.add_argument("paths", nargs="*",
                    default=["pytorch_distributed_tpu", "tools"])
    ap.add_argument("--root", default=None,
                    help="repo root (README/TESTING + relpaths); "
                         "default cwd")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: "
                         "tools/apexlint_baseline.json when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run ('gen' = the "
                         "generic pass, 'apex' = the invariant rules)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--allow-stale", action="store_true",
                    help="stale baseline entries warn instead of fail")
    ap.add_argument("--write-baseline", default=None, metavar="FILE",
                    help="write current findings as a baseline skeleton "
                         "(justifications must then be filled in)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, doc in RULES.items():
            print(f"{rule:22s} {doc}")
        return 0

    rules: Optional[Set[str]] = None
    if args.rules:
        rules = set()
        for r in args.rules.split(","):
            r = r.strip()
            if r == "gen":
                rules.update(GENERIC_RULES)
            elif r == "apex":
                rules.update(k for k in RULES
                             if k not in GENERIC_RULES)
            elif r in RULES:
                rules.add(r)
            else:
                print(f"apexlint: unknown rule '{r}'", file=sys.stderr)
                return 2
        rules.add("parse-error")

    root = os.path.abspath(args.root or os.getcwd())
    baseline = args.baseline
    if baseline is None and not args.no_baseline:
        default = os.path.join(root, "tools", "apexlint_baseline.json")
        if os.path.exists(default):
            baseline = default
    if args.no_baseline:
        baseline = None

    try:
        report = run(args.paths, root=root, baseline=baseline,
                     rules=rules)
    except BaselineError as e:
        print(f"apexlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        # still-matching and out-of-scope entries keep their written
        # justifications; only NEW findings get TODO skeletons
        entries = report.matched_entries + report.carried_entries + [
            dict(rule=f.rule, path=f.path, context=f.context,
                 message=f.message,
                 justification="TODO: justify or fix")
            for f in report.findings]
        with open(args.write_baseline, "w") as fh:
            json.dump({"entries": entries}, fh, indent=2,
                      ensure_ascii=False)
            fh.write("\n")
        print(f"apexlint: wrote {len(entries)} baseline entries "
              f"({len(report.findings)} new) to {args.write_baseline} "
              f"— fill in every TODO justification")
        return 1 if report.findings else 0

    if args.as_json:
        print(json.dumps(report.to_json(), indent=2))
    else:
        for f in report.findings:
            print(f.format())
        for e in report.stale:
            print(f"{e['path']} · baseline-stale · {e['rule']} entry no "
                  f"longer matches: {e['message'][:60]}")
        print(f"apexlint: {report.files} files, "
              f"{len(report.findings)} findings, "
              f"{report.suppressed} baselined, "
              f"{len(report.stale)} stale baseline entries")
    if report.findings:
        return 1
    if report.stale and not args.allow_stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
