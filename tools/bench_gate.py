#!/usr/bin/env python
"""Bench regression gate: diff two bench JSONs, keep history, exit
nonzero on regression — perf as a CI check, not an offline artifact.

``bench.py`` prints one JSON line per run; until this tool the only
consumer was a human eyeballing BENCH_r0N files.  The gate makes the
comparison mechanical and schema-aware:

- **What is compared**: a fixed spec table of throughput keys (higher is
  better) and overhead fractions (lower is better, absolute tolerance),
  spanning every bench section — micro headline, per-family rows,
  sampler, actor pipeline, e2e, health/perf overhead, and the ``--smoke``
  section.  Keys missing on EITHER side are skipped (an e2e-less candidate
  is not a regression), and ``bench_schema`` must match — a key whose
  MEANING changed between schemas (the round-3 lesson bench.py documents)
  must never be numerically compared across them
  (``--allow-schema-drift`` overrides, for deliberate migrations).
- **Tolerances**: per-section relative slack (dispatch through a
  tunnelled chip is noisy; e2e carries actor jitter), overridable with
  repeatable ``--tol SECTION=FRAC``.  Overhead fractions use an absolute
  band instead — a 0.001 -> 0.002 "2x regression" on a noise-floor
  number is not a finding.
- **History**: ``--record FILE`` appends one JSONL row per gate run
  (wall clock, schema, headline, verdict, per-key outcomes), building
  the same-machine longitudinal record absolute rates need
  (``BENCH_HISTORY.jsonl`` at the repo root by convention).

Usage:
    python bench.py --smoke | python tools/bench_gate.py - \
        --against BENCH_SMOKE_BASELINE.json --record BENCH_HISTORY.jsonl
    python tools/bench_gate.py BENCH_r04.json --against BENCH_r03.json

Exit codes: 0 pass, 1 regression, 2 usage/schema error.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# comparison spec: (dotted path, direction, section)
#
# direction "higher" — candidate must stay within (1 - tol) * baseline;
# direction "lower_abs" — candidate must stay under baseline + tol
# (absolute: these are overhead FRACTIONS living near the noise floor).
# A "*" path segment fans out over the keys present in BOTH dicts.
# ---------------------------------------------------------------------------

SPECS: List[Tuple[str, str, str]] = [
    ("updates_per_sec", "higher", "micro"),
    ("updates_per_sec_peak", "higher", "micro"),
    ("chip_bound_updates_per_sec", "higher", "micro"),
    ("families.*.updates_per_sec", "higher", "families"),
    # ISSUE-13 megabatch capability rows: the flat families' widened-
    # gather fused rate (bench_families MEGABATCH_FAMILIES leg) and the
    # smoke twin — the MLP-family wins this campaign lands would
    # otherwise be unprotected
    ("families.*.updates_per_sec_megabatch", "higher", "families"),
    ("sampler.xla_draws_per_sec", "higher", "sampler"),
    ("sampler.pallas_draws_per_sec", "higher", "sampler"),
    ("act_ab.act_ms_host", "lower_rel", "act"),
    ("actor_pipeline.inline.frames_per_sec", "higher", "actor"),
    ("actor_pipeline.pipelined.frames_per_sec", "higher", "actor"),
    ("actor_pipeline.env_only_frames_per_sec", "higher", "actor"),
    ("e2e_frames_per_sec", "higher", "e2e"),
    ("e2e_paced_updates_per_sec", "higher", "e2e"),
    ("health_overhead.health_overhead_frac", "lower_abs", "overhead"),
    ("perf_overhead.perf_overhead_frac", "lower_abs", "overhead"),
    ("provenance_overhead.provenance_overhead_frac", "lower_abs",
     "overhead"),
    ("metrics_overhead.metrics_overhead_frac", "lower_abs", "overhead"),
    ("flow_overhead.flow_overhead_frac", "lower_abs", "overhead"),
    ("replica_overhead.replica_overhead_frac", "lower_abs", "overhead"),
    ("gateway_ha_overhead.gateway_ha_overhead_frac", "lower_abs",
     "overhead"),
    # ISSUE-18 wire byte economics: deterministic counts (savez layout
    # at fixed geometry), so a regression here is a wire-format change
    # — the compression campaign must move these DOWN, never up
    ("wire.bytes_per_transition", "lower_rel", "wire"),
    ("wire.replica_bytes_per_round", "lower_rel", "wire"),
    ("wire_overhead.wire_overhead_frac", "lower_abs", "overhead"),
    # ISSUE-20 sharded-replay plane: per-shard-count sample latency
    # (loopback, so plane arithmetic — regressions are tree/merge
    # changes, not socket noise) and the mass-refresh+route cost held
    # inside the overhead band
    ("shard.sample_ms_1shard", "lower_rel", "shard"),
    ("shard.sample_ms_2shard", "lower_rel", "shard"),
    ("shard.sample_ms_4shard", "lower_rel", "shard"),
    ("shard_overhead.shard_overhead_frac", "lower_abs", "overhead"),
    ("device_env.host_frames_per_sec", "higher", "device_env"),
    ("device_env.device_frames_per_sec", "higher", "device_env"),
    ("device_env.fused_frames_per_sec", "higher", "device_env"),
    ("device_env.speedup_vs_host", "higher", "device_env"),
    ("anakin.frames_per_sec", "higher", "anakin"),
    ("anakin.updates_per_sec", "higher", "anakin"),
    ("anakin.speedup_vs_device", "higher", "anakin"),
    ("smoke.updates_per_sec", "higher", "smoke"),
    ("smoke.updates_per_sec_megabatch", "higher", "smoke"),
    ("smoke.device_env_frames_per_sec", "higher", "smoke"),
    ("smoke.anakin_frames_per_sec", "higher", "smoke"),
]

# Per-section default tolerance.  Relative for rates (sized to the
# window noise each section's docstring documents), ABSOLUTE for the
# overhead fractions.
DEFAULT_TOL: Dict[str, float] = {
    "micro": 0.15,
    "families": 0.20,
    "sampler": 0.20,
    "act": 0.30,
    "actor": 0.25,
    "e2e": 0.30,
    "overhead": 0.02,   # absolute band on a <2%-by-contract fraction
    # env-fleet rates: XLA dispatch + host scheduling noise on small
    # hosts; the speedup ratio divides out most machine noise but
    # keeps the same band for simplicity
    "device_env": 0.30,
    # closed-loop pair rate + its split-process speedup (ISSUE 12):
    # same dispatch-noise profile as device_env, and the split leg
    # adds spawn-queue scheduling jitter on loaded hosts
    "anakin": 0.30,
    "smoke": 0.40,      # CPU-host scheduling noise is large at small K
    # byte counts are layout-deterministic; the slack only covers savez
    # header drift across numpy versions
    "wire": 0.10,
    # loopback sample latency: pure python/numpy tree walks measured
    # best-of-chunks, but a gate host running the full check.sh chain
    # is LOADED — a genuine regression (an accidental linear scan in
    # the two-level walk) blows past 2x, scheduler contention doesn't
    "shard": 1.00,
}


def _lookup(d: dict, path: str) -> Any:
    cur: Any = d
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _expand(path: str, cand: dict, base: dict) -> List[str]:
    """Expand one '*' segment over keys present in BOTH sides."""
    if "*" not in path:
        return [path]
    head, _, tail = path.partition(".*.")
    c, b = _lookup(cand, head), _lookup(base, head)
    if not isinstance(c, dict) or not isinstance(b, dict):
        return []
    return [f"{head}.{k}.{tail}" for k in sorted(c.keys() & b.keys())]


def compare(candidate: dict, baseline: dict,
            tol: Optional[Dict[str, float]] = None) -> dict:
    """Schema-aware diff.  Returns a report dict with ``checked`` (every
    key compared, with values and verdicts), ``regressions`` (the failed
    subset) and ``improvements`` (informational)."""
    tols = dict(DEFAULT_TOL)
    tols.update(tol or {})
    checked, regressions, improvements = [], [], []
    for spec_path, direction, section in SPECS:
        for path in _expand(spec_path, candidate, baseline):
            c, b = _lookup(candidate, path), _lookup(baseline, path)
            if not isinstance(c, (int, float)) \
                    or not isinstance(b, (int, float)):
                continue  # missing/errored on either side: not comparable
            t = tols.get(section, 0.2)
            if direction == "higher":
                bad = c < b * (1.0 - t)
                better = c > b * (1.0 + t)
            elif direction == "lower_rel":
                bad = c > b * (1.0 + t)
                better = c < b * (1.0 - t)
            else:  # lower_abs
                bad = c > b + t
                better = c < b - t
            row = {"key": path, "candidate": c, "baseline": b,
                   "direction": direction, "tolerance": t,
                   "section": section,
                   "verdict": ("regression" if bad else
                               "improvement" if better else "ok")}
            checked.append(row)
            if bad:
                regressions.append(row)
            elif better:
                improvements.append(row)
    return {"checked": checked, "regressions": regressions,
            "improvements": improvements}


def record_history(path: str, candidate: dict, against: str,
                   report: dict) -> None:
    """One append-only JSONL row per gate run — the same-machine
    longitudinal record.  Append is a single atomic line write, same
    contract as the metrics stream (utils/metrics.py)."""
    row = {
        "wall": time.time(),
        "bench_schema": candidate.get("bench_schema"),
        "metric": candidate.get("metric"),
        "value": candidate.get("value"),
        "device_kind": candidate.get("device_kind"),
        "mode": candidate.get("mode", "full"),
        "against": against,
        "checked": len(report["checked"]),
        "regressions": [r["key"] for r in report["regressions"]],
        "improvements": [r["key"] for r in report["improvements"]],
        "pass": not report["regressions"],
    }
    with open(path, "a") as f:
        f.write(json.dumps(row) + "\n")


def _load(source: str) -> dict:
    """A bench artifact: a JSON file, or '-' for stdin.  bench.py prints
    exactly one JSON line on stdout, but artifacts saved from noisy
    runs may carry stray stderr lines — take the LAST parseable object
    line."""
    text = sys.stdin.read() if source == "-" else open(source).read()
    last_err: Optional[Exception] = None
    try:
        return json.loads(text)
    except ValueError as e:
        last_err = e
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            return json.loads(line)
        except ValueError as e:
            last_err = e
    raise ValueError(f"no JSON object found in {source!r}: {last_err}")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/bench_gate.py",
        description="diff two bench JSONs; exit 1 on regression")
    ap.add_argument("candidate",
                    help="candidate bench JSON (file path, or '-' to "
                         "read bench.py's output from stdin)")
    ap.add_argument("--against", required=True, metavar="BASELINE.json",
                    help="baseline bench JSON to gate against")
    ap.add_argument("--tol", action="append", default=[],
                    metavar="SECTION=FRAC",
                    help="per-section tolerance override (repeatable), "
                         f"sections: {', '.join(sorted(DEFAULT_TOL))}")
    ap.add_argument("--record", type=str, default=None,
                    metavar="HISTORY.jsonl",
                    help="append this gate run to a JSONL history file")
    ap.add_argument("--json", action="store_true",
                    help="print the full machine-readable report")
    ap.add_argument("--allow-schema-drift", action="store_true",
                    help="compare across differing bench_schema values "
                         "(keys may have changed MEANING — only for "
                         "deliberate migrations)")
    args = ap.parse_args(argv)

    tol: Dict[str, float] = {}
    for kv in args.tol:
        k, _, v = kv.partition("=")
        if k not in DEFAULT_TOL:
            ap.error(f"unknown tolerance section {k!r} "
                     f"(know: {', '.join(sorted(DEFAULT_TOL))})")
        try:
            tol[k] = float(v)
        except ValueError:
            ap.error(f"bad tolerance value in {kv!r}")

    try:
        candidate = _load(args.candidate)
        baseline = _load(args.against)
    except (OSError, ValueError) as e:
        print(f"bench_gate: {e}", file=sys.stderr)
        return 2

    cs, bs = candidate.get("bench_schema"), baseline.get("bench_schema")
    if cs != bs and not args.allow_schema_drift:
        print(f"bench_gate: bench_schema mismatch (candidate {cs!r} vs "
              f"baseline {bs!r}) — keys may have changed meaning; "
              f"re-baseline or pass --allow-schema-drift",
              file=sys.stderr)
        return 2

    report = compare(candidate, baseline, tol)
    if args.record:
        record_history(args.record, candidate, args.against, report)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        if not report["checked"]:
            print("bench_gate: no comparable keys between candidate and "
                  "baseline", file=sys.stderr)
        for row in report["checked"]:
            mark = {"ok": " ok ", "regression": "FAIL",
                    "improvement": " ++ "}[row["verdict"]]
            print(f"[{mark}] {row['key']}: {row['candidate']:g} vs "
                  f"baseline {row['baseline']:g} "
                  f"(tol {row['tolerance']:g}, {row['direction']})")
    if report["regressions"]:
        print(f"bench_gate: {len(report['regressions'])} regression(s) "
              f"out of {len(report['checked'])} checked", file=sys.stderr)
        return 1
    print(f"bench_gate: pass ({len(report['checked'])} checked, "
          f"{len(report['improvements'])} improved)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
