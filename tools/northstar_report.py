#!/usr/bin/env python
"""Produce the north-star artifact (RESULTS.json) from a training run.

Reads a run's ``scalars.jsonl`` and computes the BASELINE.md acceptance
numbers for "Distributed DQN reaches 18.0 mean eval reward on TPU":

- wall-clock (and learner steps) to the first eval >= threshold,
- env frames/sec/chip over the full run (agent steps; x4 emulated
  frames, reference core/envs/atari_env.py:95) — the accounting of
  reference core/single_processes/dqn_logger.py:42,
- learner updates/sec (median of logger windows),
- the full eval-reward curve for the record.

Usage:
    python tools/northstar_report.py <log_dir> [--threshold 18] \
        [--out RESULTS.json] [--meta k=v ...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def load(log_dir: str):
    path = os.path.join(log_dir, "scalars.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def series(rows, tag):
    """(wall, value, learner_step) triples for one tag (every scalar
    record carries the learner step as its x-axis, utils/metrics.py)."""
    return [(r["wall"], r["value"], r.get("step", 0)) for r in rows
            if r["tag"] == tag]


def report(log_dir: str, threshold: float, n_chips: int = 1) -> dict:
    rows = load(log_dir)
    t0 = min(r["wall"] for r in rows)
    evals = series(rows, "evaluator/avg_reward")
    frames = series(rows, "actor/total_nframes")  # per-window drained counts
    lsteps = series(rows, "learner/steps_per_sec")

    out = {
        "threshold": threshold,
        "n_chips": n_chips,
        "run_seconds": round(max(w for w, _, _ in evals + frames) - t0, 1),
        "eval_curve": [[round(w - t0, 1), v, s] for w, v, s in evals],
        "best_eval_reward": max(v for _, v, _ in evals) if evals else None,
    }

    hit = next(((w, v, s) for w, v, s in evals if v >= threshold), None)
    if hit:
        out["wall_clock_to_threshold_sec"] = round(hit[0] - t0, 1)
        out["learner_steps_to_threshold"] = int(hit[2])
    else:
        out["wall_clock_to_threshold_sec"] = None

    if len(frames) > 1:
        span = frames[-1][0] - frames[0][0]
        agent_steps = sum(v for _, v, _ in frames[1:])
        out["env_frames_per_sec_per_chip"] = round(
            agent_steps / span / n_chips, 1)
        out["emulator_frames_per_sec_per_chip"] = round(
            4 * agent_steps / span / n_chips, 1)
        out["total_agent_steps"] = int(sum(v for _, v, _ in frames))
    if lsteps:
        vals = sorted(v for _, v, _ in lsteps if v > 0)
        if vals:
            out["learner_updates_per_sec_median"] = round(
                vals[len(vals) // 2], 2)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("log_dir")
    ap.add_argument("--threshold", type=float, default=18.0)
    ap.add_argument("--n-chips", type=int, default=1)
    ap.add_argument("--out", default=None)
    ap.add_argument("--meta", action="append", default=[], metavar="K=V")
    args = ap.parse_args()

    rep = report(args.log_dir, args.threshold, args.n_chips)
    for kv in args.meta:
        k, _, v = kv.partition("=")
        rep[k] = v
    text = json.dumps(rep, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    sys.stdout.write(text + "\n")


if __name__ == "__main__":
    main()
