#!/usr/bin/env bash
# Pre-PR gate (ISSUE 9): chain the whole tool layer — the lint plane
# (invariant rules + generic pass), the seconds-scale smoke bench, and
# the schema-aware regression gate.  Exit nonzero on the first failing
# stage.  TESTING.md "Static-analysis gate" documents the workflow.
#
#   tools/check.sh                 # full gate
#   APEXLINT_ONLY=1 tools/check.sh # lint only (noisy-host escape hatch)
set -u -o pipefail
cd "$(dirname "$0")/.."

tmp="$(mktemp -d)"
trap 'rm -rf "$tmp"' EXIT

echo "== stage 1: apexlint (invariant rules + generic pass) =="
python tools/apexlint.py pytorch_distributed_tpu tools --json \
    > "$tmp/apexlint.json"
lint_rc=$?
if [ "$lint_rc" -ne 0 ]; then
    # exit 2 = usage/config error (malformed baseline, unknown rule):
    # the real message is already on stderr and no JSON was written
    if [ "$lint_rc" -eq 2 ]; then
        echo "apexlint: CONFIG ERROR (see the message above — likely"
        echo "tools/apexlint_baseline.json or the invocation)"
        exit "$lint_rc"
    fi
    python - "$tmp/apexlint.json" <<'EOF' || true
import json, sys
try:
    d = json.load(open(sys.argv[1]))
except Exception:
    sys.exit(1)
for f in d["findings"]:
    print(f"  {f['path']}:{f['line']} · {f['rule']} · {f['message']}")
for e in d["stale_baseline"]:
    print(f"  stale baseline: {e['rule']} at {e['path']}")
EOF
    echo "apexlint: FAIL (fix the findings or baseline them with a"
    echo "justification in tools/apexlint_baseline.json)"
    exit "$lint_rc"
fi
echo "apexlint: PASS ($(python -c "import json,sys;d=json.load(open('$tmp/apexlint.json'));print(f\"{d['files']} files, {d['baselined']} baselined\")"))"

echo "== stage 1b: fleet_top --selftest (mission-control alert plane) =="
# the ISSUE-10 smoke: a synthetic gateway + mission control probed over
# the real wire — T_METRICS push, absence alert fires, --json blocks
# round-trip.  Seconds-scale, no jax.
if ! JAX_PLATFORMS=cpu python tools/fleet_top.py --selftest; then
    echo "fleet_top --selftest: FAIL"
    exit 1
fi

if [ "${APEXLINT_ONLY:-0}" = "1" ]; then
    echo "APEXLINT_ONLY=1: skipping bench stages"
    exit 0
fi

echo "== stage 1c: gateway failover drill (ISSUE 16) =="
# the fast HA drill: kill the primary under a live synthetic fleet —
# the warm standby must promote within one lease window, clients must
# fail over, the ledger must stay EXACT (failover_lost counted), and
# the gateway_failover alert must fire and resolve.  Seconds-scale,
# no jax; a standby that never promotes is a readable nonzero verdict
if ! JAX_PLATFORMS=cpu python tools/chaos_soak.py \
        --seconds 6 --kill-gateway 1.5 --gateway-lease 0.6; then
    echo "gateway failover drill: FAIL"
    exit 1
fi

echo "== stage 1d: shard-loss degradation drill (ISSUE 20) =="
# the fast replay-shard drill: kill one shard of a live 3-shard
# priority plane — the lease must fence within one window, sampling
# must continue on the survivors, the row ledger must stay EXACT
# (minted == ingested + shard_lost + route_dropped), the dead
# generation's write-backs must be rejected, and the rejoined shard
# must pass the join barrier.  Seconds-scale, no jax.
if ! JAX_PLATFORMS=cpu python tools/chaos_soak.py \
        --seconds 6 --kill-shard 1.5 --rejoin-shard --shard-lease 0.5; then
    echo "shard-loss drill: FAIL"
    exit 1
fi

echo "== stage 2: bench --smoke =="
# covers the fused learner program, the ISSUE-7 device-env engine AND
# the ISSUE-12 anakin closed-loop pair rate (smoke.anakin_frames_per_sec
# gates vs the baseline in stage 3)
if ! python bench.py --smoke > "$tmp/smoke.json"; then
    echo "bench --smoke: FAIL"
    exit 1
fi
echo "bench --smoke: PASS"

echo "== stage 2b: megabatch smoke key (ISSUE 13) =="
# the megabatched fused-learner rate must be present and positive —
# a smoke run that silently dropped the leg would leave the campaign's
# capability ungated (stage 3 then regression-compares it)
if ! python - "$tmp/smoke.json" <<'EOF'
import json, sys
d = json.load(open(sys.argv[1]))
v = d.get("smoke", {}).get("updates_per_sec_megabatch")
assert isinstance(v, (int, float)) and v > 0, \
    f"smoke.updates_per_sec_megabatch missing/invalid: {v!r}"
print(f"smoke.updates_per_sec_megabatch = {v}")
EOF
then
    echo "megabatch smoke key: FAIL"
    exit 1
fi

echo "== stage 2c: replica smoke key (ISSUE 15) =="
# the replica-plane overhead fraction must be present and sane — a
# smoke run that silently dropped the leg would leave the multi-learner
# plane's cost ungated (stage 3 then holds it under the 0.02 band)
if ! python - "$tmp/smoke.json" <<'EOF'
import json, sys
d = json.load(open(sys.argv[1]))
v = d.get("replica_overhead", {}).get("replica_overhead_frac")
assert isinstance(v, (int, float)) and 0 <= v, \
    f"replica_overhead.replica_overhead_frac missing/invalid: {v!r}"
print(f"replica_overhead.replica_overhead_frac = {v}")
EOF
then
    echo "replica smoke key: FAIL"
    exit 1
fi

echo "== stage 2d: gateway HA smoke key (ISSUE 16) =="
# the gateway HA-plane overhead fraction must be present and sane — a
# smoke run that silently dropped the leg would leave the failover
# plane's cost ungated (stage 3 then holds it under the 0.02 band)
if ! python - "$tmp/smoke.json" <<'EOF'
import json, sys
d = json.load(open(sys.argv[1]))
v = d.get("gateway_ha_overhead", {}).get("gateway_ha_overhead_frac")
assert isinstance(v, (int, float)) and 0 <= v, \
    f"gateway_ha_overhead.gateway_ha_overhead_frac missing/invalid: {v!r}"
print(f"gateway_ha_overhead.gateway_ha_overhead_frac = {v}")
EOF
then
    echo "gateway HA smoke key: FAIL"
    exit 1
fi

echo "== stage 2e: wire smoke keys (ISSUE 18) =="
# the bandwidth X-ray's headline (frame-packed bytes/transition) must
# be present and NONZERO — a zero here means the accountant stopped
# stamping the EXP plane — and the accountant's hot-path cost must be
# present and sane (stage 3 then holds it under the 0.02 band)
if ! python - "$tmp/smoke.json" <<'EOF'
import json, sys
d = json.load(open(sys.argv[1]))
v = d.get("wire", {}).get("bytes_per_transition")
assert isinstance(v, (int, float)) and v > 0, \
    f"wire.bytes_per_transition missing/zero: {v!r}"
print(f"wire.bytes_per_transition = {v}")
f = d.get("wire_overhead", {}).get("wire_overhead_frac")
assert isinstance(f, (int, float)) and 0 <= f, \
    f"wire_overhead.wire_overhead_frac missing/invalid: {f!r}"
print(f"wire_overhead.wire_overhead_frac = {f}")
EOF
then
    echo "wire smoke keys: FAIL"
    exit 1
fi

echo "== stage 2f: shard smoke keys (ISSUE 20) =="
# the sharded-replay plane: per-shard-count sample latency must be
# present and positive, and the sharding overhead fraction must be
# present and sane (stage 3 then holds it under the 0.02 band)
if ! python - "$tmp/smoke.json" <<'EOF'
import json, sys
d = json.load(open(sys.argv[1]))
v = d.get("shard", {}).get("sample_ms_1shard")
assert isinstance(v, (int, float)) and v > 0, \
    f"shard.sample_ms_1shard missing/invalid: {v!r}"
print(f"shard.sample_ms_1shard = {v}")
f = d.get("shard_overhead", {}).get("shard_overhead_frac")
assert isinstance(f, (int, float)) and 0 <= f, \
    f"shard_overhead.shard_overhead_frac missing/invalid: {f!r}"
print(f"shard_overhead.shard_overhead_frac = {f}")
EOF
then
    echo "shard smoke keys: FAIL"
    exit 1
fi

echo "== stage 3: bench_gate vs BENCH_SMOKE_BASELINE.json =="
# generous smoke tolerance: this stage pins the pipeline on any host;
# same-machine perf gating uses the recorded history (TESTING.md)
if ! python tools/bench_gate.py "$tmp/smoke.json" \
        --against BENCH_SMOKE_BASELINE.json --tol smoke=0.9 \
        --record BENCH_HISTORY.jsonl; then
    echo "bench_gate: FAIL"
    exit 1
fi
echo "bench_gate: PASS"
echo "pre-PR gate: ALL STAGES PASS"
