#!/usr/bin/env python
"""Name the resource that bounds the flagship learner's MFU.

The bench (bench.py micro) reports ~17% MFU for the fused batch-128
Nature-DQN update at the chip-bound asymptote — this probe explains WHY,
with a real XLA profile rather than an assertion:

1. captures a ``jax.profiler`` trace of the production fused K=32
   program on the chip and converts it op-by-op with xprof
   (tensorboard_plugin_profile) to a self-time ranking;
2. sweeps the levers that would move the number if the bound were
   elsewhere: batch scaling (128 -> 512 at constant FLOP intensity per
   row) and compute dtype (bf16 vs f32);
3. prints one JSON blob with the top ops, the per-lever MFUs, and the
   inferred ``mfu_bound`` string the bench can quote.

Usage: python tools/mfu_probe.py [--trace-dir DIR] [--skip-trace]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_fused(B: int, K: int, compute_dtype, channels_last: bool = False):
    import jax

    from pytorch_distributed_tpu.memory.device_replay import (
        DeviceReplay, build_uniform_fused_step,
    )
    from pytorch_distributed_tpu.models import DqnCnnModel
    from pytorch_distributed_tpu.ops.losses import (
        build_dqn_train_step, init_train_state, make_optimizer,
    )
    from pytorch_distributed_tpu.utils.experience import Transition

    model = DqnCnnModel(action_space=6, norm_val=255.0,
                        compute_dtype=compute_dtype,
                        nhwc_input=channels_last)
    obs = np.zeros((1, 84, 84, 4) if channels_last else (1, 4, 84, 84),
                   dtype=np.uint8)
    params = model.init(jax.random.PRNGKey(0), obs)
    tx = make_optimizer(lr=1e-4)
    state = init_train_state(params, tx)
    step = build_dqn_train_step(model.apply, tx, target_model_update=250)
    ring = DeviceReplay(capacity=2048, state_shape=(4, 84, 84),
                        state_dtype=np.uint8, channels_last=channels_last)
    rng = np.random.default_rng(0)
    C = 512
    for _ in range(ring.capacity // C):
        ring.feed_chunk(Transition(
            state0=rng.integers(0, 255, (C, 4, 84, 84)).astype(np.uint8),
            action=rng.integers(0, 6, C).astype(np.int32),
            reward=rng.normal(size=C).astype(np.float32),
            gamma_n=np.full(C, 0.99 ** 5, np.float32),
            state1=rng.integers(0, 255, (C, 4, 84, 84)).astype(np.uint8),
            terminal1=(rng.random(C) < 0.1).astype(np.float32)))
    fused = build_uniform_fused_step(step, B, steps_per_call=K)
    return fused, state, ring


def measure(fused, state, ring, K: int, windows: int = 5,
            iters: int = 24) -> tuple:
    """Fetch-bounded updates/s + XLA cost-analysis flops/update."""
    import jax

    key = jax.random.PRNGKey(0)

    def keymat():
        nonlocal key
        key, sub = jax.random.split(key)
        return jax.random.split(sub, K)

    compiled = fused.lower(state, ring.state, keymat()).compile()
    # shared with bench.py and the live perf plane (utils/perf.py) —
    # one extraction, three consumers
    from pytorch_distributed_tpu.utils.perf import flops_of_compiled

    flops = flops_of_compiled(compiled)
    for _ in range(6):
        state, m = compiled(state, ring.state, keymat())
    float(jax.device_get(m["learner/critic_loss"]))
    rates = []
    for _ in range(windows):
        ks = [keymat() for _ in range(iters)]
        jax.block_until_ready(ks[-1])
        t0 = time.perf_counter()
        for k in ks:
            state, m = compiled(state, ring.state, k)
        float(jax.device_get(m["learner/critic_loss"]))  # fetch-bounded
        rates.append(iters * K / (time.perf_counter() - t0))
    return float(np.median(rates)), flops, state, compiled


def capture_trace(compiled, state, ring, K: int, trace_dir: str) -> None:
    import jax

    key = jax.random.PRNGKey(1)
    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        for _ in range(4):
            key, sub = jax.random.split(key)
            state, m = compiled(state, ring.state,
                                jax.random.split(sub, K))
        float(jax.device_get(m["learner/critic_loss"]))


def op_breakdown(trace_dir: str, top: int = 12) -> list:
    """Convert the captured xplane with xprof and rank ops by self time."""
    paths = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        return [{"error": "no xplane.pb captured"}]
    path = max(paths, key=os.path.getmtime)
    # xprof is the maintained layout; the legacy tensorboard_plugin_profile
    # ships stale protobuf gencode that explodes on protobuf>=4 unless the
    # pure-python parser is forced
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                          "python")
    try:
        from xprof.convert import raw_to_tool_data
    except ImportError:
        from tensorboard_plugin_profile.convert import raw_to_tool_data
    data, _ = raw_to_tool_data.xspace_to_tool_data([path], "hlo_stats", {})
    if isinstance(data, bytes):
        data = data.decode()
    table = json.loads(data)
    # gviz DataTable: {"cols": [{id,label}...], "rows": [{"c": [{"v":..}]}]}
    cols = [c.get("label", c.get("id", "")).lower()
            for c in table.get("cols", [])]
    rows = [[cell.get("v") if isinstance(cell, dict) else cell
             for cell in r.get("c", [])] for r in table.get("rows", [])]
    if not rows:
        return [{"error": "empty hlo_stats"}]

    def col(*names):
        for n in names:
            for i, h in enumerate(cols):
                if n in h:
                    return i
        return None

    i_name = col("hlo op name", "op name", "op_name")
    i_cat = col("category")
    i_self = col("total self time (us)", "self time (us)", "self")
    i_pct = col("total self time (%)", "self time (%)")
    out = []
    rows.sort(key=lambda r: -float(r[i_self] or 0))
    for r in rows[:top]:
        out.append({
            "op": str(r[i_name])[:90],
            "category": r[i_cat] if i_cat is not None else "?",
            "self_us": round(float(r[i_self] or 0), 1),
            "self_pct": (round(float(r[i_pct] or 0), 2)
                         if i_pct is not None else None),
        })
    return out


# trace categories that are layout work, not model math: the re-tiling
# share the bench's ``mfu_bound`` note quotes (ISSUE-13 satellite)
_RETILING_CATS = ("copy", "transpose", "reshape", "convert",
                  "data formatting")


def attribution_of(top_ops: list) -> dict:
    """Machine-readable attribution over an ``op_breakdown`` ranking:
    per-category self-time bins (fractions of the ranked total) and the
    re-tiling share (copy/transpose/reshape/convert categories) —
    what ``bench.py`` micro's ``mfu_bound`` note consumes from an
    ``MFU_PROBE.json`` artifact instead of a hand-copied string."""
    rows = [r for r in top_ops if "error" not in r]
    total = sum(r.get("self_us", 0.0) for r in rows)
    bins: dict = {}
    for r in rows:
        cat = str(r.get("category", "?")).lower() or "?"
        bins[cat] = bins.get(cat, 0.0) + r.get("self_us", 0.0)
    if total <= 0:
        return {"error": "no ranked ops", "bins": {}, "retiling_share": None}
    bins = {k: round(v / total, 4) for k, v in bins.items()}
    retiling = sum(v for k, v in bins.items()
                   if any(t in k for t in _RETILING_CATS))
    return {"retiling_share": round(retiling, 4), "bins": bins,
            "basis": "fraction of ranked-op self time"}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace-dir", default="/tmp/mfu_probe_trace")
    ap.add_argument("--skip-trace", action="store_true")
    ap.add_argument("--skip-levers", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="one-line machine-readable JSON (adds the "
                         "'attribution' section: re-tiling share + "
                         "per-category self-time bins)")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the JSON blob to FILE (point it "
                         "at MFU_PROBE.json in the repo root so "
                         "bench.py's mfu_bound note quotes this probe)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.utils.helpers import enable_compile_cache

    enable_compile_cache()
    dev = jax.devices()[0]
    from pytorch_distributed_tpu.utils.perf import peak_flops_of

    peak = peak_flops_of(dev) or float("nan")
    out = {"device_kind": getattr(dev, "device_kind", "?")}

    # production point: B=128, K=32, bf16
    fused, state, ring = build_fused(128, 32, jnp.bfloat16)
    rate, flops, state, compiled = measure(fused, state, ring, 32)
    out["b128_bf16"] = {
        "updates_per_sec": round(rate, 1),
        "flops_per_update": flops,
        "mfu": round(rate * flops / peak, 4) if flops else None,
    }
    if not args.skip_trace:
        capture_trace(compiled, state, ring, 32, args.trace_dir)
        out["top_ops"] = op_breakdown(args.trace_dir, top=24)
        out["trace_dir"] = args.trace_dir
        out["attribution"] = attribution_of(out["top_ops"])

    if not args.skip_levers:
        # lever 1: batch 512 (same program shape, 4x rows) — if the bound
        # were dispatch or bandwidth this rises sharply; if the MXU lanes
        # are the wall it rises only mildly
        fused4, state4, ring4 = build_fused(512, 8, jnp.bfloat16)
        r4, f4, _s, _c = measure(fused4, state4, ring4, 8)
        out["b512_bf16"] = {
            "updates_per_sec": round(r4, 1),
            "flops_per_update": f4,
            "mfu": round(r4 * f4 / peak, 4) if f4 else None,
        }
        # lever 2: f32 compute — halves MXU peak; if bf16 were underused
        # (e.g. everything upcast anyway) the rate would barely move
        fusedf, statef, ringf = build_fused(128, 32, jnp.float32)
        rf, ff, _s, _c = measure(fusedf, statef, ringf, 32)
        out["b128_f32"] = {
            "updates_per_sec": round(rf, 1),
            "flops_per_update": ff,
            "mfu_vs_bf16_peak": round(rf * ff / peak, 4) if ff else None,
        }

    blob = json.dumps(out) if args.json else json.dumps(out, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(json.dumps(out, indent=1) + "\n")
    print(blob)


if __name__ == "__main__":
    main()
