#!/usr/bin/env python
"""Unified fleet incident timeline (ISSUE 8 tentpole part 3).

Post-mortem data for one run is scattered across four artifact planes —
flight-recorder blackbox rings (``blackbox/<role>.jsonl``), the metrics
stream (``scalars.jsonl``: scalars, histogram rows, sampled trace
spans), ingest-quarantine files (``quarantine/<source>-<n>.npz``) and
injected-fault records (which land in the blackbox rings) — with no way
to read them as ONE story.  This tool merges them into a single
clock-aligned, causally-ordered timeline:

- **Clock alignment**: every DCN client estimates its wall-clock offset
  to the learner-host gateway off T_CLOCK reply midpoints (NTP-style,
  parallel/dcn.py) and records it as ``clock_sync`` blackbox events;
  the timeline shifts each remote role's events by its latest recorded
  offset, so cross-host ordering is honest to ~RTT/2 rather than to
  whatever the hosts' clocks drifted to.  Single-host runs need no
  shift.
- **Correlation keys**: rows join on ``run_id`` (stamped by
  MetricsWriter, blackbox dump headers and quarantine files), trace ids
  (spans + quarantine), and the ISSUE-8 provenance columns — never on
  directory layout.
- **Filtering**: ``--around PATTERN --window N`` cuts the timeline to
  ±N seconds around the first event matching PATTERN (substring on
  kind/tag/detail — e.g. ``--around EXIT_HUNG``, ``--around rollback``,
  ``--around quarantine``).
- **Export**: ``--json`` for machines; ``--perfetto out.json`` writes
  Chrome trace-event JSON (instants for blackbox/quarantine events,
  complete-events for sampled spans, counters for scalar series) that
  opens directly in Perfetto / chrome://tracing.

Usage:
    python tools/timeline.py logs/<refs>
    python tools/timeline.py logs/<refs> --around poison --window 10
    python tools/timeline.py logs/<refs> --perfetto trace.json
    python tools/timeline.py logs/<refs> --json | jq '.[0]'
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from pytorch_distributed_tpu.utils.metrics import read_scalars  # noqa: E402

# scalar tags included by default (the data/health planes a post-mortem
# reads); everything else needs --all-scalars.  Spans, histogram rows
# and bucket rows are always included — they are sparse by design.
_DEFAULT_SCALAR_PREFIXES = (
    "health/", "replay/priority", "learner/staleness",
    "learner/sample_age", "replay/actor_share", "perf/",
    # ISSUE 10: alert-state step rows (0 ok, 1 pending, 2 firing) from
    # the mission-control engine — the scalar-stream leg of an alert
    # transition; the blackbox leg is the "alert" event kind below
    "alert/",
    # ISSUE 11: overload-state / brownout-tier rows from the flow
    # governor — the scalar leg the ``overload_shed`` rule watches;
    # the blackbox leg is the "overload" event kind below
    "flow/",
    # ISSUE 18: bandwidth X-ray counters — per-link bytes/s, bytes/
    # transition, replay occupancy and checkpoint-epoch sizes as
    # Perfetto counter tracks on the same clock as spans/alerts
    "wire/",
    "ckpt/",
    "replay/hbm_bytes",
    "replay/host_bytes",
)

# blackbox event kinds that mark the *incident* skeleton — rendered
# prominently and matched first by --around
_LOUD_KINDS = {
    "fault", "rollback", "anomaly", "dump", "dcn-terminal", "reconnect",
    "divergence-fatal", "quarantine", "hang-kill", "preemption",
    "session-start", "prefetch-failed", "alert",
    # ISSUE 11: overload-governor state/tier transitions and the
    # gateway's tier-3 experience sheds — the incident skeleton of an
    # overload event, clock-aligned with the alerts it should trigger
    "overload", "flow-shed", "brownout",
}


def _detail(fields: Dict[str, Any], limit: int = 160) -> str:
    parts = []
    for k, v in fields.items():
        if k in ("t", "kind", "wall", "role", "run_id"):
            continue
        parts.append(f"{k}={v}")
    out = " ".join(parts)
    return out if len(out) <= limit else out[: limit - 1] + "…"


def _read_jsonl(path: str) -> List[dict]:
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue  # torn line (SIGKILL mid-write)
    except OSError:
        return []
    return out


def collect_blackbox(log_dir: str) -> List[dict]:
    """Blackbox rings -> events; the dump header itself becomes a
    ``blackbox_dump`` event (it records WHY the ring was written)."""
    events: List[dict] = []
    for path in sorted(glob.glob(os.path.join(log_dir, "blackbox",
                                              "*.jsonl"))):
        rows = _read_jsonl(path)
        if not rows:
            continue
        role = os.path.splitext(os.path.basename(path))[0]
        run_id = None
        for r in rows:
            if r.get("kind") == "dump":
                role = r.get("role", role)
                run_id = r.get("run_id")
                events.append({
                    "wall": float(r.get("t", 0.0)), "role": role,
                    "kind": "blackbox_dump", "source": "blackbox",
                    "run_id": run_id,
                    "detail": _detail({"reason": r.get("reason", ""),
                                       "events": r.get("events")}),
                    "data": r,
                })
                continue
            events.append({
                "wall": float(r.get("t", 0.0)), "role": role,
                "kind": str(r.get("kind", "event")),
                "source": "blackbox", "run_id": run_id,
                "detail": _detail(r), "data": r,
            })
    return events


def collect_scalars(log_dir: str, all_scalars: bool = False) -> List[dict]:
    events: List[dict] = []
    for r in read_scalars(log_dir):
        tag = r.get("tag")
        if not tag or "wall" not in r:
            continue
        kind = r.get("kind")
        role = r.get("role", "metrics")
        run_id = r.get("run_id")
        if kind == "span":
            events.append({
                "wall": float(r["wall"]), "role": role, "kind": "span",
                "source": "span", "run_id": run_id, "tag": tag,
                "detail": f"{r.get('span', tag)} "
                          f"{r.get('value', 0):.3f}ms "
                          f"trace={r.get('trace_id', '')}",
                "data": r,
            })
        elif kind == "histogram":
            events.append({
                "wall": float(r["wall"]), "role": role,
                "kind": "histogram", "source": "scalars",
                "run_id": run_id, "tag": tag,
                "detail": f"{tag} p50={r.get('p50')} p95={r.get('p95')} "
                          f"max={r.get('max')} n={r.get('count')}",
                "data": r,
            })
        elif kind == "buckets":
            events.append({
                "wall": float(r["wall"]), "role": role,
                "kind": "priority_xray", "source": "scalars",
                "run_id": run_id, "tag": tag,
                "detail": f"{tag} rows={r.get('rows')} "
                          f"ess={r.get('ess')} "
                          f"ess_frac={r.get('ess_frac')}",
                "data": r,
            })
        elif "value" in r:
            if not all_scalars and not tag.startswith(
                    _DEFAULT_SCALAR_PREFIXES):
                continue
            events.append({
                "wall": float(r["wall"]), "role": role, "kind": "scalar",
                "source": "scalars", "run_id": run_id, "tag": tag,
                "detail": f"{tag}={r['value']:g} @step {r.get('step')}",
                "data": r,
            })
    return events


def collect_quarantine(log_dir: str) -> List[dict]:
    events: List[dict] = []
    for path in sorted(glob.glob(os.path.join(log_dir, "quarantine",
                                              "*.npz"))):
        try:
            with np.load(path, allow_pickle=False) as z:
                cols = {k: z[k] for k in z.files}
        except Exception:  # noqa: BLE001 - a torn file must not kill the report
            continue
        reasons = [str(x) for x in cols.get("reason", [])]
        n = len(reasons) or len(cols.get("priority", []))
        wall = (float(cols["wall"][0]) if "wall" in cols
                else os.path.getmtime(path))
        run_id = str(cols["run_id"][0]) if "run_id" in cols else None
        trace = str(cols["trace_id"][0]) if "trace_id" in cols else ""
        actors: List[int] = []
        pv = cols.get("prov")
        if pv is not None and np.ndim(pv) == 2:
            actors = sorted({int(a) for a in pv[:, 0] if a >= 0})
        source = os.path.basename(path).rsplit("-", 1)[0]
        events.append({
            "wall": wall, "role": source, "kind": "quarantine",
            "source": "quarantine", "run_id": run_id,
            "detail": f"{n} transition(s) ({reasons[0] if reasons else '?'})"
                      + (f" from actor(s) {actors}" if actors else "")
                      + (f" trace={trace}" if trace else "")
                      + f" file={os.path.basename(path)}",
            "data": {"path": path, "reasons": reasons[:8],
                     "actors": actors, "trace_id": trace},
        })
    return events


def clock_offsets(events: List[dict]) -> Dict[str, float]:
    """Per-role wall-clock corrections from the LATEST ``clock_sync``
    blackbox event each DCN client recorded.  The offset of client slot
    ``s`` applies to its own ring role (``dcn-client-s``) and to the
    co-process roles that share its host clock (``actor-s``)."""
    out: Dict[str, float] = {}
    best: Dict[int, tuple] = {}
    for e in events:
        if e.get("kind") != "clock_sync":
            continue
        slot = e.get("data", {}).get("slot")
        off = e.get("data", {}).get("offset")
        if slot is None or off is None:
            continue
        prev = best.get(int(slot))
        if prev is None or e["wall"] > prev[0]:
            best[int(slot)] = (e["wall"], float(off))
    for slot, (_w, off) in best.items():
        out[f"dcn-client-{slot}"] = off
        out[f"actor-{slot}"] = off
    return out


def build_timeline(log_dir: str, all_scalars: bool = False) -> List[dict]:
    events = (collect_blackbox(log_dir)
              + collect_scalars(log_dir, all_scalars)
              + collect_quarantine(log_dir))
    offsets = clock_offsets(events)
    for e in events:
        off = offsets.get(e.get("role", ""), 0.0)
        e["raw_wall"] = e["wall"]
        e["clock_offset"] = off
        e["wall"] = e["wall"] + off
    events.sort(key=lambda e: (e["wall"], e.get("role", "")))
    return events


def filter_around(events: List[dict], pattern: str,
                  window: float) -> List[dict]:
    """Events within ±window seconds of the first match of ``pattern``
    (case-insensitive substring over kind, tag and detail; loud incident
    kinds are searched first so ``--around fault`` anchors on the fault,
    not on a scalar row that mentions it)."""
    pat = pattern.lower()

    def matches(e: dict) -> bool:
        return (pat in e.get("kind", "").lower()
                or pat in str(e.get("tag", "")).lower()
                or pat in e.get("detail", "").lower())

    anchor = next((e for e in events
                   if e.get("kind", "").lower() in _LOUD_KINDS
                   and matches(e)), None)
    if anchor is None:
        anchor = next((e for e in events if matches(e)), None)
    if anchor is None:
        return []
    t0 = anchor["wall"]
    out = [e for e in events if abs(e["wall"] - t0) <= window]
    for e in out:
        e["anchor"] = e is anchor
    return out


def render_text(events: List[dict], limit: int = 200) -> str:
    if not events:
        return "(no events)"
    t0 = events[0]["wall"]
    lines = [f"timeline: {len(events)} event(s) from "
             f"{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(t0))} "
             f"(t0)"]
    shown = events if len(events) <= limit else events[:limit]
    for e in shown:
        mark = ">>" if e.get("anchor") else ("!!" if e.get("kind")
                                            in _LOUD_KINDS else "  ")
        off = f" (clk{e['clock_offset']:+.3f}s)" \
            if e.get("clock_offset") else ""
        lines.append(
            f"{mark} +{e['wall'] - t0:10.3f}s  [{e.get('role', '?'):>14}]"
            f" {e.get('kind', '?'):<14} {e.get('detail', '')}{off}")
    if len(events) > limit:
        lines.append(f"... {len(events) - limit} more "
                     f"(raise --limit, or narrow with --around)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Perfetto (Chrome trace-event) export
# ---------------------------------------------------------------------------

def to_perfetto(events: List[dict]) -> dict:
    """Chrome trace-event JSON: one ``pid`` per role (named via metadata
    events), instants for discrete events, complete-events ("X") for
    sampled spans (duration known), counters for scalar series.
    Timestamps are absolute epoch microseconds — Perfetto normalizes."""
    roles = sorted({e.get("role", "?") for e in events})
    pid_of = {r: i + 1 for i, r in enumerate(roles)}
    trace: List[dict] = []
    for role, pid in pid_of.items():
        trace.append({"name": "process_name", "ph": "M", "pid": pid,
                      "tid": 0, "args": {"name": role}})
    for e in events:
        pid = pid_of[e.get("role", "?")]
        ts = e["wall"] * 1e6
        if e["kind"] == "span":
            dur_us = float(e["data"].get("value", 0.0)) * 1e3
            trace.append({
                "name": e["data"].get("span", e.get("tag", "span")),
                "ph": "X", "ts": max(ts - dur_us, 0.0), "dur": dur_us,
                "pid": pid, "tid": 1,
                "args": {"trace_id": e["data"].get("trace_id", ""),
                         "step": e["data"].get("step")},
            })
        elif e["kind"] == "scalar":
            trace.append({
                "name": e.get("tag", "scalar"), "ph": "C", "ts": ts,
                "pid": pid, "tid": 0,
                "args": {"value": float(e["data"].get("value", 0.0))},
            })
        elif e["kind"] == "histogram":
            trace.append({
                "name": e.get("tag", "histogram"), "ph": "C", "ts": ts,
                "pid": pid, "tid": 0,
                "args": {"p95": float(e["data"].get("p95") or 0.0)},
            })
        else:
            trace.append({
                "name": e.get("kind", "event"), "ph": "i", "ts": ts,
                "pid": pid, "tid": 0, "s": "p",
                "args": {"detail": e.get("detail", ""),
                         "source": e.get("source", "")},
            })
    return {"traceEvents": trace, "displayTimeUnit": "ms",
            "otherData": {"generator": "tools/timeline.py"}}


def _jsonable(e: dict) -> dict:
    out = {}
    for k, v in e.items():
        if isinstance(v, (np.integer,)):
            out[k] = int(v)
        elif isinstance(v, (np.floating,)):
            out[k] = float(v)
        elif isinstance(v, np.ndarray):
            out[k] = v.tolist()
        elif isinstance(v, dict):
            out[k] = _jsonable(v)
        else:
            out[k] = v
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/timeline.py",
        description="merge blackbox/spans/quarantine/scalars into one "
                    "clock-aligned incident timeline")
    ap.add_argument("log_dir", help="run directory (logs/<refs>)")
    ap.add_argument("--around", type=str, default=None, metavar="PATTERN",
                    help="cut to ±window seconds around the first event "
                         "matching PATTERN (substring over "
                         "kind/tag/detail, e.g. EXIT_HUNG, rollback, "
                         "quarantine)")
    ap.add_argument("--window", type=float, default=30.0, metavar="SECS",
                    help="half-width of the --around cut (default 30)")
    ap.add_argument("--json", action="store_true",
                    help="print the event list as JSON")
    ap.add_argument("--perfetto", type=str, default=None, metavar="OUT",
                    help="write Chrome trace-event JSON (open in "
                         "ui.perfetto.dev or chrome://tracing)")
    ap.add_argument("--limit", type=int, default=200,
                    help="max events in the text rendering")
    ap.add_argument("--all-scalars", action="store_true",
                    help="include EVERY scalar row (default: only the "
                         "health/data planes)")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.log_dir):
        print(f"timeline: no such run dir {args.log_dir!r}",
              file=sys.stderr)
        return 2
    events = build_timeline(args.log_dir, all_scalars=args.all_scalars)
    if args.around:
        events = filter_around(events, args.around, args.window)
        if not events:
            print(f"timeline: no event matches {args.around!r}",
                  file=sys.stderr)
            return 1
    if args.perfetto:
        doc = to_perfetto(events)
        with open(args.perfetto, "w") as f:
            json.dump(doc, f)
        print(f"timeline: wrote {len(doc['traceEvents'])} trace events "
              f"-> {args.perfetto}", file=sys.stderr)
    if args.json:
        print(json.dumps([_jsonable(e) for e in events]))
    elif not args.perfetto:
        print(render_text(events, limit=args.limit))
    return 0


if __name__ == "__main__":
    sys.exit(main())
