#!/usr/bin/env python
"""Offline validator for checkpoint-epoch directories.

A checkpoint that can't be trusted is worse than none: after a host
crash, a TPU preemption, or a partially synced copy, this command tells
you — without starting a run — whether a ``{model_name}_ckpt`` directory
still holds a recovery point the resume path will accept
(utils/checkpoint.py resolve_epoch applies exactly the same rules).

    python tools/ckpt_fsck.py models/run_ckpt
    python tools/ckpt_fsck.py models/run            # _ckpt suffix implied
    python tools/ckpt_fsck.py --require-complete models/*_ckpt

Per epoch it reports one of:

- ``complete``   — manifest committed and every artifact's sha256 digest
  verifies; counters consistent between manifest and extras.  Resumable.
- ``incomplete`` — no MANIFEST.json: a save was killed before its atomic
  commit.  Expected crash debris, NOT a violation (the next run's save
  clears it); an older complete epoch still carries the run.
- ``rolled-back`` — committed but fenced off by a health-sentinel
  rollback (``ROLLED_BACK.json``): its params are suspected diverged, so
  resume skips it.  Clean, NOT a violation — a run that rolled back
  mid-training fscks with exit 0, and the learner_step regression its
  successor epochs carry is legal exactly because the overtaken epochs
  are marked (an UNMARKED step regression between complete epochs is
  still flagged).
- ``corrupt``    — a committed manifest is lying (missing artifact, digest
  mismatch, inconsistent learner_step).  Every lie is listed and counted
  as a violation.

Exit codes: 0 = no violations (every committed epoch is whole);
1 = violations found; 2 = a named path is not a checkpoint directory.
``--require-complete`` additionally fails (1) when a directory has no
complete epoch at all — what a kill-resume drill asserts after the first
commit has happened.

The final line is a JSON report for scripting (one object per root).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_tpu.utils import checkpoint as ckpt


def fsck_path(path: str) -> dict:
    """Accept either the ``*_ckpt`` root itself or the model_name prefix
    it was derived from."""
    root = path
    if not os.path.isdir(root) and os.path.isdir(path + "_ckpt"):
        root = path + "_ckpt"
    return ckpt.fsck(root)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="+",
                    help="checkpoint roots (*_ckpt dirs) or model_name "
                         "prefixes")
    ap.add_argument("--require-complete", action="store_true",
                    help="also fail when a root holds no complete epoch")
    args = ap.parse_args(argv)

    reports = []
    rc = 0
    for path in args.paths:
        rep = fsck_path(path)
        reports.append(rep)
        if not os.path.isdir(rep["root"]):
            print(f"[ckpt_fsck] {path}: not a checkpoint directory")
            rc = max(rc, 2)
            continue
        for e in sorted(rep["epochs"], key=lambda e: e["epoch"]):
            line = f"[ckpt_fsck] {rep['root']} epoch {e['epoch']}: " \
                   f"{e['status']}"
            if e["status"] in ("complete", "rolled-back") \
                    and e.get("learner_step") is not None:
                line += f" (learner_step {e.get('learner_step')}"
                if e.get("bytes") is not None:
                    line += f", {e['bytes']} bytes"
                line += ")"
            print(line)
            # per-artifact byte sizes (bandwidth X-ray, ISSUE 18):
            # the MANIFEST-recorded sizes verify_epoch checked against
            # the on-disk artifacts — a disagreement is a VIOLATION
            # line below, not a silent skew
            for name, nb in sorted((e.get("artifacts") or {}).items()):
                print(f"[ckpt_fsck]   {name}: {nb} bytes")
            for v in e["violations"]:
                print(f"[ckpt_fsck]   VIOLATION: {v}")
        if rep.get("rolled_back"):
            print(f"[ckpt_fsck] {rep['root']}: {rep['rolled_back']} "
                  f"epoch(s) fenced by health-sentinel rollback "
                  f"(kept as post-mortem evidence; never resumed from)")
        if rep["violations"]:
            rc = max(rc, 1)
        if args.require_complete and rep["newest_complete"] is None:
            print(f"[ckpt_fsck] {rep['root']}: no complete epoch")
            rc = max(rc, 1)
        if not rep["epochs"]:
            print(f"[ckpt_fsck] {rep['root']}: empty checkpoint root")
    print(json.dumps(reports))
    return rc


if __name__ == "__main__":
    sys.exit(main())
