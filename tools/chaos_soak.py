"""Randomized fault-injection soak for the DCN session layer.

Runs a learner-plane simulation (gateway + clock/param/stat fixtures, no
jax) with N synthetic remote actors hammering every client surface, while
a seeded orchestrator restarts the gateway and the per-client
FaultInjectors (utils/faults.py random mode) sever/delay/corrupt the
wire.  Exits nonzero on any invariant violation:

- **lost slot** — an actor ends "disconnected" (or never ends) even
  though the gateway was only ever down for less than the reconnect
  budget;
- **duplicate slot** — a slot observed outside the expected range, or a
  slot whose incarnation moved backwards (two live claimants);
- **learner-step regression** — a client observes the learner clock run
  backwards (the tell for answering a stale/ghost gateway);
- **lost experience** — a chunk the wire acknowledged that never reached
  ``put_chunk`` (duplicates are legal — delivery is at-least-once — loss
  is not);
- **poison delivered** — a non-finite reward reached ``put_chunk``: the
  soak mixes deliberately poisoned chunks (NaN reward/priority, the
  health sentinel's ``poison_chunk`` fault) into every actor's schedule
  and the gateway's ingest quarantine must divert ALL of them;
- **stall mishandled** — one seeded actor freezes mid-run for several
  heartbeat intervals (the hang-adjacent stall): its session must ride
  through on heartbeats, never end disconnected;
- **alert contract broken** (ISSUE 10, with ``--learner-stall``): the
  soak attaches a mission-control plane (utils/telemetry.py) fed by a
  simulated learner's stats cadence and freezes that learner for a
  window mid-run.  The ``learner/updates_per_s`` absence rule must
  walk pending→firing during the stall and resolve after recovery;
  an EXPECTED alert that never fires, an alert still unresolved at the
  end, or any UNEXPECTED rule firing is each a violation — the alert
  engine is drilled exactly like the session layer.  With ``--log-dir``
  the run leaves the production artifact set (blackbox rings with the
  alert transitions, ``alert/*`` scalar rows) so ``tools/timeline.py``
  reconstructs the incident.

Usage:
    python tools/chaos_soak.py --seconds 30 --actors 4 --seed 0
    python tools/chaos_soak.py --seconds 60 --restart-every 5
    python tools/chaos_soak.py --seconds 10 --learner-stall 2.5 \
        --learner-stall-at 3 --log-dir logs/soak

The same ``SyntheticActor`` drives the deterministic chaos scenarios in
tests/test_chaos.py; this entry point is the long-haul randomized
version (satellite of the fault-tolerant session layer, parallel/dcn.py
failure model).
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.parallel.dcn import (
    DcnClient, DcnGateway, RemoteClock, RemoteParamStore, RemoteStats,
)
from pytorch_distributed_tpu.utils.experience import Transition
from pytorch_distributed_tpu.utils.faults import FaultInjector


def tagged_transition(tag: int) -> Transition:
    """A minimal transition whose reward carries a chunk-traceable id."""
    z = np.zeros(2, dtype=np.float32)
    return Transition(state0=z, action=np.int32(0),
                      reward=np.float32(tag), gamma_n=np.float32(0.99),
                      state1=z, terminal1=np.float32(0.0))


class ChunkLog:
    """Gateway-side ``put_chunk`` sink: records the id tag of every
    delivered transition (thread-safe — serve threads race into it).
    Non-finite rewards — poisoned chunks the quarantine should have
    diverted — are counted as ``poisoned_delivered``, the soak's
    replay-cleanliness invariant."""

    def __init__(self):
        self._lock = threading.Lock()
        self.tags: List[int] = []
        self.poisoned_delivered = 0

    def __call__(self, items: list) -> None:
        with self._lock:
            for t, _p in items:
                if not np.isfinite(t.reward):
                    self.poisoned_delivered += 1
                else:
                    self.tags.append(int(t.reward))

    def seen(self) -> Dict[int, int]:
        with self._lock:
            out: Dict[int, int] = {}
            for tag in self.tags:
                out[tag] = out.get(tag, 0) + 1
            return out


class SyntheticActor:
    """Drives every client surface of the session layer — experience
    chunks, clock ticks, stat pushes, param fetches — without envs, jax,
    or a real learner, so chaos drills run in milliseconds.  Records
    which chunk tags the wire ACKNOWLEDGED (the at-least-once delivery
    set the gateway must cover) and how the loop ended."""

    def __init__(self, address, slot: int, steps: int = 10 ** 9,
                 client_kwargs: Optional[dict] = None, pace: float = 0.0,
                 poison_every: int = 0, stall_at: int = -1,
                 stall_s: float = 0.0):
        self.address = address
        self.slot = slot
        self.steps = steps
        self.pace = pace
        self.poison_every = poison_every  # every Nth chunk ships NaN
        self.stall_at = stall_at          # chunk index of a long freeze
        self.stall_s = stall_s
        self.client_kwargs = client_kwargs or {}
        self.client: Optional[DcnClient] = None
        self.acked_tags: List[int] = []
        self.poisoned_sent = 0
        self.step_regressions = 0
        self.outcome: Optional[str] = None  # "stopped"|"disconnected"|err
        self.thread: Optional[threading.Thread] = None

    def start(self) -> "SyntheticActor":
        self.thread = threading.Thread(
            target=self.run, name=f"chaos-actor-{self.slot}", daemon=True)
        self.thread.start()
        return self

    def run(self) -> None:
        try:
            self.client = client = DcnClient(
                self.address, process_ind=self.slot, **self.client_kwargs)
        except Exception as e:  # refused HELLO / dead gateway
            self.outcome = f"connect-failed: {e!r}"
            return
        rclock = RemoteClock(client, flush_every=16, max_age=0.5)
        rstats = RemoteStats(client)
        rparams = RemoteParamStore(client)
        i = 0
        last_step = -1
        try:
            while not rclock.done(self.steps):
                if i == self.stall_at and self.stall_s > 0:
                    # alive-but-quiet freeze: heartbeats must keep the
                    # session claimed through it (hang-adjacent stall)
                    time.sleep(self.stall_s)
                tag = (self.slot << 20) | i
                if self.poison_every and i and i % self.poison_every == 0:
                    # the poison_chunk fault, wire edition: NaN reward +
                    # NaN priority — must be quarantined at the gateway,
                    # never delivered (tag is NOT expected in the log)
                    t = tagged_transition(tag)
                    t = t._replace(reward=np.float32(np.nan))
                    client.send_chunk([(t, float("nan"))])
                    self.poisoned_sent += 1
                else:
                    client.send_chunk(
                        [(tagged_transition(tag), None)])  # acked iff returns
                    self.acked_tags.append(tag)
                rclock.add_actor_steps(1)
                if i % 8 == 0:
                    rparams.fetch(0)
                if i % 16 == 0:
                    rstats.add(nepisodes=1.0, total_reward=1.0)
                step = client.learner_step
                if step < last_step:
                    self.step_regressions += 1
                last_step = step
                i += 1
                if self.pace:
                    time.sleep(self.pace)
        except (ConnectionError, OSError):
            pass  # terminal loss: outcome read from the latched events
        except Exception as e:
            self.outcome = f"crashed: {e!r}"
            client.close()
            return
        try:
            rclock.flush()
        except (ConnectionError, OSError):
            pass
        client.close()
        self.outcome = ("disconnected"
                        if client.disconnected.is_set()
                        and not client.stop.is_set() else "stopped")


# the drill rule set a --learner-stall soak runs: the absence rule the
# stall MUST fire, plus a threshold rule that must stay quiet — the
# unexpected-alert invariant needs a rule that could fire but shouldn't
SOAK_ALERT_RULES = ("learner_stall: learner/updates_per_s absent 1.5s; "
                    "learner_slow: learner/updates_per_s < 1 for 2s")


def soak(seconds: float = 20.0, actors: int = 3, seed: int = 0,
         restart_every: Optional[float] = 5.0,
         fault_rates: Optional[Dict[str, float]] = None,
         reconnect_timeout: float = 10.0,
         poison_every: int = 40,
         learner_stall: float = 0.0, learner_stall_at: float = 3.0,
         log_dir: Optional[str] = None, port: int = 0,
         alert_rules: Optional[str] = None,
         verbose: bool = True) -> dict:
    """Run the randomized soak; returns a report dict whose
    ``violations`` list is empty on a healthy session layer (and, with
    ``learner_stall`` > 0, a healthy alert plane — see module
    docstring)."""
    rng = np.random.default_rng(seed)
    clock = GlobalClock()
    stats = ActorStats()
    store = ParamStore(8)
    store.publish(np.zeros(8, dtype=np.float32))
    log = ChunkLog()

    # ---- mission-control plane (ISSUE 10): attached whenever the
    # learner-stall drill or an explicit rule set asks for it
    mission = None
    learner_writer = None
    if learner_stall > 0 or alert_rules is not None or log_dir:
        from pytorch_distributed_tpu.config import (
            AlertParams, MetricsParams,
        )
        from pytorch_distributed_tpu.utils import (
            flight_recorder, telemetry,
        )
        from pytorch_distributed_tpu.utils.metrics import MetricsWriter

        if log_dir:
            flight_recorder.configure(log_dir, run_id="chaos-soak")
        mission = telemetry.MissionControl(
            log_dir, MetricsParams(enabled=True, poll_s=0.2),
            AlertParams(rules=alert_rules or SOAK_ALERT_RULES))
        mission.start()
        if log_dir:
            # the full production ingest path: the simulated learner
            # WRITES rows, the mission TAILS them (no direct feeding)
            learner_writer = MetricsWriter(
                log_dir, enable_tensorboard=False, role="learner",
                run_id="chaos-soak")

    def _health() -> dict:
        return mission.status_block() if mission is not None else {}

    gw = DcnGateway(store, clock, stats, put_chunk=log,
                    host="127.0.0.1", port=port, idle_deadline=30.0,
                    health=_health,
                    metrics_sink=(mission.ingest_remote
                                  if mission is not None else None))
    port = gw.port
    violations: List[str] = []
    fenced = 0
    quarantined = 0
    gateway_restarts = 0

    # one seeded actor gets a mid-run freeze of several heartbeat
    # intervals — the hang-adjacent stall the session layer must ride
    # through (the full hang->SIGKILL->respawn ladder needs a process
    # supervisor and is drilled by tests/test_health.py)
    stall_slot = int(rng.integers(actors)) if actors else -1
    fleet = [
        SyntheticActor(
            ("127.0.0.1", port), slot=i, pace=0.002,
            poison_every=poison_every,
            stall_at=(50 + int(rng.integers(100))
                      if i == stall_slot else -1),
            stall_s=2.5,
            client_kwargs=dict(
                reconnect_timeout=reconnect_timeout,
                heartbeat_interval=0.5,
                faults=FaultInjector.random(
                    seed * 1000 + i,
                    rates=fault_rates, name=f"actor-{i}"),
            )).start()
        for i in range(actors)
    ]

    t_start = time.monotonic()
    deadline = t_start + seconds
    next_restart = (time.monotonic() + restart_every
                    if restart_every else float("inf"))
    incarnation_high: Dict[int, int] = {}
    learner_step = 0
    stall_seen = False
    while time.monotonic() < deadline:
        time.sleep(0.1)
        elapsed = time.monotonic() - t_start
        stalled = (learner_stall > 0
                   and learner_stall_at <= elapsed
                   < learner_stall_at + learner_stall)
        if stalled:
            # the injected learner stall (ISSUE 10 drill): the step
            # clock freezes AND the stats cadence stops emitting — a
            # stuck learner writes nothing, which is exactly what the
            # absence rule watches for
            stall_seen = True
        else:
            learner_step += 5  # the simulated learner's clock
            clock.set_learner_step(learner_step)
            if mission is not None:
                row = {"tag": "learner/updates_per_s", "value": 50.0,
                       "wall": time.time(), "step": learner_step,
                       "role": "learner"}
                if learner_writer is not None:
                    learner_writer.scalar(row["tag"], row["value"],
                                          step=learner_step,
                                          wall=row["wall"])
                    learner_writer.flush()
                else:
                    mission.metrics.ingest([row])
        if learner_step and learner_step % 50 == 0 and not stalled:
            store.publish(np.full(8, learner_step, dtype=np.float32))
        # invariant: slots in range, incarnations never move backwards
        for slot, inc in gw.active_slots.items():
            if not (0 <= slot < actors):
                violations.append(f"unexpected slot {slot} active")
            if inc < incarnation_high.get(slot, 0):
                violations.append(
                    f"slot {slot} incarnation regressed "
                    f"{incarnation_high[slot]} -> {inc}")
            incarnation_high[slot] = max(
                inc, incarnation_high.get(slot, 0))
        if time.monotonic() >= next_restart:
            fenced += gw.fenced
            quarantined += sum(gw.quarantined.values())
            gw.close()
            gateway_restarts += 1
            gw = DcnGateway(store, clock, stats, put_chunk=log,
                            host="127.0.0.1", port=port,
                            idle_deadline=30.0, health=_health,
                            metrics_sink=(mission.ingest_remote
                                          if mission is not None
                                          else None))
            next_restart = (time.monotonic() + restart_every
                            * (0.5 + float(rng.random())))

    clock.stop.set()  # next reply any client sees carries stop:true
    for a in fleet:
        a.thread.join(reconnect_timeout + 15.0)
        if a.thread.is_alive():
            violations.append(f"actor {a.slot} failed to stop (lost slot)")
        elif a.outcome != "stopped":
            violations.append(f"actor {a.slot} ended {a.outcome!r} "
                              f"(lost slot)")
        if a.step_regressions:
            violations.append(f"actor {a.slot} saw the learner clock "
                              f"regress {a.step_regressions}x")
    fenced += gw.fenced
    quarantined += sum(gw.quarantined.values())
    gw.close()

    # ---- alert-plane verdict (ISSUE 10): expected alerts must have
    # fired AND resolved; anything else firing is a violation
    alert_report: dict = {}
    if mission is not None:
        mission.stop()
        snap = mission.engine.snapshot()
        fired = sorted(a["rule"] for a in snap if a["fired_total"] > 0)
        unresolved = sorted(a["rule"] for a in snap
                            if a["state"] in ("pending", "firing"))
        expected = ["learner_stall"] if stall_seen else []
        unexpected = [r for r in fired if r not in expected]
        if unexpected:
            violations.append(
                f"unexpected alert(s) fired: {unexpected}")
        for r in expected:
            if r not in fired:
                violations.append(
                    f"expected alert {r!r} never fired during the "
                    f"learner-stall drill")
        if unresolved:
            violations.append(
                f"alert(s) {unresolved} still unresolved after "
                f"recovery")
        alert_report = {
            "rules": len(snap),
            "fired": fired,
            "unexpected": unexpected,
            "unresolved": unresolved,
            "resolved_total": sum(a["resolved_total"] for a in snap),
            "stall_injected": bool(stall_seen),
        }
        if learner_writer is not None:
            learner_writer.close()
        if log_dir:
            # leave the production post-mortem set: the mission's ring
            # (alert transitions) + every other ring this process holds
            from pytorch_distributed_tpu.utils import flight_recorder

            flight_recorder.dump_all("chaos soak complete")

    seen = log.seen()
    acked = [t for a in fleet for t in a.acked_tags]
    lost = [t for t in acked if t not in seen]
    if lost:
        violations.append(f"{len(lost)} acked chunks never delivered "
                          f"(first: {lost[:5]})")
    poisoned_sent = sum(a.poisoned_sent for a in fleet)
    if log.poisoned_delivered:
        violations.append(
            f"{log.poisoned_delivered} poisoned transitions reached "
            f"put_chunk (quarantine breached)")
    if poisoned_sent and not quarantined:
        violations.append(
            f"{poisoned_sent} poisoned chunks sent but the gateway "
            f"quarantined none")
    report = {
        "violations": violations,
        "actors": actors,
        "acked_chunks": len(acked),
        "delivered_chunks": len(log.tags),
        "duplicate_deliveries": len(log.tags) - len(seen),
        "reconnects": sum(a.client.reconnects for a in fleet if a.client),
        "injected_faults": sum(
            a.client_kwargs["faults"].injected for a in fleet),
        "poisoned_sent": poisoned_sent,
        "poisoned_delivered": log.poisoned_delivered,
        "quarantined": quarantined,
        "gateway_restarts": gateway_restarts,
        "fenced": fenced,
        "final_learner_step": learner_step,
        "alerts": alert_report,
        "port": port,
    }
    if verbose:
        for k, v in report.items():
            if k != "violations":
                print(f"[chaos] {k}: {v}")
        for v in violations:
            print(f"[chaos] VIOLATION: {v}")
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/chaos_soak.py",
        description="randomized fault-injection soak for the DCN "
                    "session layer (exits nonzero on invariant "
                    "violations)")
    ap.add_argument("--seconds", type=float, default=20.0)
    ap.add_argument("--actors", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--restart-every", type=float, default=5.0,
                    help="mean seconds between gateway kill+rebinds "
                         "(0 disables)")
    ap.add_argument("--reconnect-timeout", type=float, default=10.0)
    ap.add_argument("--poison-every", type=int, default=40,
                    help="every Nth chunk per actor ships NaN "
                         "reward/priority (0 disables); the gateway "
                         "quarantine must divert every one")
    ap.add_argument("--learner-stall", type=float, default=0.0,
                    metavar="SECS",
                    help="freeze the simulated learner (clock + stats "
                         "cadence) for SECS mid-run: the mission-"
                         "control absence alert must fire during the "
                         "stall and resolve after recovery (0 "
                         "disables the alert drill)")
    ap.add_argument("--learner-stall-at", type=float, default=3.0,
                    metavar="SECS",
                    help="seconds into the run the learner stall "
                         "starts")
    ap.add_argument("--log-dir", type=str, default=None,
                    help="leave the production artifact set (blackbox "
                         "rings with alert transitions, alert/* "
                         "scalar rows) here for tools/timeline.py")
    ap.add_argument("--port", type=int, default=0,
                    help="gateway port (0 = ephemeral); pin it so a "
                         "concurrent fleet_top can watch the soak")
    args = ap.parse_args(argv)
    report = soak(seconds=args.seconds, actors=args.actors, seed=args.seed,
                  restart_every=args.restart_every or None,
                  reconnect_timeout=args.reconnect_timeout,
                  poison_every=args.poison_every,
                  learner_stall=args.learner_stall,
                  learner_stall_at=args.learner_stall_at,
                  log_dir=args.log_dir, port=args.port)
    ok = not report["violations"]
    print(f"[chaos] {'OK' if ok else 'FAILED'} after {args.seconds:.0f}s: "
          f"{len(report['violations'])} violations")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
