"""Randomized fault-injection soak for the DCN session layer.

Runs a learner-plane simulation (gateway + clock/param/stat fixtures, no
jax) with N synthetic remote actors hammering every client surface, while
a seeded orchestrator restarts the gateway and the per-client
FaultInjectors (utils/faults.py random mode) sever/delay/corrupt the
wire.  Exits nonzero on any invariant violation:

- **lost slot** — an actor ends "disconnected" (or never ends) even
  though the gateway was only ever down for less than the reconnect
  budget;
- **duplicate slot** — a slot observed outside the expected range, or a
  slot whose incarnation moved backwards (two live claimants);
- **learner-step regression** — a client observes the learner clock run
  backwards (the tell for answering a stale/ghost gateway);
- **lost experience** — a chunk the wire acknowledged that never reached
  ``put_chunk`` (duplicates are legal — delivery is at-least-once — loss
  is not);
- **poison delivered** — a non-finite reward reached ``put_chunk``: the
  soak mixes deliberately poisoned chunks (NaN reward/priority, the
  health sentinel's ``poison_chunk`` fault) into every actor's schedule
  and the gateway's ingest quarantine must divert ALL of them;
- **stall mishandled** — one seeded actor freezes mid-run for several
  heartbeat intervals (the hang-adjacent stall): its session must ride
  through on heartbeats, never end disconnected;
- **alert contract broken** (ISSUE 10, with ``--learner-stall``): the
  soak attaches a mission-control plane (utils/telemetry.py) fed by a
  simulated learner's stats cadence and freezes that learner for a
  window mid-run.  The ``learner/updates_per_s`` absence rule must
  walk pending→firing during the stall and resolve after recovery;
  an EXPECTED alert that never fires, an alert still unresolved at the
  end, or any UNEXPECTED rule firing is each a violation — the alert
  engine is drilled exactly like the session layer.  With ``--log-dir``
  the run leaves the production artifact set (blackbox rings with the
  alert transitions, ``alert/*`` scalar rows) so ``tools/timeline.py``
  reconstructs the incident.

Overload drills (ISSUE 11, the flow-control plane — utils/flow.py):
``--flood`` (every actor pushes flat-out at a slow simulated learner
ingest), ``--slow-learner-ingest SECS`` (the drain freezes mid-run),
and ``--slow-slot`` (one runaway actor floods while its neighbours
pace normally — the fairness drill).  Each runs the PRODUCTION credit
path: the gateway's overload governor reads live backlog pressure,
grants per-slot credits on acks, clients park experience in their
bounded drop-oldest rings, and the ``overload`` alert must fire during
the event and resolve after it.  Violations on top of the session-layer
set:

- **deadlock** — any actor thread still alive at the join deadline
  (the exact fleet-freeze the credit plane exists to prevent);
- **unbounded memory** — the ingest backlog or any client ring
  exceeding its declared bound;
- **uncounted drops / conservation breached** — the ledger
  ``minted = delivered + dropped(client) + shed(gateway) + quarantined
  + buffered`` must balance EXACTLY (every drop happens at a declared,
  counted shed point; the drills run without wire faults so
  at-least-once retransmits cannot blur the count);
- **overload never engaged** — a flood that never moves the governor
  proves nothing;
- **fairness breached** (``--slow-slot``) — a well-paced actor starved
  (acked below 70% of minted) by its runaway neighbour.

Replica drills (ISSUE 15, the elastic multi-learner plane —
parallel/dcn.py ReplicaRegistry): ``--kill-replica AT`` (the highest
replica crashes at round AT through the production REPLICA fault plane
— dies WITHOUT releasing, so its lease must expire and fence),
``--hang-replica AT`` (the round loop freezes while the lease renewer
keeps renewing — only the registry's round-stall rule can fence it),
and ``--rejoin`` (a replacement re-leases at a NEW generation through
the join-barrier epoch).  Verdict failures: deadlock,
divergent-params across live replicas, unfenced-stale-write (a
zombie's stale-generation gradient or priority write-back accepted),
expected-alert-never-fired / any-unexpected-alert /
unresolved-after-rejoin on the ``replica_degraded`` membership rule,
and any lease/round/fence counter off its script-predicted value
(EXACT-ledger verdict).  See ``replica_soak``.

Gateway HA drills (ISSUE 16, the warm-standby failover plane —
parallel/dcn.py GatewayJournal + T_SYNC): ``--kill-gateway AT`` kills
the primary mid-run with an undrained backlog behind it; the standby
must promote within one lease window through the fenced on-disk term
bump, clients must fail over along their endpoint lists, and the
ledger must stay EXACT (never-delivered acked rows counted in
``failover_lost``, nothing uncounted).  ``--resurrect-primary`` brings
the old primary back on its STALE term — every write must be a counted
reject (``gateway_term_fenced``), none applied.  ``--no-standby``
proves the seed contract unchanged: clients end disconnected exactly
as EXIT_DISCONNECTED always demanded.  A standby that never promotes
is an explicit readable "gateway never recovered" violation and a
nonzero exit — never a hang.  See ``gateway_soak``.

Shard drills (ISSUE 20, the sharded prioritized-replay plane —
memory/shard_plane.py): ``--kill-shard AT`` (SIGKILL-equivalent crash
of the highest replay shard mid-ingest; its lease must expire within
~one window, sampling must continue over the survivors, the
conservation ledger ``minted = ingested + shard_lost + route_dropped``
must balance EXACTLY, and the pre-kill batch's write-back must be a
counted fenced reject), ``--rejoin-shard`` (a fresh host re-leases the
shard id at a NEW generation through the join barrier; the
``shard_membership`` alert must resolve and a zombie holding the dead
generation must be a counted reject at the rejoined shard), and
``--shard-rebalance`` (graceful release + fresh-incarnation
re-acquire: the route rebuilds both ways, released rows land counted
in ``shard_lost``).  See ``shard_soak``.

Usage:
    python tools/chaos_soak.py --seconds 30 --actors 4 --seed 0
    python tools/chaos_soak.py --seconds 60 --restart-every 5
    python tools/chaos_soak.py --seconds 10 --learner-stall 2.5 \
        --learner-stall-at 3 --log-dir logs/soak
    python tools/chaos_soak.py --seconds 12 --flood
    python tools/chaos_soak.py --seconds 12 --slow-learner-ingest 3
    python tools/chaos_soak.py --seconds 12 --slow-slot
    python tools/chaos_soak.py --kill-replica 8 --rejoin
    python tools/chaos_soak.py --hang-replica 10 --rejoin
    python tools/chaos_soak.py --seconds 6 --kill-gateway 1.5
    python tools/chaos_soak.py --seconds 6 --kill-gateway 1.5 \
        --resurrect-primary
    python tools/chaos_soak.py --seconds 6 --kill-gateway 1.5 --no-standby

The same ``SyntheticActor`` drives the deterministic chaos scenarios in
tests/test_chaos.py; this entry point is the long-haul randomized
version (satellite of the fault-tolerant session layer, parallel/dcn.py
failure model).
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.parallel.dcn import (
    DcnClient, DcnGateway, RemoteClock, RemoteParamStore, RemoteStats,
)
from pytorch_distributed_tpu.utils.experience import Transition
from pytorch_distributed_tpu.utils.faults import FaultInjector


def tagged_transition(tag: int) -> Transition:
    """A minimal transition whose reward carries a chunk-traceable id."""
    z = np.zeros(2, dtype=np.float32)
    return Transition(state0=z, action=np.int32(0),
                      reward=np.float32(tag), gamma_n=np.float32(0.99),
                      state1=z, terminal1=np.float32(0.0))


class ChunkLog:
    """Gateway-side ``put_chunk`` sink: records the id tag of every
    delivered transition (thread-safe — serve threads race into it).
    Non-finite rewards — poisoned chunks the quarantine should have
    diverted — are counted as ``poisoned_delivered``, the soak's
    replay-cleanliness invariant."""

    def __init__(self):
        self._lock = threading.Lock()
        self.tags: List[int] = []
        self.poisoned_delivered = 0

    def __call__(self, items: list) -> None:
        with self._lock:
            for t, _p in items:
                if not np.isfinite(t.reward):
                    self.poisoned_delivered += 1
                else:
                    self.tags.append(int(t.reward))

    def seen(self) -> Dict[int, int]:
        with self._lock:
            out: Dict[int, int] = {}
            for tag in self.tags:
                out[tag] = out.get(tag, 0) + 1
            return out


class IngestSim:
    """Simulated learner-side ingest: a bounded-pressure backlog plus a
    paced drain thread — the spawn queue + learner drain cadence
    without jax.  The gateway's ``put_chunk`` appends; the drain pops
    oldest-first at ``rate`` chunks/s into the real sink (ChunkLog),
    consulting the ``INGEST_FAULTS`` injector once per drained chunk
    (``delay@N:S`` is the scripted slow-ingest lever).  ``pressure()``
    — backlog depth over ``bound`` — is the overload governor's input;
    ``pause()`` is the ``--slow-learner-ingest`` lever."""

    def __init__(self, sink, bound: int = 64, rate: float = 400.0):
        self._sink = sink
        self.bound = bound
        self.rate = rate
        self._lock = threading.Lock()
        self._backlog: List[list] = []
        self.backlog_high = 0
        self.drained_chunks = 0
        self._pause_until = 0.0
        self._faults = FaultInjector.from_env("ingest")
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._drain_loop,
                                        name="ingest-sim", daemon=True)
        self._thread.start()

    def __call__(self, items: list) -> None:
        with self._lock:
            self._backlog.append(items)
            self.backlog_high = max(self.backlog_high, len(self._backlog))

    def pressure(self) -> float:
        with self._lock:
            return min(1.0, len(self._backlog) / self.bound)

    def pause(self, seconds: float) -> None:
        self._pause_until = max(self._pause_until,
                                time.monotonic() + seconds)

    def _drain_loop(self) -> None:
        period = 1.0 / max(self.rate, 1.0)
        while not self._stop.is_set():
            if time.monotonic() < self._pause_until:
                time.sleep(0.02)
                continue
            with self._lock:
                items = self._backlog.pop(0) if self._backlog else None
            if items is None:
                time.sleep(0.005)
                continue
            self._faults.frame(b"")
            self._sink(items)
            self.drained_chunks += 1
            time.sleep(period)

    def close(self) -> None:
        """Stop pacing and hand the remaining backlog to the sink — at
        shutdown every gateway-admitted row must reach the delivery log
        or the conservation verdict would blame the simulator."""
        self._stop.set()
        self._thread.join(2.0)
        with self._lock:
            backlog, self._backlog = self._backlog, []
        for items in backlog:
            self._sink(items)
            self.drained_chunks += 1

    def spill(self) -> List[int]:
        """Failover (ISSUE 16): the primary died with this backlog
        undrained.  Stop the drain, DISCARD the backlog, and return the
        chunk tags it held — the caller hands the count to
        ``note_failover_lost`` (the counted ledger bucket) and the
        verdict checks every never-delivered acked tag is in this set:
        loss across a failover is legal only where it is counted."""
        self._stop.set()
        self._thread.join(2.0)
        with self._lock:
            backlog, self._backlog = self._backlog, []
        tags: List[int] = []
        for items in backlog:
            for t, _p in items:
                if np.isfinite(t.reward):
                    tags.append(int(t.reward))
        return tags


class SyntheticActor:
    """Drives every client surface of the session layer — experience
    chunks, clock ticks, stat pushes, param fetches — without envs, jax,
    or a real learner, so chaos drills run in milliseconds.  Records
    which chunk tags the wire ACKNOWLEDGED (the at-least-once delivery
    set the gateway must cover) and how the loop ended."""

    def __init__(self, address, slot: int, steps: int = 10 ** 9,
                 client_kwargs: Optional[dict] = None, pace: float = 0.0,
                 poison_every: int = 0, stall_at: int = -1,
                 stall_s: float = 0.0,
                 calm_at: float = -1.0, calm_pace: float = 0.05):
        self.address = address
        self.slot = slot
        self.steps = steps
        self.pace = pace
        # overload drills: flood until ``calm_at`` seconds in, then drop
        # to ``calm_pace`` — the recovery phase the governor (and the
        # ``overload`` alert's resolve leg) must be observed through
        self.calm_at = calm_at
        self.calm_pace = calm_pace
        self.poison_every = poison_every  # every Nth chunk ships NaN
        self.stall_at = stall_at          # chunk index of a long freeze
        self.stall_s = stall_s
        self.client_kwargs = client_kwargs or {}
        self.client: Optional[DcnClient] = None
        self.acked_tags: List[int] = []
        self.poisoned_sent = 0
        self.step_regressions = 0
        self.outcome: Optional[str] = None  # "stopped"|"disconnected"|err
        self.thread: Optional[threading.Thread] = None

    def start(self) -> "SyntheticActor":
        self.thread = threading.Thread(
            target=self.run, name=f"chaos-actor-{self.slot}", daemon=True)
        self.thread.start()
        return self

    def run(self) -> None:
        try:
            self.client = client = DcnClient(
                self.address, process_ind=self.slot, **self.client_kwargs)
        except Exception as e:  # refused HELLO / dead gateway
            self.outcome = f"connect-failed: {e!r}"
            return
        rclock = RemoteClock(client, flush_every=16, max_age=0.5)
        rstats = RemoteStats(client)
        rparams = RemoteParamStore(client)
        i = 0
        last_step = -1
        t0 = time.monotonic()
        try:
            while not rclock.done(self.steps):
                if i == self.stall_at and self.stall_s > 0:
                    # alive-but-quiet freeze: heartbeats must keep the
                    # session claimed through it (hang-adjacent stall)
                    time.sleep(self.stall_s)
                tag = (self.slot << 20) | i
                if self.poison_every and i and i % self.poison_every == 0:
                    # the poison_chunk fault, wire edition: NaN reward +
                    # NaN priority — must be quarantined at the gateway,
                    # never delivered (tag is NOT expected in the log)
                    t = tagged_transition(tag)
                    t = t._replace(reward=np.float32(np.nan))
                    client.send_chunk([(t, float("nan"))])
                    self.poisoned_sent += 1
                else:
                    client.send_chunk(
                        [(tagged_transition(tag), None)])  # acked iff returns
                    self.acked_tags.append(tag)
                rclock.add_actor_steps(1)
                if i % 8 == 0:
                    rparams.fetch(0)
                if i % 16 == 0:
                    rstats.add(nepisodes=1.0, total_reward=1.0)
                step = client.learner_step
                if step < last_step:
                    self.step_regressions += 1
                last_step = step
                i += 1
                pace = self.pace
                if 0 <= self.calm_at <= time.monotonic() - t0:
                    pace = self.calm_pace
                if pace:
                    time.sleep(pace)
        except (ConnectionError, OSError):
            pass  # terminal loss: outcome read from the latched events
        except Exception as e:
            self.outcome = f"crashed: {e!r}"
            client.close()
            return
        try:
            rclock.flush()
        except (ConnectionError, OSError):
            pass
        client.close()
        self.outcome = ("disconnected"
                        if client.disconnected.is_set()
                        and not client.stop.is_set() else "stopped")


# the drill rule set a --learner-stall soak runs: the absence rule the
# stall MUST fire, plus a threshold rule that must stay quiet — the
# unexpected-alert invariant needs a rule that could fire but shouldn't
SOAK_ALERT_RULES = ("learner_stall: learner/updates_per_s absent 1.5s; "
                    "learner_slow: learner/updates_per_s < 1 for 2s")

# the overload drills' rule set (ISSUE 11): the flow rule the drill
# MUST fire (>= 0.5 catches throttled=1 and shedding=2) and resolve,
# plus the quiet-by-construction learner rule for the unexpected-alert
# invariant (the simulated learner keeps emitting 50 up/s throughout)
FLOW_ALERT_RULES = ("overload: flow/overload_state >= 0.5 for 0.3s; "
                    "learner_slow: learner/updates_per_s < 1 for 2s")


class _AggregatorWriter:
    """MetricsWriter-shaped shim feeding the overload governor's
    ``flow/*`` rows straight into the aggregator when the soak runs
    without a log dir (with one, the governor gets a real writer and
    the mission TAILS it — the production path)."""

    def __init__(self, metrics):
        self._metrics = metrics

    def scalar(self, tag, value, step=0, wall=None):
        self._metrics.ingest([{"tag": tag, "value": float(value),
                               "wall": wall or time.time(),
                               "step": int(step), "role": "gateway"}])

    def flush(self):
        pass


def soak(seconds: float = 20.0, actors: int = 3, seed: int = 0,
         restart_every: Optional[float] = 5.0,
         fault_rates: Optional[Dict[str, float]] = None,
         reconnect_timeout: float = 10.0,
         poison_every: int = 40,
         learner_stall: float = 0.0, learner_stall_at: float = 3.0,
         flood: bool = False, slow_ingest: float = 0.0,
         slow_ingest_at: float = 3.0, slow_slot: bool = False,
         log_dir: Optional[str] = None, port: int = 0,
         alert_rules: Optional[str] = None,
         verbose: bool = True) -> dict:
    """Run the randomized soak; returns a report dict whose
    ``violations`` list is empty on a healthy session layer (and, with
    ``learner_stall`` > 0 or an overload drill flag, a healthy
    alert/flow plane — see module docstring)."""
    from pytorch_distributed_tpu.config import FlowParams
    from pytorch_distributed_tpu.utils import flow as flow_mod

    rng = np.random.default_rng(seed)
    clock = GlobalClock()
    stats = ActorStats()
    store = ParamStore(8)
    store.publish(np.zeros(8, dtype=np.float32))
    log = ChunkLog()

    # ---- overload drills (ISSUE 11): deterministic conservation needs
    # a wire with no injected faults (retransmit duplicates would blur
    # the exactly-once count) and one long-lived governor (no gateway
    # restarts); the flood keeps a quarantine leg only where the drill
    # isn't shedding most of the poison client-side anyway
    flow_drill = bool(flood or slow_ingest > 0 or slow_slot)
    drill_env_saved: Dict[str, Optional[str]] = {}
    if flow_drill:
        restart_every = None
        fault_rates = {}
        learner_stall = 0.0
        if flood or slow_slot:
            poison_every = 0
        # clients resolve their OWN FlowParams from the environment
        # (the production spawn-inheritance contract) — size their ring
        # for a seconds-scale drill: at the default 256 chunks, three
        # recovering clients dump ~768 buffered chunks into a 48-bound
        # ingest and re-flood it forever (bufferbloat oscillation — the
        # drill would never observe the alert resolve)
        for k, v in (("TPU_APEX_FLOW_CLIENT_RING", "24"),):
            drill_env_saved[k] = os.environ.get(k)
            os.environ[k] = v
    flow_mod.reset_shed_state()

    # ---- mission-control plane (ISSUE 10): attached whenever the
    # learner-stall drill, an overload drill, or an explicit rule set
    # asks for it
    mission = None
    learner_writer = None
    flow_writer = None
    if learner_stall > 0 or flow_drill or alert_rules is not None \
            or log_dir:
        from pytorch_distributed_tpu.config import (
            AlertParams, MetricsParams,
        )
        from pytorch_distributed_tpu.utils import (
            flight_recorder, telemetry,
        )
        from pytorch_distributed_tpu.utils.metrics import MetricsWriter

        if log_dir:
            flight_recorder.configure(log_dir, run_id="chaos-soak")
        mission = telemetry.MissionControl(
            log_dir, MetricsParams(enabled=True, poll_s=0.2),
            AlertParams(rules=alert_rules
                        or (FLOW_ALERT_RULES if flow_drill
                            else SOAK_ALERT_RULES)))
        mission.start()
        if log_dir:
            # the full production ingest path: the simulated learner
            # (and the overload governor) WRITE rows, the mission TAILS
            # them (no direct feeding)
            learner_writer = MetricsWriter(
                log_dir, enable_tensorboard=False, role="learner",
                run_id="chaos-soak")
            flow_writer = MetricsWriter(
                log_dir, enable_tensorboard=False, role="gateway",
                run_id="chaos-soak")
        elif mission is not None:
            flow_writer = _AggregatorWriter(mission.metrics)

    def _health() -> dict:
        return mission.status_block() if mission is not None else {}

    # ---- the ingest + flow plane for overload drills: a paced drain
    # behind the gateway, its backlog pressure driving the governor.
    # Non-drill soaks keep the direct sink and an inert flow plane
    # (healthy forever — no pressure provider), exactly as before.
    ingest: Optional[IngestSim] = None
    flow_params = None
    pressure = None
    if flow_drill:
        # flood: a drain the fleet trivially outruns; slow-slot: one
        # the RUNAWAY alone swamps but calm peers don't; slow-ingest: a
        # comfortable drain, so overload comes only from the pause
        ingest = IngestSim(log, bound=48,
                           rate=(120.0 if flood else
                                 160.0 if slow_slot else 400.0))
        pressure = ingest.pressure
        flow_params = FlowParams(
            dwell_s=0.2, recover_s=0.4, brownout_dwell_s=1.0,
            throttle_at=0.6, shed_at=0.9, recover_at=0.3,
            client_ring=24,
            # slow-slot: per-slot buckets sized so a well-paced actor
            # (~50 chunks/s) never drains its bucket while the runaway
            # does — the fairness mechanism under test
            bucket_rate=80.0, bucket_burst=40.0)

    gw = DcnGateway(store, clock, stats,
                    put_chunk=(ingest if ingest is not None else log),
                    host="127.0.0.1", port=port, idle_deadline=30.0,
                    health=_health,
                    metrics_sink=(mission.ingest_remote
                                  if mission is not None else None),
                    flow_params=flow_params, pressure=pressure,
                    flow_writer=flow_writer)
    if gw.flow is not None and flow_drill:
        gw.flow._update_every = 0.1  # seconds-scale drill cadence
    port = gw.port
    violations: List[str] = []
    fenced = 0
    quarantined = 0
    gateway_restarts = 0

    # one seeded actor gets a mid-run freeze of several heartbeat
    # intervals — the hang-adjacent stall the session layer must ride
    # through (the full hang->SIGKILL->respawn ladder needs a process
    # supervisor and is drilled by tests/test_health.py).  Overload
    # drills skip it (their timing story is the credit plane's).
    stall_slot = (int(rng.integers(actors))
                  if actors and not flow_drill else -1)

    def _pace(i: int) -> float:
        if flood:
            return 0.0005       # everyone floods
        if slow_slot:
            return 0.0005 if i == 0 else 0.04  # one runaway, calm peers
        if slow_ingest > 0:
            return 0.01         # healthy until the drain pauses
        return 0.002

    def _calm_at(i: int) -> float:
        """Seconds into the run a flooding actor drops to a gentle pace
        — the recovery window the ``overload`` alert must RESOLVE in
        (a drill that ends mid-overload can't tell resolution from a
        stuck alert).  Only flooding actors switch; paced actors keep
        their rate throughout."""
        if flood or (slow_slot and i == 0):
            return seconds * 0.55
        return -1.0

    fleet = [
        SyntheticActor(
            ("127.0.0.1", port), slot=i, pace=_pace(i),
            calm_at=_calm_at(i),
            poison_every=poison_every,
            stall_at=(50 + int(rng.integers(100))
                      if i == stall_slot else -1),
            stall_s=2.5,
            client_kwargs=dict(
                reconnect_timeout=reconnect_timeout,
                heartbeat_interval=(0.3 if flow_drill else 0.5),
                faults=FaultInjector.random(
                    seed * 1000 + i,
                    rates=fault_rates, name=f"actor-{i}"),
            )).start()
        for i in range(actors)
    ]

    t_start = time.monotonic()
    deadline = t_start + seconds
    next_restart = (time.monotonic() + restart_every
                    if restart_every else float("inf"))
    incarnation_high: Dict[int, int] = {}
    learner_step = 0
    stall_seen = False
    ingest_paused = False
    while time.monotonic() < deadline:
        time.sleep(0.1)
        elapsed = time.monotonic() - t_start
        if (ingest is not None and slow_ingest > 0 and not ingest_paused
                and elapsed >= slow_ingest_at):
            # the --slow-learner-ingest event: the drain freezes for
            # ``slow_ingest`` seconds mid-run; pressure must climb, the
            # governor must engage, and everything must recover after
            ingest.pause(slow_ingest)
            ingest_paused = True
        stalled = (learner_stall > 0
                   and learner_stall_at <= elapsed
                   < learner_stall_at + learner_stall)
        if stalled:
            # the injected learner stall (ISSUE 10 drill): the step
            # clock freezes AND the stats cadence stops emitting — a
            # stuck learner writes nothing, which is exactly what the
            # absence rule watches for
            stall_seen = True
        else:
            learner_step += 5  # the simulated learner's clock
            clock.set_learner_step(learner_step)
            if mission is not None:
                row = {"tag": "learner/updates_per_s", "value": 50.0,
                       "wall": time.time(), "step": learner_step,
                       "role": "learner"}
                if learner_writer is not None:
                    learner_writer.scalar(row["tag"], row["value"],
                                          step=learner_step,
                                          wall=row["wall"])
                    learner_writer.flush()
                else:
                    mission.metrics.ingest([row])
        if learner_step and learner_step % 50 == 0 and not stalled:
            store.publish(np.full(8, learner_step, dtype=np.float32))
        # invariant: slots in range, incarnations never move backwards
        for slot, inc in gw.active_slots.items():
            if not (0 <= slot < actors):
                violations.append(f"unexpected slot {slot} active")
            if inc < incarnation_high.get(slot, 0):
                violations.append(
                    f"slot {slot} incarnation regressed "
                    f"{incarnation_high[slot]} -> {inc}")
            incarnation_high[slot] = max(
                inc, incarnation_high.get(slot, 0))
        if time.monotonic() >= next_restart:
            fenced += gw.fenced
            quarantined += sum(gw.quarantined.values())
            gw.close()
            gateway_restarts += 1
            gw = DcnGateway(store, clock, stats, put_chunk=log,
                            host="127.0.0.1", port=port,
                            idle_deadline=30.0, health=_health,
                            metrics_sink=(mission.ingest_remote
                                          if mission is not None
                                          else None))
            next_restart = (time.monotonic() + restart_every
                            * (0.5 + float(rng.random())))

    clock.stop.set()  # next reply any client sees carries stop:true
    for a in fleet:
        a.thread.join(reconnect_timeout + 15.0)
        if a.thread.is_alive():
            violations.append(f"actor {a.slot} failed to stop (lost slot)")
        elif a.outcome != "stopped":
            violations.append(f"actor {a.slot} ended {a.outcome!r} "
                              f"(lost slot)")
        if a.step_regressions:
            violations.append(f"actor {a.slot} saw the learner clock "
                              f"regress {a.step_regressions}x")
    fenced += gw.fenced
    quarantined += sum(gw.quarantined.values())
    gw.close()
    for k, old in drill_env_saved.items():  # clients are done: restore
        if old is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = old
    if ingest is not None:
        # flush the paced drain's remaining backlog into the delivery
        # log: from here on, "still in flight" is not a ledger bucket
        ingest.close()

    # ---- alert-plane verdict (ISSUE 10): expected alerts must have
    # fired AND resolved; anything else firing is a violation
    alert_report: dict = {}
    if mission is not None:
        mission.stop()
        snap = mission.engine.snapshot()
        fired = sorted(a["rule"] for a in snap if a["fired_total"] > 0)
        unresolved = sorted(a["rule"] for a in snap
                            if a["state"] in ("pending", "firing"))
        expected = ["learner_stall"] if stall_seen else []
        if flow_drill:
            # the overload drills' alert contract: the flow rule must
            # fire during the event AND resolve after recovery; the
            # learner rule (a healthy simulated learner) must stay quiet
            expected = ["overload"]
        unexpected = [r for r in fired if r not in expected]
        if unexpected:
            violations.append(
                f"unexpected alert(s) fired: {unexpected}")
        for r in expected:
            if r not in fired:
                violations.append(
                    f"expected alert {r!r} never fired during the "
                    f"learner-stall drill")
        if unresolved:
            violations.append(
                f"alert(s) {unresolved} still unresolved after "
                f"recovery")
        alert_report = {
            "rules": len(snap),
            "fired": fired,
            "unexpected": unexpected,
            "unresolved": unresolved,
            "resolved_total": sum(a["resolved_total"] for a in snap),
            "stall_injected": bool(stall_seen),
        }
        if learner_writer is not None:
            learner_writer.close()
        if log_dir:
            # leave the production post-mortem set: the mission's ring
            # (alert transitions) + every other ring this process holds
            from pytorch_distributed_tpu.utils import flight_recorder

            flight_recorder.dump_all("chaos soak complete")

    seen = log.seen()
    acked = [t for a in fleet for t in a.acked_tags]
    lost = [t for t in acked if t not in seen]
    if lost and not flow_drill:
        # flow drills shed on purpose (ring drops / gateway tier-3) —
        # their loss accounting is the conservation ledger below, not
        # the per-tag at-least-once check
        violations.append(f"{len(lost)} acked chunks never delivered "
                          f"(first: {lost[:5]})")
    poisoned_sent = sum(a.poisoned_sent for a in fleet)
    if log.poisoned_delivered:
        violations.append(
            f"{log.poisoned_delivered} poisoned transitions reached "
            f"put_chunk (quarantine breached)")
    if poisoned_sent and not quarantined:
        violations.append(
            f"{poisoned_sent} poisoned chunks sent but the gateway "
            f"quarantined none")

    # ---- flow-plane verdict (ISSUE 11): the overload drills' extra
    # invariant set — degradation engaged, memory stayed bounded, and
    # every minted row is in exactly one ledger bucket
    flow_report: dict = {}
    if flow_drill and gw.flow is not None:
        gov = gw.flow.governor
        minted = sum(a.client.flow_minted_rows for a in fleet if a.client)
        dropped = sum(a.client.flow_ring.dropped_rows
                      for a in fleet if a.client)
        buffered = sum(a.client.flow_ring.buffered_rows
                       for a in fleet if a.client)
        ring_high = max((a.client.flow_ring.buffered_high
                         for a in fleet if a.client), default=0)
        ring_bound = max((a.client.flow_ring.max_chunks
                          for a in fleet if a.client), default=1)
        gw_shed = sum(gw.flow.shed_rows.values())
        delivered = len(log.tags) + log.poisoned_delivered
        accounted = delivered + dropped + gw_shed + quarantined + buffered
        drop_share = {}
        for a in fleet:
            if a.client:
                for actor_id, n in a.client.flow_ring.dropped_by_actor.items():
                    drop_share[actor_id] = drop_share.get(actor_id, 0) + n
        for s, n in gw.flow.shed_rows.items():
            drop_share[s] = drop_share.get(s, 0) + n
        total_drops = sum(drop_share.values())
        flow_report = {
            "state": gov.state,
            "tier": gov.tier,
            "transitions": gov.transitions,
            "minted": minted,
            "delivered": delivered,
            "dropped_client": dropped,
            "shed_gateway": gw_shed,
            "quarantined": quarantined,
            "buffered_client": buffered,
            "accounted": accounted,
            "balanced": bool(minted == accounted),
            "client_ring_high": ring_high,
            "ingest_backlog_high": ingest.backlog_high,
            "shed_counts": flow_mod.shed_counts(),
            # who paid for the overload, next to replay/actor_share in
            # the data X-ray: per-actor share of every counted drop
            "drop_share": ({str(aid): round(n / total_drops, 4)
                            for aid, n in sorted(drop_share.items())}
                           if total_drops else {}),
        }
        if minted != accounted:
            violations.append(
                f"conservation breached: minted {minted} != delivered "
                f"{delivered} + dropped {dropped} + gw-shed {gw_shed} "
                f"+ quarantined {quarantined} + buffered {buffered} "
                f"= {accounted} (uncounted drop somewhere)")
        # byte ledger (ISSUE 18): at quiescence every acked EXP
        # payload byte is in exactly one gateway bucket — EXACT, even
        # under brownout (shed bytes counted, never silently lost).
        # Ring-dropped chunks are never encoded, so their bytes never
        # exist; buffered chunks were never acked.
        acked_bytes = sum(a.client.flow_acked_bytes
                          for a in fleet if a.client)
        accounted_bytes = (gw.flow.ingested_bytes
                           + gw.flow.rejected_bytes
                           + gw.flow.shed_bytes)
        flow_report["acked_bytes"] = acked_bytes
        flow_report["ingested_bytes"] = gw.flow.ingested_bytes
        flow_report["rejected_bytes"] = gw.flow.rejected_bytes
        flow_report["shed_bytes"] = gw.flow.shed_bytes
        # bytes shed per brownout rung (tier -> bytes)
        flow_report["shed_bytes_by_tier"] = {
            str(t): int(n)
            for t, n in sorted(gw.flow.shed_bytes_by_tier.items())}
        if acked_bytes != accounted_bytes:
            violations.append(
                f"byte conservation breached: acked {acked_bytes} B "
                f"!= ingested {gw.flow.ingested_bytes} + rejected "
                f"{gw.flow.rejected_bytes} + shed "
                f"{gw.flow.shed_bytes} = {accounted_bytes} B "
                f"(uncounted bytes somewhere)")
        if gov.transitions == 0:
            violations.append(
                "overload never engaged: the governor sat in 'healthy' "
                "through the whole drill (nothing was tested)")
        if ring_high > ring_bound + 1:
            violations.append(
                f"client ring exceeded its bound: high-water "
                f"{ring_high} > {ring_bound} chunks")
        if ingest.backlog_high > ingest.bound * 8:
            violations.append(
                f"ingest backlog unbounded: high-water "
                f"{ingest.backlog_high} chunks vs bound {ingest.bound} "
                f"(flow control never bit)")
        if slow_slot:
            # fairness: the runaway (slot 0) must not starve its calm
            # neighbours — their sends ride their OWN token buckets
            for a in fleet:
                if a.slot == 0 or not a.client:
                    continue
                m = a.client.flow_minted_rows
                ak = a.client.flow_acked_rows
                if m and ak < 0.7 * m:
                    violations.append(
                        f"fairness breached: calm slot {a.slot} got "
                        f"only {ak}/{m} rows through "
                        f"({ak / m:.0%} < 70%)")
    report = {
        "violations": violations,
        "actors": actors,
        "acked_chunks": len(acked),
        "delivered_chunks": len(log.tags),
        "duplicate_deliveries": len(log.tags) - len(seen),
        "reconnects": sum(a.client.reconnects for a in fleet if a.client),
        "injected_faults": sum(
            a.client_kwargs["faults"].injected for a in fleet),
        "poisoned_sent": poisoned_sent,
        "poisoned_delivered": log.poisoned_delivered,
        "quarantined": quarantined,
        "gateway_restarts": gateway_restarts,
        "fenced": fenced,
        "final_learner_step": learner_step,
        "alerts": alert_report,
        "flow": flow_report,
        "port": port,
    }
    if verbose:
        for k, v in report.items():
            if k != "violations":
                print(f"[chaos] {k}: {v}")
        for v in violations:
            print(f"[chaos] VIOLATION: {v}")
    return report


# ---------------------------------------------------------------------------
# replica-plane drills (ISSUE 15): kill / hang / rejoin through the
# production fault plane
# ---------------------------------------------------------------------------

class SyntheticReplica:
    """Numpy-only learner replica for the chaos drills: the REAL
    lease/round/fencing machinery — ReplicaClient over the wire against
    a gateway's ReplicaRegistry — with a toy params vector standing in
    for the TrainState, so membership drills run in milliseconds
    without jax (the jax-true oracle lives in tests/test_replicas.py).

    Faults ride the production plane (utils/faults.py), consulted once
    per round exactly like the real driver: ``crash@N`` dies without
    releasing the lease (the in-process stand-in for SIGKILL — the
    renewer stops with the 'process', so the lease expires and fences);
    ``hang@N:S`` freezes the round loop while the renewer keeps
    faithfully renewing — the alive-but-stuck mode only the registry's
    round-stall rule can fence.

    ``history[r]`` records the params vector after round ``r`` — the
    drill's divergent-params verdict compares these across replicas."""

    def __init__(self, address, rid: int, replicas: int, dim: int = 64,
                 rounds: int = 30, pace: float = 0.02,
                 faults: Optional[FaultInjector] = None,
                 epoch_store: Optional[dict] = None,
                 join: bool = False, seed: int = 0,
                 hold: Optional[threading.Event] = None):
        self.address = address
        self.rid = rid
        self.replicas = replicas
        self.dim = dim
        self.rounds = rounds
        self.pace = pace
        self.faults = faults or FaultInjector(name=f"replica-{rid}")
        self.epoch_store = epoch_store if epoch_store is not None else {}
        self.join = join
        self.rng = np.random.default_rng((seed, rid))
        self.params = np.zeros(dim, np.float32)
        self.history: Dict[int, np.ndarray] = {}
        self.members_seen: List[List[int]] = []
        self.outcome: Optional[str] = None
        self.dead_generation: Optional[int] = None
        self.client = None
        self.thread: Optional[threading.Thread] = None
        # drill choreography: a finished replica HOLDS its lease (the
        # renewer keeps it) until the orchestrator has read the alert
        # verdict from a fully-recovered membership, then releases
        self.hold = hold
        self.done_rounds = threading.Event()

    def start(self) -> "SyntheticReplica":
        self.thread = threading.Thread(
            target=self.run, name=f"chaos-replica-{self.rid}",
            daemon=True)
        self.thread.start()
        return self

    def run(self) -> None:
        from pytorch_distributed_tpu.parallel.dcn import (
            RSTAT_OK, ReplicaClient, ReplicaFenced,
        )
        from pytorch_distributed_tpu.utils.faults import InjectedCrash

        try:
            self.client = client = ReplicaClient(self.address, self.rid)
            reply = client.acquire()
        except (ReplicaFenced, ConnectionError, OSError) as e:
            self.outcome = f"lease-refused: {e!r}"
            return
        client.start_renewer()
        r = int(reply.get("round", 0))
        barrier = reply.get("epoch_barrier")
        if barrier is None:
            # fresh start: hold the first submit until the whole fleet
            # has leased — a peer acquiring after round 0 opens would
            # otherwise (correctly, but nondeterministically for the
            # drill ledger) enter through the join barrier instead
            client.wait_members(self.replicas, timeout=10.0)
        if barrier is not None:
            # the joiner leg: wait for the survivors' barrier epoch,
            # load exactly it, fast-forward, activate
            deadline = time.monotonic() + 20.0
            epoch_step = None
            while time.monotonic() < deadline:
                j = client.poll_join()
                if j is None:
                    self.outcome = "join-cancelled"
                    client.close()
                    return
                if j.get("epoch_step") is not None:
                    epoch_step = int(j["epoch_step"])
                    break
                time.sleep(0.02)
            while epoch_step is not None and \
                    self.epoch_store.get("step", -1) < epoch_step \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            if epoch_step is None or \
                    self.epoch_store.get("step", -1) < epoch_step:
                self.outcome = "join-epoch-missing"
                client.close()
                return
            self.params = np.asarray(self.epoch_store["params"],
                                     np.float32).copy()
            r = int(reply["round"])
            client.activate(epoch_step)
        try:
            while r < self.rounds:
                self.faults.frame(b"")
                grad = self.rng.standard_normal(self.dim).astype(
                    np.float32)
                res = client.submit_round(
                    r, grad, pidx=np.asarray([r % 16], np.int32),
                    ptd=np.asarray([0.5], np.float32))
                if res["status"] != RSTAT_OK:
                    self.outcome = "fenced"
                    self.dead_generation = client.generation
                    client.close()
                    return
                self.members_seen.append(list(res["members"]))
                if res["grad"] is not None:
                    self.params = self.params - 0.1 * np.asarray(
                        res["grad"], np.float32)
                self.history[r] = self.params.copy()
                if res.get("epoch_due") and res["members"] \
                        and res["members"][0] == self.rid:
                    # rank 0 commits the join-barrier "epoch" (the
                    # shared dict stands in for the checkpoint store)
                    self.epoch_store["step"] = r + 1
                    self.epoch_store["params"] = self.params.copy()
                    client.note_epoch(r, r + 1)
                r += 1
                if self.pace:
                    time.sleep(self.pace)
        except InjectedCrash:
            # the kill drill: die WITHOUT releasing — the renewer dies
            # with the 'process' and the lease must expire and fence
            self.outcome = "killed"
            self.dead_generation = client.generation
            client.close()
            return
        except (ConnectionError, OSError) as e:
            self.outcome = f"wire-lost: {e!r}"
            client.close()
            return
        self.done_rounds.set()
        if self.hold is not None:
            self.hold.wait(30.0)
        self.outcome = "done"
        client.release()
        client.close()


def replica_soak(replicas: int = 2, rounds: int = 30, seed: int = 0,
                 kill_at: Optional[int] = None,
                 hang_at: Optional[int] = None,
                 rejoin: bool = False, lease_s: float = 0.6,
                 log_dir: Optional[str] = None, port: int = 0,
                 verbose: bool = True) -> dict:
    """The ISSUE-15 replica chaos drill: N synthetic replicas train a
    toy model through the REAL gateway registry while the scripted
    fault (kill or hang, via the production ``utils/faults.py`` plane)
    removes one mid-run; with ``rejoin`` a replacement re-leases at a
    new generation through the epoch barrier.  Verdict failures:

    - **deadlock** — any replica thread alive at the join deadline;
    - **divergent-params** — two live replicas disagree on the params
      vector after any common round (the one-logical-model invariant);
    - **unfenced-stale-write** — the killed replica's zombie submits a
      stale-generation gradient and priority write-back; both must be
      counted rejects, and the fencing counters must match EXACTLY;
    - **expected-alert-never-fired / any-unexpected-alert / unresolved**
      — the ``replica_degraded`` membership alert must fire during the
      degraded window, resolve after the rejoin, and nothing else may
      fire;
    - **ledger mismatch** — every lease/round/fence counter on the
      registry must equal the drill script's predicted value."""
    from pytorch_distributed_tpu.config import (
        AlertParams, MetricsParams, ReplicaParams,
    )
    from pytorch_distributed_tpu.parallel.dcn import (
        ReplicaClient, ReplicaRegistry, RSTAT_FENCED, RSTAT_STALE,
    )
    from pytorch_distributed_tpu.utils import flight_recorder, telemetry
    from pytorch_distributed_tpu.utils.metrics import MetricsWriter

    assert not (kill_at is not None and hang_at is not None), \
        "pick ONE of --kill-replica / --hang-replica per drill"
    fault_at = kill_at if kill_at is not None else hang_at
    violations: List[str] = []

    rules = (f"replica_degraded: replica/members < {replicas} for 0.3s; "
             f"replica_churny: replica/generation_churn > 50 for 2s")
    if log_dir:
        flight_recorder.configure(log_dir, run_id="chaos-soak")
    mission = telemetry.MissionControl(
        log_dir, MetricsParams(enabled=True, poll_s=0.1),
        AlertParams(rules=rules))
    mission.start()
    if log_dir:
        reg_writer = MetricsWriter(log_dir, enable_tensorboard=False,
                                   role="gateway", run_id="chaos-soak")
    else:
        reg_writer = _AggregatorWriter(mission.metrics)

    registry = ReplicaRegistry(
        ReplicaParams(replicas=replicas, lease_s=lease_s,
                      join_timeout_s=15.0),
        writer=reg_writer)
    clock = GlobalClock()
    store = ParamStore(8)
    store.publish(np.zeros(8, dtype=np.float32))
    gw = DcnGateway(store, clock, ActorStats(),
                    put_chunk=lambda items: None, host="127.0.0.1",
                    port=port, idle_deadline=30.0,
                    health=lambda: mission.status_block(),
                    replicas=registry)
    addr = ("127.0.0.1", gw.port)

    pace = 0.04
    epoch_store: dict = {}
    hold = threading.Event()
    fleet = []
    victim = replicas - 1  # the highest slot dies; rank 0 survives
    for i in range(replicas):
        spec = ""
        if i == victim and fault_at is not None:
            spec = (f"crash@{fault_at}" if kill_at is not None
                    else f"hang@{fault_at}:{lease_s * 3:.2f}")
        fleet.append(SyntheticReplica(
            addr, i, replicas, rounds=rounds, pace=pace,
            faults=(FaultInjector.scripted(spec, name=f"replica-{i}")
                    if spec else None),
            epoch_store=epoch_store, seed=seed, hold=hold).start())

    deadline = time.monotonic() + max(30.0, rounds * pace * 3 + 25.0)
    joiner = None
    if rejoin and fault_at is not None:
        # the replacement: spawned once the degraded window is live (so
        # the membership alert has a dwell's worth of it to fire on),
        # re-leases at a NEW generation and syncs through the
        # join-barrier epoch — while the survivors are still training.
        # Wait for FULL membership first: before the fleet finishes
        # leasing, "degraded" is trivially true and a joiner spawned
        # then would fence the still-live victim instead of replacing
        # a dead one.
        while time.monotonic() < deadline and \
                len(registry.status_block()["members"]) < replicas:
            time.sleep(0.02)
        while time.monotonic() < deadline \
                and not registry.status_block()["degraded"]:
            time.sleep(0.05)
        time.sleep(1.0)  # let the alert walk pending -> firing
        joiner = SyntheticReplica(
            addr, victim, replicas, rounds=rounds, pace=pace,
            epoch_store=epoch_store, join=True, seed=seed,
            hold=hold).start()

    survivors = [rep for rep in fleet
                 if fault_at is None or rep.rid != victim]
    for rep in survivors + ([joiner] if joiner is not None else []):
        if not rep.done_rounds.wait(max(0.1, deadline
                                        - time.monotonic())):
            violations.append(f"deadlock: replica {rep.rid} never "
                              f"finished its rounds")
    if fault_at is not None:
        fleet[victim].thread.join(max(0.1, deadline - time.monotonic()))
        if fleet[victim].thread.is_alive():
            violations.append("deadlock: victim replica still running "
                              "at the join deadline")

    # ---- zombie leg: the dead replica's generation must be fenced —
    # a stale gradient AND a stale priority write-back, both counted
    stale_expected = 0
    if fault_at is not None:
        dead = fleet[victim]
        dead_gen = dead.dead_generation
        if dead_gen is None:
            violations.append(
                f"victim replica ended {dead.outcome!r} with no "
                f"generation to test fencing with")
        else:
            zc = ReplicaClient(addr, victim)
            zc.generation = dead_gen  # the zombie's stale credential
            res = zc.submit_round(max(0, rounds - 1),
                                  np.zeros(4, np.float32))
            if res["status"] not in (RSTAT_FENCED, RSTAT_STALE):
                violations.append(
                    f"unfenced stale write: zombie gradient accepted "
                    f"(status {res['status']})")
            pres = zc.merge_prio(np.asarray([0], np.int32),
                                 np.asarray([9.9], np.float32))
            if pres.get("status") != "stale":
                violations.append(
                    f"unfenced stale write: zombie priority write-back "
                    f"accepted ({pres})")
            zc.close()
            stale_expected = 1

    # ---- alert verdict, read while the (recovered) membership still
    # holds its leases: with a rejoin the degraded rule must have
    # resolved by now; without one it legitimately stays firing
    if rejoin and fault_at is not None:
        end = time.monotonic() + 5.0
        while time.monotonic() < end:
            mission.poll()
            snap = {a["rule"]: a for a in mission.engine.snapshot()}
            dg = snap.get("replica_degraded", {})
            if dg.get("fired_total", 0) > 0 \
                    and dg.get("state") not in ("pending", "firing"):
                break
            time.sleep(mission.params.poll_s)
    else:
        time.sleep(3 * mission.params.poll_s + 0.2)
    mission.poll()
    alert_snap = mission.engine.snapshot()
    hold.set()  # verdict read: finished replicas may release now
    for rep in fleet + ([joiner] if joiner is not None else []):
        rep.thread.join(10.0)
    clock.stop.set()
    mission.stop()
    gw.close()

    # ---- membership / params verdicts -------------------------------------
    live = [rep for rep in fleet if rep.rid != victim
            or fault_at is None]
    for rep in live:
        if rep.outcome != "done":
            violations.append(f"replica {rep.rid} ended "
                              f"{rep.outcome!r} (expected 'done')")
    if fault_at is not None:
        v = fleet[victim]
        want = ("killed",) if kill_at is not None else ("fenced",)
        if v.outcome not in want:
            violations.append(f"victim replica ended {v.outcome!r} "
                              f"(expected {want[0]!r})")
        if rejoin and (joiner is None or joiner.outcome != "done"):
            violations.append(
                f"rejoined replica ended "
                f"{joiner.outcome if joiner else 'never-spawned'!r}")
    peers = list(fleet) + ([joiner] if joiner is not None else [])
    for i, a in enumerate(peers):
        for b in peers[i + 1:]:
            common = sorted(set(a.history) & set(b.history))
            for r in common:
                if not np.array_equal(a.history[r], b.history[r]):
                    violations.append(
                        f"divergent params: replicas {a.rid}/{b.rid} "
                        f"disagree after round {r}")
                    break

    # ---- exact-ledger verdict ---------------------------------------------
    c = registry.status_block()["counters"]
    expected_granted = replicas + (1 if (rejoin and joiner is not None)
                                   else 0)
    checks = [("leases_granted", expected_granted),
              ("stale_prio_rejected", stale_expected),
              ("joins_completed",
               1 if (rejoin and joiner is not None
                     and joiner.outcome == "done") else 0),
              ("lease_fenced", 0),
              ("joins_timed_out", 0)]
    if fault_at is not None:
        checks.append(("leases_expired", 1))
        # the zombie's stale gradient is one counted grad reject; the
        # hung victim's own post-expulsion submit is a second one
        checks.append(("stale_grad_rejected",
                       stale_expected + (1 if hang_at is not None
                                         else 0)))
    for name, want in checks:
        if c.get(name) != want:
            violations.append(f"ledger mismatch: {name} = "
                              f"{c.get(name)} (expected {want})")
    if fault_at is not None and registry.degraded_completions < 1:
        violations.append("no degraded round completion was counted "
                          "(the fault never bit)")

    # ---- alert verdict (snapshot taken while membership was full) ----------
    fired = sorted(a["rule"] for a in alert_snap
                   if a["fired_total"] > 0)
    unresolved = sorted(a["rule"] for a in alert_snap
                        if a["state"] in ("pending", "firing"))
    expected_alerts = (["replica_degraded"] if fault_at is not None
                       else [])
    unexpected = [r for r in fired if r not in expected_alerts]
    if unexpected:
        violations.append(f"unexpected alert(s) fired: {unexpected}")
    for r in expected_alerts:
        if r not in fired:
            violations.append(f"expected alert {r!r} never fired "
                              f"during the degraded window")
    if rejoin and unresolved:
        violations.append(f"alert(s) {unresolved} still unresolved "
                          f"after the rejoin recovered membership")

    report = {
        "violations": violations,
        "replicas": replicas,
        "rounds": rounds,
        "kill_at": kill_at,
        "hang_at": hang_at,
        "rejoin": rejoin,
        "outcomes": {rep.rid: rep.outcome for rep in fleet},
        "joiner_outcome": joiner.outcome if joiner is not None else None,
        "counters": c,
        "rounds_completed": registry.rounds_completed,
        "degraded_completions": registry.degraded_completions,
        "alerts": {"fired": fired, "unexpected": unexpected,
                   "unresolved": unresolved},
        "port": addr[1],
    }
    if log_dir:
        reg_writer.close()
        flight_recorder.dump_all("replica chaos drill complete")
    if verbose:
        for k, v in report.items():
            if k != "violations":
                print(f"[chaos] {k}: {v}")
        for v in violations:
            print(f"[chaos] VIOLATION: {v}")
    return report


# ---------------------------------------------------------------------------
# sharded-replay drills (ISSUE 20): kill / rejoin / rebalance a replay
# shard under live ingest + two-level sampling — the priority plane must
# degrade to the survivors within one lease window, with an EXACT
# conservation ledger and a fenced write-back plane
# ---------------------------------------------------------------------------

# the shard drill's rule set: the membership rule MUST fire while the
# plane is degraded and resolve once the rejoin/re-acquire activates;
# the flap rule (same tag, a dwell no drill can sustain) is the
# quiet-by-construction guard for the unexpected-alert invariant
SHARD_ALERT_RULES = (
    "shard_membership: replay/shard_degraded >= 1 for 0.3s; "
    "shard_flap: replay/shard_degraded >= 1 for 30s")


class SyntheticShardHost:
    """One replay-shard host in-process: a ``LocalShard`` behind its OWN
    ``DcnGateway`` (T_EXP ingest + the shard verbs on the real wire),
    lease-renewing against the coordinator gateway —
    ``fleet.run_replay_shard_host`` without the process boundary, so the
    drill can kill it at an exact quiescent instant and read its trees
    directly for the sampling-mass-vs-survivor-mass verdict."""

    def __init__(self, coordinator, sid: int, shard_capacity: int,
                 lease_s: float, incarnation: int = 1):
        from pytorch_distributed_tpu.memory.shard_plane import (
            LocalShard, ShardLease,
        )

        self.sid = int(sid)
        self.shard_capacity = int(shard_capacity)
        self.shard = LocalShard(sid, self._fresh_per())
        self.lease = ShardLease(coordinator, sid,
                                incarnation=incarnation,
                                capacity=shard_capacity)
        self.lease.acquire()
        self.shard.generation = int(self.lease.generation)
        self.lease_s = float(lease_s)
        self._stop = threading.Event()
        self.clock = GlobalClock()
        self.gw = DcnGateway(ParamStore(4), self.clock, ActorStats(),
                             put_chunk=self._ingest, host="127.0.0.1",
                             port=0, idle_deadline=30.0,
                             shards=self.shard)
        self.addr = ("127.0.0.1", self.gw.port)
        self._renewer = threading.Thread(
            target=self._renew_loop, name=f"shard-host-{sid}",
            daemon=True)
        self._renewer.start()

    def _fresh_per(self):
        from pytorch_distributed_tpu.memory.prioritized import (
            PrioritizedReplay,
        )

        return PrioritizedReplay(
            capacity=self.shard_capacity, state_shape=(2,),
            state_dtype=np.float32, action_shape=(),
            action_dtype=np.int32, priority_exponent=0.6,
            importance_weight=0.4, importance_anneal_steps=1000)

    def _report(self) -> dict:
        m = self.shard.mass()
        m["mass"] = m["total"]
        m["fill"] = m["size"] / max(1, self.shard.per.capacity)
        return m

    def _ingest(self, items: list) -> None:
        for t, p in items:
            self.shard.feed(t, p)
        if not self.shard.alive:
            return
        if self.lease.joining and self.shard.ingested_rows > 0:
            self.lease.activate()
        # renew BEFORE the gateway acks the chunk (the T_CLOCK ack goes
        # out after put_chunk returns): every row the plane counts as
        # delivered is already in the registry's ingested leg — the
        # conservation ledger is exact at the kill instant, not
        # eventually
        self.lease.renew(self._report())

    def _renew_loop(self) -> None:
        period = max(0.05, self.lease_s / 3.0)
        while not self._stop.wait(period):
            if self.shard.alive:
                try:
                    self.lease.renew(self._report())
                except (ConnectionError, OSError):
                    pass

    def final_renew(self) -> None:
        """Push the definitive ingest report before a verdict read."""
        if self.shard.alive:
            self.lease.renew(self._report())

    def rebalance_reacquire(self) -> None:
        """The --shard-rebalance leg: after a graceful release, take the
        slot back as a FRESH incarnation — empty ring, zeroed ledger
        legs (the released rows were counted ``shard_lost``; serving
        them again would double-count) — through the join barrier."""
        self.shard.per = self._fresh_per()
        self.shard.ingested_rows = 0
        self.shard.stale_rejected = 0
        self.lease.incarnation += 1
        self.lease.acquire()
        self.shard.generation = int(self.lease.generation)

    def kill(self) -> None:
        """SIGKILL-equivalent: the shard answers nothing, renews
        nothing, and its lease expires on the coordinator."""
        self.shard.alive = False
        self._stop.set()
        self.clock.stop.set()
        self.gw.close()

    def shutdown(self) -> None:
        self._stop.set()
        if self.shard.alive:
            self.lease.release()
        self.clock.stop.set()
        self.gw.close()


def shard_soak(shards: int = 3, seconds: float = 8.0, seed: int = 0,
               kill_at: Optional[float] = None, rejoin: bool = False,
               rebalance: bool = False, lease_s: float = 0.5,
               batch: int = 32, log_dir: Optional[str] = None,
               port: int = 0, verbose: bool = True) -> dict:
    """The ISSUE-20 shard-loss degradation drill: N synthetic shard
    hosts serve one fault-fenced priority plane through REAL gateways
    (T_EXP ingest, T_SSAMPLE two-level sampling, T_SPRIO write-back,
    T_SMASS leases) while actors mint and a learner-side sampler draws
    and writes back continuously.  Verdict failures:

    - **deadlock** — any actor/sampler thread alive at the join
      deadline, or the plane never reaching steady sampling;
    - **conservation breached** — the ledger ``minted = ingested +
      shard_lost + route_dropped`` must balance EXACTLY (every row a
      dead shard took down is COUNTED, never silently resampled away);
    - **fencing too slow / never fenced** — the killed shard must leave
      membership within ~one lease window;
    - **sampling stalled** — the survivors must keep serving batches
      through the degraded window;
    - **mass divergence** — the plane's sampling-mass vector must equal
      the survivors' exact ``sum_tree.total`` floats;
    - **unfenced stale write-back** — a batch sampled before the kill
      must have its dead-shard rows counted as rejects on write-back
      (plane side), and a zombie writer holding the dead generation
      must be a counted reject at the rejoined shard (host side);
    - **expected-alert-never-fired / any-unexpected-alert /
      unresolved** — the ``shard_membership`` alert must fire during
      the degraded window, resolve after the rejoin/re-acquire, and
      nothing else may fire."""
    from pytorch_distributed_tpu.config import (
        AlertParams, MetricsParams, ShardParams,
    )
    from pytorch_distributed_tpu.memory.shard_plane import (
        RemoteShardChannel, ShardedReplayPlane, ShardRegistry,
    )
    from pytorch_distributed_tpu.utils import flight_recorder, telemetry
    from pytorch_distributed_tpu.utils.experience import make_prov
    from pytorch_distributed_tpu.utils.metrics import MetricsWriter

    violations: List[str] = []
    if log_dir:
        flight_recorder.configure(log_dir, run_id="chaos-soak")
    mission = telemetry.MissionControl(
        log_dir, MetricsParams(enabled=True, poll_s=0.1),
        AlertParams(rules=SHARD_ALERT_RULES))
    mission.start()
    if log_dir:
        reg_writer = MetricsWriter(log_dir, enable_tensorboard=False,
                                   role="gateway", run_id="chaos-soak")
    else:
        reg_writer = _AggregatorWriter(mission.metrics)

    registry = ShardRegistry(
        ShardParams(shards=shards, lease_s=lease_s,
                    join_timeout_s=15.0),
        writer=reg_writer)
    clock = GlobalClock()
    gw = DcnGateway(ParamStore(4), clock, ActorStats(),
                    put_chunk=lambda items: None, host="127.0.0.1",
                    port=port, idle_deadline=30.0,
                    health=lambda: mission.status_block(),
                    shards=registry)
    addr = ("127.0.0.1", gw.port)

    cap = 512
    hosts: Dict[int, SyntheticShardHost] = {
        sid: SyntheticShardHost(addr, sid, cap, lease_s)
        for sid in range(shards)}
    channels = {sid: RemoteShardChannel(h.addr, sid,
                                        h.lease.generation)
                for sid, h in hosts.items()}
    plane = ShardedReplayPlane(
        channels, registry, cap, state_shape=(2,),
        state_dtype=np.float32, action_dtype=np.int32,
        importance_weight=0.4, importance_anneal_steps=1000)

    # ONE learner: every plane op (routed feed, two-level sample,
    # write-back, the kill itself) serializes on this lock — which is
    # what makes the kill land at a QUIESCENT instant, so the
    # conservation ledger must balance exactly, not modulo a race
    plane_lock = threading.Lock()
    stop = threading.Event()
    # one actor per shard plus one: slot-stable routing (prov[0] picks
    # the shard) must leave NO shard coverage-starved — including the
    # rejoiner, whose activation rides its first routed row
    actors = shards + 1
    minted = [0] * actors
    sampled = [0]

    def actor_loop(aid: int) -> None:
        step = 0
        while not stop.is_set():
            t = tagged_transition(aid * 1_000_000 + step)
            t = t._replace(prov=make_prov(aid, 0, 0, step))
            with plane_lock:
                plane.feed(t, None)
                minted[aid] += 1
            step += 1
            time.sleep(0.004)

    rng = np.random.default_rng(seed)

    def sampler_loop() -> None:
        while not stop.is_set():
            with plane_lock:
                plane._refresh_mass(force=True)
                if plane._mass and sum(
                        e["size"] for e in plane._mass) >= batch:
                    b = plane.sample(batch, rng)
                    plane.update_priorities(
                        b.index, np.abs(b.reward) * 1e-7 + 0.5)
                    sampled[0] += 1
            time.sleep(0.004)

    threads = [threading.Thread(target=actor_loop, args=(aid,),
                                name=f"shard-actor-{aid}", daemon=True)
               for aid in range(actors)]
    threads.append(threading.Thread(target=sampler_loop,
                                    name="shard-sampler", daemon=True))
    t0 = time.monotonic()
    for th in threads:
        th.start()

    victim = shards - 1
    stale_expected = 0
    joiner: Optional[SyntheticShardHost] = None
    fence_s = None
    dead_generation = None
    try:
        # ---- warm-up: the plane must actually be sampling ---------------
        while time.monotonic() - t0 < 15.0 and sampled[0] < 3:
            time.sleep(0.02)
        if sampled[0] < 3:
            violations.append("deadlock: the plane never reached "
                              "steady sampling during warm-up")

        if kill_at is not None and not violations:
            while time.monotonic() - t0 < kill_at:
                time.sleep(0.01)
            # ---- the kill, at a quiescent instant -----------------------
            with plane_lock:
                # draw the soon-to-be-stale batch FIRST: its dead-shard
                # rows are the unfenced-stale-write probe
                plane._refresh_mass(force=True)
                stale_batch = plane.sample(batch * 2, rng)
                stale_victim_rows = int(
                    (stale_batch.index // cap == victim).sum())
                dead_generation = hosts[victim].shard.generation
                hosts[victim].kill()
            if stale_victim_rows == 0:
                violations.append(
                    "drill impotent: the pre-kill batch drew no "
                    "victim rows (nothing to test fencing with)")
            t_kill = time.monotonic()
            # ---- fencing: within ~one lease window ----------------------
            while time.monotonic() - t_kill < lease_s * 4 + 2.0:
                if registry.status_block()["degraded"]:
                    break
                time.sleep(0.01)
            fence_s = time.monotonic() - t_kill
            if not registry.status_block()["degraded"]:
                violations.append(
                    f"shard loss never fenced (no degradation after "
                    f"{fence_s:.1f}s; lease window {lease_s}s)")
            elif fence_s > lease_s * 2.0 + 0.5:
                violations.append(
                    f"fencing too slow: {fence_s:.2f}s > one lease "
                    f"window ({lease_s}s) + slop")
            # ---- sampling must CONTINUE over the survivors --------------
            s_before = sampled[0]
            t_chk = time.monotonic()
            while time.monotonic() - t_chk < 5.0 \
                    and sampled[0] < s_before + 5:
                time.sleep(0.02)
            if sampled[0] < s_before + 5:
                violations.append("sampling stalled after the shard "
                                  "loss (survivors must keep serving)")
            # ---- mass vector == the survivors' EXACT tree totals --------
            with plane_lock:
                plane._refresh_mass(force=True)
                got = {e["shard"]: float(e["total"])
                       for e in plane._mass}
                want = {sid: float(h.shard.per.sum_tree.total)
                        for sid, h in hosts.items() if h.shard.alive}
                if got != want:
                    violations.append(
                        f"sampling mass diverged from survivor mass: "
                        f"plane={got} survivors={want}")
                # ---- the stale write-back: counted, never applied -------
                before = registry.stale_writeback_rejected
                plane.update_priorities(
                    stale_batch.index,
                    np.full(len(stale_batch.index), 9.9, np.float32))
                counted = registry.stale_writeback_rejected - before
                if counted != stale_victim_rows:
                    violations.append(
                        f"unfenced stale write-back: "
                        f"{stale_victim_rows} dead-shard rows in the "
                        f"batch, {counted} counted rejects")
            stale_expected = stale_victim_rows

        if rejoin and kill_at is not None:
            time.sleep(1.0)  # the 0.3s-dwell membership alert fires
            joiner = SyntheticShardHost(addr, victim, cap, lease_s,
                                        incarnation=2)
            if not joiner.lease.joining:
                violations.append("rejoin skipped the join barrier "
                                  "(fresh lease was not 'joining')")
            with plane_lock:
                channels[victim] = RemoteShardChannel(
                    joiner.addr, victim, joiner.lease.generation)
                plane.attach_channel(victim, channels[victim])
            # routed ingest warms it; the first acked row activates it.
            # degraded flips False the moment the lease is GRANTED (the
            # joiner counts as a member while JOINING), so waiting on
            # degraded alone is a no-op — wait for the activation proper
            t_j = time.monotonic()
            while time.monotonic() - t_j < 10.0 and \
                    (registry.joins_completed < 1
                     or registry.status_block()["degraded"]):
                time.sleep(0.02)
            if registry.status_block()["degraded"]:
                violations.append("membership never recovered after "
                                  "the rejoin")
            if registry.joins_completed < 1:
                violations.append("rejoiner never activated (no routed "
                                  "ingest reached it before the join "
                                  "deadline)")
            # ---- zombie leg: the dead generation fences at the
            # REJOINED shard (host-side counted reject) ------------------
            zc = RemoteShardChannel(joiner.addr, victim,
                                    dead_generation)
            if zc.write_prio(np.asarray([0], np.int64),
                             np.asarray([9.9], np.float32),
                             dead_generation) is not False:
                violations.append(
                    "unfenced stale write: the zombie's dead-"
                    "generation write-back was accepted at the "
                    "rejoined shard")
            if joiner.shard.stale_rejected != 1:
                violations.append(
                    f"zombie write not counted at the shard "
                    f"(stale_rejected={joiner.shard.stale_rejected})")
            zc.close()

        if rebalance:
            rb_sid = 0  # a live shard distinct from the kill victim
            rb = hosts[rb_sid]
            with plane_lock:
                rb.final_renew()  # the definitive count before the move
                rb.lease.release()
            t_rb = time.monotonic()
            while time.monotonic() - t_rb < 5.0 and \
                    not registry.status_block()["degraded"]:
                time.sleep(0.01)
            if not registry.status_block()["degraded"]:
                violations.append("graceful release never degraded "
                                  "membership (rebalance drill)")
            time.sleep(1.0)  # alert dwell: fire during the gap
            jc0 = registry.joins_completed
            with plane_lock:
                rb.rebalance_reacquire()
            if not rb.lease.joining:
                violations.append("rebalance re-acquire skipped the "
                                  "join barrier")
            # as in the rejoin leg: degraded clears at the GRANT, so
            # wait for the activation itself (first routed ingest acked)
            t_rj = time.monotonic()
            while time.monotonic() - t_rj < 10.0 and \
                    (registry.joins_completed <= jc0
                     or registry.status_block()["degraded"]):
                time.sleep(0.02)
            if registry.status_block()["degraded"]:
                violations.append("membership never recovered after "
                                  "the rebalance re-acquire")
            if registry.joins_completed <= jc0:
                violations.append("rebalanced shard never re-activated "
                                  "(no routed ingest reached it before "
                                  "the join deadline)")

        # ---- alert verdict, polled while membership still holds ---------
        recovered = (rejoin and kill_at is not None) or rebalance
        if recovered:
            end = time.monotonic() + 5.0
            while time.monotonic() < end:
                mission.poll()
                snap = {a["rule"]: a for a in mission.engine.snapshot()}
                dg = snap.get("shard_membership", {})
                if dg.get("fired_total", 0) > 0 \
                        and dg.get("state") not in ("pending",
                                                    "firing"):
                    break
                time.sleep(mission.params.poll_s)
        else:
            time.sleep(3 * mission.params.poll_s + 0.2)
        mission.poll()
        alert_snap = mission.engine.snapshot()

        # ---- stop the load; read the ledger at a quiescent point --------
        stop.set()
        for th in threads:
            th.join(10.0)
            if th.is_alive():
                violations.append(f"deadlock: {th.name} still running "
                                  f"at the join deadline")
        with plane_lock:
            for h in list(hosts.values()) \
                    + ([joiner] if joiner is not None else []):
                if h.shard.alive:
                    h.final_renew()
            led = registry.ledger()
            counters = dict(registry.status_block()["counters"])
        minted_total = sum(minted)
        accounted = (led["ingested"] + led["shard_lost"]
                     + led["route_dropped"])
        if minted_total != accounted:
            violations.append(
                f"conservation breached: minted {minted_total} != "
                f"ingested {led['ingested']} + shard_lost "
                f"{led['shard_lost']} + route_dropped "
                f"{led['route_dropped']} = {accounted}")

        # ---- exact-counter verdict --------------------------------------
        expected_granted = shards \
            + (1 if joiner is not None else 0) \
            + (1 if rebalance else 0)
        checks = [
            ("leases_granted", expected_granted),
            ("leases_expired", 1 if kill_at is not None else 0),
            ("leases_released", 1 if rebalance else 0),
            ("lease_fenced", 0),
            ("joins_timed_out", 0),
            ("joins_completed",
             (1 if joiner is not None else 0)
             + (1 if rebalance else 0)),
            ("stale_writeback_rejected", stale_expected),
        ]
        for name, want in checks:
            if counters.get(name) != want:
                violations.append(f"ledger mismatch: {name} = "
                                  f"{counters.get(name)} "
                                  f"(expected {want})")

        # ---- alert verdict ----------------------------------------------
        fired = sorted(a["rule"] for a in alert_snap
                       if a["fired_total"] > 0)
        unresolved = sorted(a["rule"] for a in alert_snap
                            if a["state"] in ("pending", "firing"))
        expected_alerts = (["shard_membership"]
                           if (kill_at is not None or rebalance)
                           else [])
        unexpected = [r for r in fired if r not in expected_alerts]
        if unexpected:
            violations.append(f"unexpected alert(s) fired: "
                              f"{unexpected}")
        for r in expected_alerts:
            if r not in fired:
                violations.append(f"expected alert {r!r} never fired "
                                  f"during the degraded window")
        if recovered and unresolved:
            violations.append(f"alert(s) {unresolved} still unresolved "
                              f"after membership recovered")
    finally:
        stop.set()
        for h in list(hosts.values()) \
                + ([joiner] if joiner is not None else []):
            try:
                h.shutdown()
            except (ConnectionError, OSError):
                pass
        for ch in channels.values():
            ch.close()
        clock.stop.set()
        mission.stop()
        gw.close()

    report = {
        "violations": violations,
        "shards": shards,
        "kill_at": kill_at,
        "rejoin": rejoin,
        "rebalance": rebalance,
        "minted": sum(minted),
        "sampled_batches": sampled[0],
        "fence_s": round(fence_s, 3) if fence_s is not None else None,
        "ledger": led,
        "counters": counters,
        "alerts": {"fired": fired, "unexpected": unexpected,
                   "unresolved": unresolved},
        "port": addr[1],
    }
    if log_dir:
        reg_writer.close()
        flight_recorder.dump_all("shard chaos drill complete")
    if verbose:
        for k, v in report.items():
            if k != "violations":
                print(f"[chaos] {k}: {v}")
        for v in violations:
            print(f"[chaos] VIOLATION: {v}")
    return report


# ---------------------------------------------------------------------------
# gateway high-availability drills (ISSUE 16): kill the primary under a
# live fleet — warm standby must promote (fenced), clients must fail
# over, and the ledger must stay EXACT across the cutover
# ---------------------------------------------------------------------------

# the gateway drill's rule set: the failover rule MUST fire during the
# outage and resolve once the promoted standby reports healthy; the
# flap rule (same tag, 30s dwell no drill can sustain) is the
# quiet-by-construction guard for the unexpected-alert invariant
GATEWAY_ALERT_RULES = (
    "gateway_failover: gateway/sync_stale >= 1 for 0.3s; "
    "gateway_flap: gateway/sync_stale >= 1 for 30s")


def _hello_probe(addr, slot: int = 99) -> bool:
    """One raw HELLO at ``addr``: True if the gateway ANSWERED (granted
    a session), False if it dropped the connection — the fenced /
    unpromoted-standby refusal path.  Raw on purpose: a DcnClient would
    redial and retry; the zombie verdict needs the single-frame answer."""
    import socket as socket_mod

    from pytorch_distributed_tpu.parallel.dcn import (
        T_HELLO, _recv_frame, _send_frame,
    )
    import json

    try:
        sock = socket_mod.create_connection(addr, timeout=2.0)
    except OSError:
        return False
    try:
        sock.settimeout(2.0)
        _send_frame(sock, T_HELLO, json.dumps(
            {"process_ind": slot,
             "incarnation": time.time_ns()}).encode())
        _recv_frame(sock)
        return True
    except (ConnectionError, OSError):
        return False
    finally:
        try:
            sock.close()
        except OSError:
            pass


def gateway_soak(seconds: float = 8.0, actors: int = 3, seed: int = 0,
                 kill_at: float = 2.5, no_standby: bool = False,
                 resurrect: bool = False, lease_s: float = 0.8,
                 sync_s: float = 0.1, poison_every: int = 40,
                 log_dir: Optional[str] = None, port: int = 0,
                 verbose: bool = True) -> dict:
    """The ISSUE-16 gateway HA drill: a primary gateway (journaling its
    control plane to the shared ``{log_dir}/gateway/`` WAL) serves N
    synthetic actors while a warm standby tails it over T_SYNC; at
    ``kill_at`` seconds the primary dies WITH an undrained ingest
    backlog.  Verdict failures:

    - **gateway never recovered** — the standby fails to promote within
      the lease window (+ sync slack): reported as an explicit readable
      violation and a NONZERO exit, never a hang;
    - **client stranded** — any actor ends other than "stopped" even
      though a promoted standby was reachable on its endpoint list;
    - **conservation breached** — an acked chunk that is neither in the
      delivery log nor in the counted ``failover_lost`` spill set (loss
      across a failover is legal only where it is counted: minted =
      delivered + quarantined + failover_lost EXACTLY);
    - **stale-term write applied** (``resurrect``) — the resurrected
      old primary answers a session verb or lands a chunk instead of
      fencing on the promoted term (its refusals must be counted in
      ``gateway_term_fenced`` with ZERO applied writes);
    - **alert contract broken** — ``gateway_failover`` must fire during
      the outage, resolve after promotion, and nothing else may fire.

    With ``no_standby`` the drill proves the SEED contract unchanged:
    every client must end "disconnected" (the EXIT_DISCONNECTED path)
    exactly as before the HA plane existed."""
    import shutil
    import tempfile

    from pytorch_distributed_tpu.config import (
        AlertParams, GatewayParams, MetricsParams,
    )
    from pytorch_distributed_tpu.utils import flight_recorder, telemetry

    violations: List[str] = []
    tmp_dir = None
    ha_dir = log_dir
    if ha_dir is None:
        # the WAL needs a dir either way; TERM.json on SHARED storage
        # is the fencing substrate (same requirement checkpoint resume
        # already has) — in-process drills share a tempdir
        ha_dir = tmp_dir = tempfile.mkdtemp(prefix="chaos-gw-")
    gp = GatewayParams(enabled=True, lease_s=lease_s, sync_s=sync_s)

    if log_dir:
        flight_recorder.configure(log_dir, run_id="chaos-soak")
    mission = telemetry.MissionControl(
        log_dir, MetricsParams(enabled=True, poll_s=0.1),
        AlertParams(rules=GATEWAY_ALERT_RULES))
    mission.start()
    ha_writer = _AggregatorWriter(mission.metrics)

    clock = GlobalClock()
    stats = ActorStats()
    store = ParamStore(8)
    store.publish(np.zeros(8, dtype=np.float32))
    log = ChunkLog()
    # the primary drains through a paced ingest so the kill strands a
    # real backlog — the failover_lost bucket under test; the standby
    # delivers straight to the log (its ingest isn't the drill's story)
    ingest = IngestSim(log, bound=256, rate=150.0)

    primary = DcnGateway(store, clock, stats, put_chunk=ingest,
                         host="127.0.0.1", port=port, idle_deadline=30.0,
                         gateway_params=gp, log_dir=ha_dir,
                         ha_role="primary", ha_writer=ha_writer)
    old_term = primary.term
    standby = None
    if not no_standby:
        standby = DcnGateway(
            store, clock, ActorStats(), put_chunk=log,
            host="127.0.0.1", port=0, idle_deadline=30.0,
            gateway_params=gp, log_dir=ha_dir, ha_role="standby",
            sync_from=("127.0.0.1", primary.port), ha_writer=ha_writer)
    endpoints = [("127.0.0.1", primary.port)]
    if standby is not None:
        endpoints.append(("127.0.0.1", standby.port))

    fleet = [
        SyntheticActor(
            endpoints, slot=i, pace=0.004,
            poison_every=poison_every,
            client_kwargs=dict(
                # without a standby the drill PROVES the seed contract:
                # clients must give up (EXIT_DISCONNECTED) on the seed
                # timescale, so keep the redial budget drill-sized
                reconnect_timeout=(2.0 if no_standby
                                   else lease_s * 4 + 8.0),
                heartbeat_interval=0.3,
            )).start()
        for i in range(actors)
    ]

    t_start = time.monotonic()
    deadline = t_start + seconds
    killed = False
    t_kill = 0.0
    promoted_in: Optional[float] = None
    spilled: List[int] = []
    learner_step = 0
    while time.monotonic() < deadline:
        time.sleep(0.05)
        learner_step += 2
        clock.set_learner_step(learner_step)
        if learner_step % 50 == 0:
            store.publish(np.full(8, learner_step, dtype=np.float32))
        if not killed and time.monotonic() - t_start >= kill_at:
            # the kill: the primary stops answering with a live backlog
            # still queued behind it — those acked-but-undrained rows
            # are the counted failover_lost bucket
            primary.close()
            spilled = ingest.spill()
            if standby is not None:
                standby.note_failover_lost(len(spilled))
            killed = True
            t_kill = time.monotonic()
        if killed and standby is not None and promoted_in is None \
                and standby.promoted.is_set():
            promoted_in = time.monotonic() - t_kill

    # ---- promotion verdict: bounded, readable, NEVER a hang ---------------
    promote_bound = lease_s + max(2.0, sync_s * 10 + 1.0)
    if killed and standby is not None and promoted_in is None:
        if standby.promoted.wait(max(0.1, promote_bound
                                     - (time.monotonic() - t_kill))):
            promoted_in = time.monotonic() - t_kill
        else:
            violations.append(
                f"gateway never recovered: standby failed to promote "
                f"within {promote_bound:.1f}s of the primary kill "
                f"(lease {lease_s:g}s) — exiting nonzero instead of "
                f"hanging the fleet")
    if promoted_in is not None and promoted_in > promote_bound:
        violations.append(
            f"promotion took {promoted_in:.2f}s (> one lease window "
            f"{lease_s:g}s + sync slack)")

    # ---- resurrection leg: the old primary comes back believing its
    # stale term — every write must fence, none may apply
    zombie_report: dict = {}
    if resurrect and standby is not None and killed:
        zsink = ChunkLog()
        zombie = DcnGateway(store, clock, ActorStats(), put_chunk=zsink,
                            host="127.0.0.1", port=0, idle_deadline=30.0,
                            gateway_params=gp, log_dir=ha_dir,
                            ha_role="primary", resume_term=old_term)
        answered = _hello_probe(("127.0.0.1", zombie.port))
        zombie_report = {
            "answered_session": bool(answered),
            "term_fenced": zombie.gateway_term_fenced,
            "chunks_applied": zombie.chunks_in + len(zsink.tags),
        }
        if answered:
            violations.append(
                "resurrected primary granted a session on its stale "
                "term (unfenced split brain)")
        if zombie.gateway_term_fenced < 1:
            violations.append(
                "resurrected primary's stale-term writes were not "
                "counted rejects (gateway_term_fenced = 0)")
        if zombie.chunks_in or zsink.tags:
            violations.append(
                f"resurrected stale-term gateway APPLIED "
                f"{zombie.chunks_in + len(zsink.tags)} writes")
        zombie.close()

    clock.stop.set()
    join_budget = (5.0 if no_standby else lease_s * 4 + 20.0)
    for a in fleet:
        a.thread.join(join_budget)
        if a.thread.is_alive():
            violations.append(f"deadlock: actor {a.slot} still running "
                              f"at the join deadline")
        elif no_standby and killed:
            if a.outcome != "disconnected":
                violations.append(
                    f"actor {a.slot} ended {a.outcome!r} (expected "
                    f"'disconnected' — the seed EXIT_DISCONNECTED "
                    f"contract must be unchanged without a standby)")
        elif a.outcome != "stopped":
            violations.append(f"actor {a.slot} ended {a.outcome!r} "
                              f"(stranded despite a live standby)")

    if not killed:
        ingest.close()
    gb: dict = {}
    if standby is not None:
        gb = standby.status_snapshot().get("gateway", {})
        standby.close()
    if not killed:
        primary.close()

    # ---- ledger verdict: EXACT conservation across the failover -----------
    quarantined = (sum(primary.quarantined.values())
                   + (sum(standby.quarantined.values())
                      if standby is not None else 0))
    seen = log.seen()
    acked = [t for a in fleet for t in a.acked_tags]
    spill_set = set(spilled)
    lost = [t for t in acked if t not in seen]
    uncounted = [t for t in lost if t not in spill_set]
    if uncounted:
        violations.append(
            f"conservation breached: {len(uncounted)} acked rows "
            f"vanished outside the counted failover_lost spill "
            f"(first: {uncounted[:5]})")
    if standby is not None and killed \
            and gb.get("failover_lost") != len(spilled):
        violations.append(
            f"ledger mismatch: failover_lost = "
            f"{gb.get('failover_lost')} (expected {len(spilled)} "
            f"spilled rows)")
    poisoned_sent = sum(a.poisoned_sent for a in fleet)
    if log.poisoned_delivered:
        violations.append(
            f"{log.poisoned_delivered} poisoned transitions reached "
            f"put_chunk (quarantine breached across failover)")
    if poisoned_sent and not quarantined:
        violations.append(
            f"{poisoned_sent} poisoned chunks sent but neither "
            f"gateway quarantined any")
    # ---- byte-ledger verdict across the cutover (ISSUE 18) ----------------
    # Every acked EXP payload byte must be accounted by SOME gateway's
    # counted buckets (no uncounted loss).  One-sided on purpose: a
    # frame the dying primary processed whose ack never landed is
    # retransmitted to (and re-counted by) the standby — the same
    # documented lost-ack residual the row ledger carries, so the
    # gateway legs may LEAD the client count, never trail it.
    wire_report: dict = {}
    if standby is not None and killed and promoted_in is not None \
            and primary.flow is not None and standby.flow is not None:
        acked_bytes = sum(a.client.flow_acked_bytes
                          for a in fleet if a.client)
        primary_bytes = (primary.flow.ingested_bytes
                         + primary.flow.rejected_bytes
                         + primary.flow.shed_bytes)
        standby_bytes = (standby.flow.ingested_bytes
                         + standby.flow.rejected_bytes
                         + standby.flow.shed_bytes)
        carry = {k: int(v) for k, v in (gb.get("carry") or {}).items()
                 if k.endswith("_bytes")}
        wire_report = {
            "acked_bytes": acked_bytes,
            "primary_bytes": primary_bytes,
            "standby_bytes": standby_bytes,
            "journal_carry": carry,
            "retransmit_residual_bytes":
                primary_bytes + standby_bytes - acked_bytes,
        }
        if acked_bytes > primary_bytes + standby_bytes:
            violations.append(
                f"byte conservation breached across failover: clients "
                f"acked {acked_bytes} B but the two gateways account "
                f"only {primary_bytes + standby_bytes} B (uncounted "
                f"bytes lost in the cutover)")
        if carry.get("ingested_bytes", 0) > primary.flow.ingested_bytes:
            violations.append(
                f"journaled byte carry LEADS the primary's own ledger "
                f"({carry.get('ingested_bytes')} > "
                f"{primary.flow.ingested_bytes} B) — the journal "
                f"invented bytes")
        if primary.flow.ingested_bytes \
                and not carry.get("ingested_bytes"):
            violations.append(
                f"journaled byte carry empty despite "
                f"{primary.flow.ingested_bytes} B ingested before the "
                f"kill (byte legs not riding the HA state records)")

    failovers = sum(a.client.failovers for a in fleet if a.client)
    if standby is not None and killed and promoted_in is not None:
        if failovers < 1:
            violations.append(
                "no client ever failed over to the promoted standby "
                "(the endpoint list was never exercised)")
        if gb.get("role") != "primary" or gb.get("promotions") != 1:
            violations.append(
                f"ledger mismatch: standby ended role="
                f"{gb.get('role')!r} promotions={gb.get('promotions')} "
                f"(expected promoted primary, exactly one promotion)")
        if gb.get("term") != old_term + 1:
            violations.append(
                f"ledger mismatch: promoted term {gb.get('term')} "
                f"(expected {old_term + 1})")

    # ---- alert verdict: failover must FIRE and RESOLVE --------------------
    if standby is not None and killed and promoted_in is not None:
        end = time.monotonic() + 5.0
        while time.monotonic() < end:
            mission.poll()
            snap = {a["rule"]: a for a in mission.engine.snapshot()}
            fa = snap.get("gateway_failover", {})
            if fa.get("fired_total", 0) > 0 \
                    and fa.get("state") not in ("pending", "firing"):
                break
            time.sleep(mission.params.poll_s)
    mission.poll()
    alert_snap = mission.engine.snapshot()
    mission.stop()
    fired = sorted(a["rule"] for a in alert_snap if a["fired_total"] > 0)
    unresolved = sorted(a["rule"] for a in alert_snap
                        if a["state"] in ("pending", "firing"))
    expected_alerts = (["gateway_failover"]
                       if standby is not None and killed else [])
    unexpected = [r for r in fired if r not in expected_alerts]
    if unexpected:
        violations.append(f"unexpected alert(s) fired: {unexpected}")
    for r in expected_alerts:
        if r not in fired:
            violations.append(
                f"expected alert {r!r} never fired during the gateway "
                f"outage")
    if unresolved:
        violations.append(f"alert(s) {unresolved} still unresolved "
                          f"after the promoted standby recovered")

    report = {
        "violations": violations,
        "actors": actors,
        "kill_at": kill_at,
        "no_standby": no_standby,
        "resurrect": resurrect,
        "wire": wire_report,
        "promoted_in_s": (round(promoted_in, 3)
                          if promoted_in is not None else None),
        "old_term": old_term,
        "gateway": gb,
        "client_failovers": failovers,
        "acked_chunks": len(acked),
        "delivered_chunks": len(log.tags),
        "duplicate_deliveries": len(log.tags) - len(seen),
        "spilled_rows": len(spilled),
        "lost_rows": len(lost),
        "quarantined": quarantined,
        "poisoned_sent": poisoned_sent,
        "poisoned_delivered": log.poisoned_delivered,
        "zombie": zombie_report,
        "alerts": {"fired": fired, "unexpected": unexpected,
                   "unresolved": unresolved},
        "outcomes": {a.slot: a.outcome for a in fleet},
        "port": primary.port,
    }
    if log_dir:
        flight_recorder.dump_all("gateway chaos drill complete")
    if tmp_dir is not None:
        shutil.rmtree(tmp_dir, ignore_errors=True)
    if verbose:
        for k, v in report.items():
            if k != "violations":
                print(f"[chaos] {k}: {v}")
        for v in violations:
            print(f"[chaos] VIOLATION: {v}")
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/chaos_soak.py",
        description="randomized fault-injection soak for the DCN "
                    "session layer (exits nonzero on invariant "
                    "violations)")
    ap.add_argument("--seconds", type=float, default=20.0)
    ap.add_argument("--actors", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--restart-every", type=float, default=5.0,
                    help="mean seconds between gateway kill+rebinds "
                         "(0 disables)")
    ap.add_argument("--reconnect-timeout", type=float, default=10.0)
    ap.add_argument("--poison-every", type=int, default=40,
                    help="every Nth chunk per actor ships NaN "
                         "reward/priority (0 disables); the gateway "
                         "quarantine must divert every one")
    ap.add_argument("--learner-stall", type=float, default=0.0,
                    metavar="SECS",
                    help="freeze the simulated learner (clock + stats "
                         "cadence) for SECS mid-run: the mission-"
                         "control absence alert must fire during the "
                         "stall and resolve after recovery (0 "
                         "disables the alert drill)")
    ap.add_argument("--learner-stall-at", type=float, default=3.0,
                    metavar="SECS",
                    help="seconds into the run the learner stall "
                         "starts")
    ap.add_argument("--flood", action="store_true",
                    help="overload drill (ISSUE 11): every actor "
                         "pushes flat-out at a slow simulated learner "
                         "ingest — the credit plane must throttle/shed "
                         "(counted), the overload alert must fire and "
                         "resolve, and the conservation ledger must "
                         "balance exactly")
    ap.add_argument("--slow-learner-ingest", type=float, default=0.0,
                    metavar="SECS",
                    help="overload drill: freeze the learner-side "
                         "ingest drain for SECS mid-run (0 disables); "
                         "same verdict set as --flood")
    ap.add_argument("--slow-ingest-at", type=float, default=3.0,
                    metavar="SECS",
                    help="seconds into the run the ingest freeze "
                         "starts")
    ap.add_argument("--slow-slot", action="store_true",
                    help="overload drill: ONE runaway actor floods "
                         "while its neighbours pace normally — the "
                         "per-slot fairness drill (calm slots must get "
                         ">= 70%% of their rows through)")
    ap.add_argument("--kill-gateway", type=float, default=None,
                    metavar="AT",
                    help="gateway HA drill (ISSUE 16): kill the primary "
                         "gateway AT seconds into the run with a live "
                         "backlog behind it — the warm standby must "
                         "promote within one lease window (fenced term "
                         "bump on the shared WAL dir), every client "
                         "must fail over along its endpoint list, the "
                         "conservation ledger must stay EXACT "
                         "(failover_lost counted), and the "
                         "gateway_failover alert must fire and resolve")
    ap.add_argument("--no-standby", action="store_true",
                    help="gateway drill leg: run --kill-gateway WITHOUT "
                         "a standby — every client must end "
                         "disconnected exactly as the seed "
                         "EXIT_DISCONNECTED contract demands")
    ap.add_argument("--resurrect-primary", action="store_true",
                    help="gateway drill leg: after promotion, restart "
                         "the old primary believing its STALE term — "
                         "its writes must be counted rejects "
                         "(gateway_term_fenced), never applied")
    ap.add_argument("--gateway-lease", type=float, default=0.8,
                    metavar="SECS",
                    help="gateway drill lease window (promotion "
                         "deadline after sync silence)")
    ap.add_argument("--kill-replica", type=int, default=None,
                    metavar="AT",
                    help="replica drill (ISSUE 15): SIGKILL-equivalent "
                         "crash of the highest replica at round AT "
                         "(through the production REPLICA fault plane, "
                         "utils/faults.py) — its lease must expire, the "
                         "round must complete over the survivors within "
                         "one lease window, the membership alert must "
                         "fire, and the zombie's stale-generation "
                         "writes must be counted rejects")
    ap.add_argument("--hang-replica", type=int, default=None,
                    metavar="AT",
                    help="replica drill: freeze the highest replica's "
                         "round loop at round AT while its lease "
                         "renewer keeps renewing — the registry's "
                         "round-stall rule must fence it (leases prove "
                         "liveness, rounds prove progress)")
    ap.add_argument("--rejoin", action="store_true",
                    help="replica drill: after the kill/hang, a "
                         "replacement re-leases at a NEW generation "
                         "and syncs through the join-barrier epoch — "
                         "membership must recover and the degraded "
                         "alert must resolve")
    ap.add_argument("--replicas", type=int, default=2,
                    help="replica-drill fleet size")
    ap.add_argument("--replica-rounds", type=int, default=30,
                    help="rounds each surviving replica must complete")
    ap.add_argument("--kill-shard", type=float, default=None,
                    metavar="AT",
                    help="shard drill (ISSUE 20): SIGKILL-equivalent "
                         "crash of the highest replay shard AT seconds "
                         "into the run, mid-ingest — its lease must "
                         "expire within ~one window, sampling must "
                         "continue over the survivors with an EXACT "
                         "conservation ledger (lost rows COUNTED), the "
                         "pre-kill batch's write-back must be a counted "
                         "fenced reject, and the shard_membership "
                         "alert must fire")
    ap.add_argument("--rejoin-shard", action="store_true",
                    help="shard drill: after the kill, a fresh host "
                         "re-leases the shard id at a NEW generation "
                         "through the join barrier — membership must "
                         "recover, the alert must resolve, and the "
                         "zombie's dead-generation write-back must be "
                         "a counted reject at the rejoined shard")
    ap.add_argument("--shard-rebalance", action="store_true",
                    help="shard drill: gracefully release one live "
                         "shard mid-run and re-acquire it as a fresh "
                         "incarnation — the route must rebuild both "
                         "ways, released rows land in shard_lost "
                         "(counted), and the membership alert must "
                         "fire during the gap and resolve after")
    ap.add_argument("--shards", type=int, default=3,
                    help="shard-drill plane width")
    ap.add_argument("--shard-lease", type=float, default=0.5,
                    metavar="SECS",
                    help="shard drill lease window (fencing deadline "
                         "after renew silence)")
    ap.add_argument("--log-dir", type=str, default=None,
                    help="leave the production artifact set (blackbox "
                         "rings with alert transitions, alert/* "
                         "scalar rows) here for tools/timeline.py")
    ap.add_argument("--port", type=int, default=0,
                    help="gateway port (0 = ephemeral); pin it so a "
                         "concurrent fleet_top can watch the soak")
    args = ap.parse_args(argv)
    if args.kill_gateway is not None:
        report = gateway_soak(
            seconds=args.seconds, actors=args.actors, seed=args.seed,
            kill_at=args.kill_gateway, no_standby=args.no_standby,
            resurrect=args.resurrect_primary,
            lease_s=args.gateway_lease,
            poison_every=args.poison_every,
            log_dir=args.log_dir, port=args.port)
        ok = not report["violations"]
        print(f"[chaos] {'OK' if ok else 'FAILED'} gateway drill: "
              f"{len(report['violations'])} violations")
        return 0 if ok else 1
    if args.kill_shard is not None or args.rejoin_shard \
            or args.shard_rebalance:
        kill_at = args.kill_shard
        if kill_at is None and args.rejoin_shard:
            kill_at = 1.5  # bare --rejoin-shard: kill-then-rejoin drill
        report = shard_soak(
            shards=args.shards, seconds=args.seconds, seed=args.seed,
            kill_at=kill_at, rejoin=args.rejoin_shard,
            rebalance=args.shard_rebalance, lease_s=args.shard_lease,
            log_dir=args.log_dir, port=args.port)
        ok = not report["violations"]
        print(f"[chaos] {'OK' if ok else 'FAILED'} shard drill: "
              f"{len(report['violations'])} violations")
        return 0 if ok else 1
    if args.kill_replica is not None or args.hang_replica is not None \
            or args.rejoin:
        kill_at = args.kill_replica
        if kill_at is None and args.hang_replica is None:
            kill_at = 8  # bare --rejoin: default kill-then-rejoin drill
        report = replica_soak(
            replicas=args.replicas, rounds=args.replica_rounds,
            seed=args.seed, kill_at=kill_at,
            hang_at=args.hang_replica, rejoin=args.rejoin,
            log_dir=args.log_dir, port=args.port)
        ok = not report["violations"]
        print(f"[chaos] {'OK' if ok else 'FAILED'} replica drill: "
              f"{len(report['violations'])} violations")
        return 0 if ok else 1
    report = soak(seconds=args.seconds, actors=args.actors, seed=args.seed,
                  restart_every=args.restart_every or None,
                  reconnect_timeout=args.reconnect_timeout,
                  poison_every=args.poison_every,
                  learner_stall=args.learner_stall,
                  learner_stall_at=args.learner_stall_at,
                  flood=args.flood,
                  slow_ingest=args.slow_learner_ingest,
                  slow_ingest_at=args.slow_ingest_at,
                  slow_slot=args.slow_slot,
                  log_dir=args.log_dir, port=args.port)
    ok = not report["violations"]
    print(f"[chaos] {'OK' if ok else 'FAILED'} after {args.seconds:.0f}s: "
          f"{len(report['violations'])} violations")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
