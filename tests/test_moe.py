"""Expert-parallel (ep axis) tests: the MoE DTQN must route correctly,
match its own replicated math when the experts shard over ep, and plug
into the r2d2 learner contract unchanged (models/moe.py,
parallel/expert_parallel.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.memory.sequence_replay import SegmentBatch
from pytorch_distributed_tpu.models.moe import (
    AUX_COLLECTION, DtqnMoeModel, MoeFfn, _top_k_dispatch, window_q_with_aux,
)
from pytorch_distributed_tpu.ops.losses import (
    init_train_state, make_optimizer,
)
from pytorch_distributed_tpu.ops.sequence_losses import build_dtqn_train_step
from pytorch_distributed_tpu.parallel.expert_parallel import (
    moe_state_shardings,
)
from pytorch_distributed_tpu.parallel.learner import ShardedLearner
from pytorch_distributed_tpu.parallel.mesh import make_mesh


def test_dispatch_assigns_unique_slots_and_respects_capacity():
    rng = np.random.default_rng(0)
    B, T, E, k, C = 3, 16, 4, 2, 5
    probs = jax.nn.softmax(
        jnp.asarray(rng.normal(size=(B, T, E)).astype(np.float32)))
    dispatch, combine, f_top1 = _top_k_dispatch(probs, k, C)
    d = np.asarray(dispatch)
    # a slot holds at most one token
    assert np.max(np.sum(d, axis=1)) <= 1.0 + 1e-6
    # a token claims at most one slot per expert, k slots total
    assert np.max(np.sum(d, axis=3)) <= 1.0 + 1e-6
    assert np.max(np.sum(d, axis=(2, 3))) <= k + 1e-6
    # combine weights live exactly on dispatched slots and a fully-kept
    # token's gates sum to 1 (renormalised over its k choices)
    c = np.asarray(combine)
    assert np.all(c[d == 0] == 0)
    per_token = np.sum(c, axis=(2, 3))
    kept_all = np.sum(d, axis=(2, 3)) == k
    np.testing.assert_allclose(per_token[kept_all], 1.0, rtol=1e-5)
    # rank-0 mask is one-hot per token
    np.testing.assert_allclose(np.sum(np.asarray(f_top1), -1), 1.0)


def test_dispatch_drops_overflow_deterministically():
    # all tokens pick expert 0 at rank 0: only the first C survive there
    B, T, E, C = 1, 8, 2, 3
    probs = jnp.tile(jnp.asarray([[0.9, 0.1]], jnp.float32), (T, 1))[None]
    dispatch, _, _ = _top_k_dispatch(probs, 1, C)
    d = np.asarray(dispatch)[0]            # (T, E, C)
    assert np.sum(d[:, 0]) == C            # capacity filled...
    assert np.all(np.sum(d[:C, 0], axis=1) == 1)   # ...by the earliest
    assert np.all(d[C:, 0] == 0)           # later tokens dropped


def test_single_expert_reduces_to_dense_ffn():
    """E=1, k=1, ample capacity: the mixture must equal the plain FFN
    computed from its own expert kernels — routing becomes the identity."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 6, 8)).astype(np.float32))
    ffn = MoeFfn(dim=8, num_experts=1, top_k=1, capacity_factor=1.0)
    params = ffn.init(jax.random.PRNGKey(0), x)
    y, aux = ffn.apply(params, x)
    p = params["params"]
    ref = jax.nn.gelu(x @ p["w1"][0] + p["b1"][0]) @ p["w2"][0] + p["b2"][0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # one expert: f=1, P=1 -> aux == 1 exactly
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-6)


def test_aux_loss_prefers_balance():
    """The Switch aux term is minimised (=1) by uniform routing and grows
    when the router collapses onto one expert."""
    B, T, E = 2, 12, 4
    uniform = jnp.full((B, T, E), 1.0 / E)
    _, _, f_u = _top_k_dispatch(uniform, 1, T)
    aux_u = E * float(jnp.sum(jnp.mean(f_u, (0, 1)) * jnp.mean(uniform,
                                                               (0, 1))))
    skew = jax.nn.softmax(
        jnp.tile(jnp.asarray([8.0, 0.0, 0.0, 0.0]), (B, T, 1)))
    _, _, f_s = _top_k_dispatch(skew, 1, T)
    aux_s = E * float(jnp.sum(jnp.mean(f_s, (0, 1)) * jnp.mean(skew,
                                                               (0, 1))))
    assert abs(aux_u - 1.0) < 1e-5
    assert aux_s > 2.0


def _setup(T=8, B=4, obs_dim=6, actions=4, aux_weight=0.01):
    model = DtqnMoeModel(action_space=actions, state_shape=(obs_dim,),
                         window=T, dim=32, heads=4, depth=2, norm_val=1.0,
                         num_experts=8, top_k=2, capacity_factor=1.25)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, obs_dim)))
    params = {"params": variables["params"]}
    tx = make_optimizer(lr=1e-3)
    state = init_train_state(params, tx)
    step = build_dtqn_train_step(
        window_q_with_aux(model), tx, burn_in=0, nstep=3, gamma=0.99,
        enable_double=True, target_model_update=100, aux_weight=aux_weight)
    L = T - 1
    rng = np.random.default_rng(7)
    batch = SegmentBatch(
        obs=rng.normal(size=(B, T, obs_dim)).astype(np.float32),
        action=rng.integers(0, actions, size=(B, L)).astype(np.int32),
        reward=rng.normal(size=(B, L)).astype(np.float32),
        terminal=np.zeros((B, L), dtype=np.float32),
        mask=np.ones((B, L), dtype=np.float32),
        c0=np.zeros((B, 1), dtype=np.float32),
        h0=np.zeros((B, 1), dtype=np.float32),
        weight=np.ones(B, dtype=np.float32),
        index=np.arange(B, dtype=np.int32),
    )
    return model, state, step, batch


def test_expert_kernels_shard_over_ep():
    mesh = make_mesh(dp_size=2, ep_size=4)
    _, state, _, _ = _setup()
    sh = moe_state_shardings(state, mesh)
    flat = jax.tree_util.tree_flatten_with_path(sh)[0]
    expert = [(p, s) for p, s in flat
              if "moe" in str(p) and any(f"'{n}'" in str(p)
                                         for n in ("w1", "w2"))]
    # depth=2 blocks x >=3 trees (params, target, adam moments)
    assert len(expert) >= 4
    for p, s in expert:
        assert s.spec[0] == "ep", (p, s.spec)
    routers = [s for p, s in flat if "router" in str(p)]
    assert routers and all(
        s.spec == jax.sharding.PartitionSpec() for s in routers)


def test_ep_sharded_step_matches_replicated():
    """One full train step (fwd+bwd+Adam+target) on a dp2 x ep4 mesh:
    expert-sharded MoE == replicated math, and the placed kernels really
    live split over ep."""
    mesh = make_mesh(dp_size=2, ep_size=4)
    _, state, step, batch = _setup()

    ref = ShardedLearner(step, mesh, donate=False)
    s0 = ref.place(state)
    s0, m0, td0 = ref.step(s0, batch)

    sh = moe_state_shardings(state, mesh)
    ep = ShardedLearner(step, mesh, donate=False, state_shardings=sh)
    s1 = ep.place(state)
    kernels = [
        (path, leaf) for path, leaf
        in jax.tree_util.tree_flatten_with_path(s1.params)[0]
        if "moe" in str(path) and "'w1'" in str(path)]
    assert kernels
    for _, leaf in kernels:
        assert leaf.sharding.spec[0] == "ep"
    s1, m1, td1 = ep.step(s1, batch)

    np.testing.assert_allclose(
        float(m1["learner/critic_loss"]), float(m0["learner/critic_loss"]),
        rtol=1e-4, atol=1e-5)
    assert "learner/moe_aux" in m1
    assert float(m1["learner/moe_aux"]) >= 1.0 - 1e-4
    np.testing.assert_allclose(np.asarray(td1), np.asarray(td0),
                               rtol=1e-3, atol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(s0.params)),
                    jax.tree_util.tree_leaves(jax.device_get(s1.params))):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-3, atol=1e-4)


def test_acting_path_matches_window_q_tail():
    """The MoE model honours the DTQN acting contract: feeding a sequence
    step-by-step through __call__ yields the same Q for the newest
    observation as one window_q pass over the filled prefix."""
    model, state, _, batch = _setup(T=8)
    params = state.params
    obs = batch.obs[:2]                     # (2, 8, 6)
    carry = model.zero_carry(2)
    apply = jax.jit(lambda p, o, c: model.apply(p, o, c))
    for t in range(4):
        q_act, carry = apply(params, obs[:, t], carry)
    q_win = model.apply(params, obs[:, :4], method=model.window_q)
    np.testing.assert_allclose(np.asarray(q_act), np.asarray(q_win[:, 3]),
                               rtol=1e-4, atol=1e-5)


def test_init_time_sown_aux_cannot_leak_into_params():
    """flax init captures the sown moe_losses collection; if those leaves
    ride inside TrainState.params they seed every later sow reduce and
    become free parameters with a constant positive aux gradient (Adam
    would drive them unboundedly negative).  Both defenses hold: factory
    init strips them, and window_q_with_aux ignores them when present."""
    from pytorch_distributed_tpu.config import build_options
    from pytorch_distributed_tpu.factory import (
        build_model, init_params, probe_env,
    )

    opt = build_options(17, seq_len=7, burn_in=0)
    spec = probe_env(opt)
    model = build_model(opt, spec)
    params = init_params(opt, spec, model, seed=0)
    assert set(params.keys()) == {"params"}

    # direct-init callers: a variables dict still carrying the collection
    # must produce the SAME aux as the clean one
    obs_dim = spec.state_shape[0]
    dirty = model.init(jax.random.PRNGKey(0), jnp.zeros((1, obs_dim)))
    assert AUX_COLLECTION in dirty
    # poison the stored leaves: if they seeded the reduce, aux would shift
    poisoned = dict(dirty)
    poisoned[AUX_COLLECTION] = jax.tree_util.tree_map(
        lambda x: x - 1000.0, dirty[AUX_COLLECTION])
    apply = window_q_with_aux(model)
    obs = jnp.asarray(np.random.default_rng(0).normal(
        size=(2, 8, obs_dim)).astype(np.float32))
    _, aux_clean = apply({"params": dirty["params"]}, obs)
    _, aux_dirty = apply(poisoned, obs)
    np.testing.assert_allclose(float(aux_dirty), float(aux_clean),
                               rtol=1e-6)
    assert float(aux_clean) >= 1.0 - 1e-5


def test_factory_builds_moe_row_and_step_runs():
    """CONFIGS row 17 constructs end-to-end: model, params, train step
    with the aux term, one update on synthetic segments."""
    from pytorch_distributed_tpu.config import build_options
    from pytorch_distributed_tpu.factory import (
        build_model, build_train_state_and_step, init_params, probe_env,
    )

    opt = build_options(17, seq_len=7, burn_in=0)
    assert opt.model_type == "dtqn-moe"
    spec = probe_env(opt)
    model = build_model(opt, spec)
    assert isinstance(model, DtqnMoeModel)
    params = init_params(opt, spec, model, seed=0)
    state, step = build_train_state_and_step(opt, spec, model, params)
    T = opt.agent_params.seq_len + 1
    L = T - 1
    rng = np.random.default_rng(3)
    B = 4
    batch = SegmentBatch(
        obs=rng.normal(size=(B, T, *spec.state_shape)).astype(np.float32),
        action=rng.integers(0, spec.num_actions, size=(B, L)).astype(
            np.int32),
        reward=rng.normal(size=(B, L)).astype(np.float32),
        terminal=np.zeros((B, L), dtype=np.float32),
        mask=np.ones((B, L), dtype=np.float32),
        c0=np.zeros((B, 1), dtype=np.float32),
        h0=np.zeros((B, 1), dtype=np.float32),
        weight=np.ones(B, dtype=np.float32),
        index=np.arange(B, dtype=np.int32),
    )
    state, metrics, pr = jax.jit(step)(state, batch)
    assert int(state.step) == 1
    assert np.isfinite(float(metrics["learner/critic_loss"]))
    assert float(metrics["learner/moe_aux"]) >= 1.0 - 1e-4
    assert pr.shape == (B,)
