"""Child process for the SLOW full-topology kill/preemption-resume
drills (tests/test_checkpoint_epochs.py TestTopologyDrills): one real
thread-backend training run (config 1, fake chain env) with the
checkpoint-epoch cadence on, optionally SIGKILLed mid-save by a
``CKPT_FAULTS`` schedule or SIGTERMed (preemption notice) by the parent.

Run: python _kill_resume_child.py <root_dir> <refs> <steps> <resume_mode>
Prints ``FINAL lstep=<n> actor=<n> preempted=<0|1>`` on a clean exit."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"


def main() -> None:
    root, refs, steps, resume = (sys.argv[1], sys.argv[2],
                                 int(sys.argv[3]), sys.argv[4])

    import jax

    jax.config.update("jax_platforms", "cpu")

    from pytorch_distributed_tpu import runtime
    from pytorch_distributed_tpu.config import build_options

    opt = build_options(
        config=1, root_dir=root, refs=refs, steps=steps, resume=resume,
        num_actors=1, learn_start=16, batch_size=8, memory_size=512,
        logger_freq=1, evaluator_freq=1, evaluator_nepisodes=1,
        visualize=False, early_stop=25, max_replay_ratio=16.0,
        checkpoint_replay=True, checkpoint_freq=10, checkpoint_retain=3,
        max_seconds=300.0)
    topo = runtime.train(opt, backend="thread")
    print(f"FINAL lstep={topo.clock.learner_step.value} "
          f"actor={topo.clock.actor_step.value} "
          f"preempted={int(topo.preempted.is_set())}", flush=True)


if __name__ == "__main__":
    main()
