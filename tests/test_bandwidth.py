"""The bandwidth X-ray (ISSUE 18, utils/bandwidth.py): byte-exact
accounting for every wire, ring, and checkpoint plane.

Four depths, mirroring the flow suite's layering:

- units: the LinkAccountant's counter table (link x verb x slot x
  direction), the socket side-table, payload sizing, the headline
  ratios, emit/status shapes, and resolve_bandwidth's env contract;
- the wire: a real DcnClient <-> DcnGateway pair — per-frame byte
  equality across the loopback, the byte conservation ledger's three
  gateway buckets (ingested / rejected / shed), and EXACT equality
  under injected corruption and severs (a frame that dies mid-wire is
  counted by NEITHER side; the clean retransmit is counted once);
- the journal: the gateway byte legs ride the ISSUE-16 HA state
  records — absolute-cumulative, double-apply idempotent, carried
  across a warm restart;
- acceptance: a short CPU topology exports every ``wire/*`` headline
  tag as role-stamped metrics rows, live-readable through T_STATUS's
  ``wire`` block.

The randomized end-to-end versions are ``tools/chaos_soak.py --flood``
(byte ledger exact under brownout) and ``--kill-gateway`` (journaled
byte carry across a promotion).
"""

import io
import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.config import (
    BandwidthParams, FlowParams, GatewayParams, build_options,
)
from pytorch_distributed_tpu.parallel.dcn import (
    T_CLOCK, T_EXP, T_HELLO, T_PING, DcnClient, DcnGateway, _recv_frame,
    _send_frame, encode_chunk, fetch_status,
)
from pytorch_distributed_tpu.utils import bandwidth
from pytorch_distributed_tpu.utils.experience import Transition
from pytorch_distributed_tpu.utils.faults import FaultInjector
from pytorch_distributed_tpu.utils.metrics import read_scalars
from tools.chaos_soak import ChunkLog, tagged_transition

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_wire(monkeypatch):
    """The accountant is a per-process lazy singleton (like perf
    monitors and tracers): isolate each test and strip any wire env an
    earlier topology exported."""
    for var in list(os.environ):
        if var == "TPU_APEX_WIRE" or var.startswith("TPU_APEX_WIRE_"):
            monkeypatch.delenv(var, raising=False)
    bandwidth.reset_for_tests()
    yield
    bandwidth.reset_for_tests()


def _tr():
    return Transition(
        state0=np.zeros(4, dtype=np.float32), action=np.int32(1),
        reward=np.float32(0.5), gamma_n=np.float32(0.99),
        state1=np.zeros(4, dtype=np.float32),
        terminal1=np.float32(0.0), prov=None)


def _chunk(tag=0, n=1):
    return [(tagged_transition(tag + i), None) for i in range(n)]


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------


class TestResolveBandwidth:
    def test_defaults_on(self):
        bp = bandwidth.resolve_bandwidth()
        assert bp.enabled and bp.spawn

    def test_bare_switch_and_field_overrides(self, monkeypatch):
        monkeypatch.setenv("TPU_APEX_WIRE", "0")
        assert not bandwidth.resolve_bandwidth().enabled
        monkeypatch.setenv("TPU_APEX_WIRE", "1")
        monkeypatch.setenv("TPU_APEX_WIRE_SPAWN", "0")
        monkeypatch.setenv("TPU_APEX_WIRE_RATE_FLOOR_S", "0.5")
        bp = bandwidth.resolve_bandwidth()
        assert (bp.enabled, bp.spawn, bp.rate_floor_s) == (True, False, 0.5)

    def test_input_never_mutated(self, monkeypatch):
        monkeypatch.setenv("TPU_APEX_WIRE_SPAWN", "0")
        src = BandwidthParams()
        out = bandwidth.resolve_bandwidth(src)
        assert src.spawn is True
        assert out.spawn is False

    def test_export_env_round_trip(self, monkeypatch):
        bp = BandwidthParams(spawn=False, rate_floor_s=0.25)
        bandwidth.export_env(bp)
        try:
            child = bandwidth.resolve_bandwidth()
            assert child.spawn is False
            assert child.rate_floor_s == 0.25
        finally:
            os.environ.pop("TPU_APEX_WIRE_SPAWN", None)
            os.environ.pop("TPU_APEX_WIRE_RATE_FLOOR_S", None)


class TestPayloadNbytes:
    def test_arrays_scalars_bytes(self):
        assert bandwidth.payload_nbytes(
            np.zeros((4,), dtype=np.float32)) == 16
        assert bandwidth.payload_nbytes(np.int32(0)) == 4
        assert bandwidth.payload_nbytes(b"abcd") == 4
        assert bandwidth.payload_nbytes(None) == 0
        assert bandwidth.payload_nbytes(object()) == 0

    def test_transition_and_chunk(self):
        # 2 x f32[4] + 3 scalar f32 + 1 i32 = 16+16+12+4
        t = _tr()
        assert bandwidth.payload_nbytes(t) == 48
        assert bandwidth.chunk_nbytes([(t, None), (t, None)]) == 96

    def test_nested_dicts_and_depth_guard(self):
        assert bandwidth.payload_nbytes(
            {"a": np.zeros(2, np.float32), "b": [np.int32(0)]}) == 12
        deep = np.zeros(2, np.float32)
        for _ in range(10):
            deep = [deep]
        assert bandwidth.payload_nbytes(deep) == 0  # past the guard


class TestLinkAccountant:
    def _acct(self):
        return bandwidth.LinkAccountant(BandwidthParams())

    def test_note_totals_and_filters(self):
        a = self._acct()
        a.note("client", "exp", 100, "tx", slot=0)
        a.note("client", "exp", 50, "tx", slot=1)
        a.note("client", "tick", 10, "tx", slot=0)
        a.note("gateway", "exp", 150, "rx")
        assert a.totals() == (310, 4)
        assert a.totals(link="client") == (160, 3)
        assert a.totals(link="client", verb="exp") == (150, 2)
        assert a.totals(direction="rx") == (150, 1)

    def test_snapshot_folds_slots(self):
        a = self._acct()
        a.note("client", "exp", 100, "tx", slot=0)
        a.note("client", "exp", 50, "tx", slot=1)
        snap = a.snapshot()
        assert snap == {"client": {"exp": {"tx": [150, 2]}}}

    def test_socket_side_table(self):
        a = self._acct()
        s1, s2 = socket.socketpair()
        try:
            a.register_socket(s1, "client", slot=3)
            a.note_frame(s1, 2, 64, "tx")       # T_EXP
            a.note_frame(s2, 2, 64, "rx")       # unregistered -> anon
            assert a.totals(link="client") == (64, 1)
            assert a.totals(link="anon") == (64, 1)
            # unweakrefable doubles are accepted, accounted anon
            a.register_socket(object(), "gateway")
        finally:
            s1.close()
            s2.close()

    def test_bytes_per_transition_rx_only(self):
        """Loopback topologies (every test) count the SAME exp frame
        tx on the client link and rx on the gateway link; the headline
        ratio divides the rx side only — no double-count."""
        a = self._acct()
        a.note("client", "exp", 400, "tx")
        a.note("gateway", "exp", 400, "rx")
        a.note("gateway", "exp", 100, "tx")     # acks don't count
        a.note_transitions(4)
        assert a.bytes_per_transition() == pytest.approx(100.0)

    def test_replica_bytes_per_round(self):
        a = self._acct()
        a.note("gateway", "rlease", 30, "rx")
        a.note("gateway", "rgrad", 50, "rx")
        a.note("gateway", "rgrad", 10, "tx")
        a.note("gateway", "rprio", 10, "rx")
        a.note("gateway", "exp", 999, "rx")     # not replica plane
        a.note_round()
        a.note_round()
        assert a.replica_bytes_per_round() == pytest.approx(50.0)
        assert bandwidth.LinkAccountant(
            BandwidthParams()).replica_bytes_per_round() == 0.0

    def test_emit_scalars_rates_ratios_gauges(self):
        a = self._acct()
        a.note("client", "exp", 1000, "tx")
        first = a.emit_scalars(now=100.0)       # primes the baseline
        assert "wire/client/bytes_per_s" not in first
        a.note("client", "exp", 500, "tx")
        a.note("gateway", "exp", 1500, "rx")
        a.note_transitions(10)
        a.set_gauge("replay/hbm_bytes", 4096.0)
        out = a.emit_scalars(now=102.0)
        assert out["wire/client/bytes_per_s"] == pytest.approx(250.0)
        assert out["wire/bytes_per_transition"] == pytest.approx(150.0)
        assert "wire/replica_bytes_per_round" not in out  # no rounds
        assert out["replay/hbm_bytes"] == 4096.0

    def test_emit_respects_rate_floor(self):
        a = bandwidth.LinkAccountant(BandwidthParams(rate_floor_s=1.0))
        a.note("client", "exp", 100, "tx")
        a.emit_scalars(now=100.0)
        a.note("client", "exp", 100, "tx")
        # a sub-floor window would divide noise by ~0: suppressed
        assert "wire/client/bytes_per_s" not in a.emit_scalars(now=100.01)

    def test_status_block_shape(self):
        a = self._acct()
        a.note("gateway", "exp", 300, "rx", slot=0)
        a.note("gateway", "clock", 30, "tx", slot=0)
        a.note_transitions(3)
        blk = a.status_block()
        g = blk["links"]["gateway"]
        assert (g["bytes"], g["frames"]) == (330, 2)
        assert (g["rx_bytes"], g["tx_bytes"]) == (300, 30)
        assert blk["transitions"] == 3
        assert blk["bytes_per_transition"] == pytest.approx(100.0)


class TestPlaneSwitch:
    def test_disabled_plane_hooks_are_noops(self, monkeypatch):
        monkeypatch.setenv("TPU_APEX_WIRE", "0")
        bandwidth.reset_for_tests()
        assert bandwidth.get_accountant() is None
        assert not bandwidth.enabled()
        # every module hook degrades to a flag check, never a crash
        bandwidth.note("client", "exp", 10, "tx")
        bandwidth.note_frame(None, 2, 10, "tx")
        bandwidth.note_spawn("mint", _chunk())
        bandwidth.note_transitions(5)
        bandwidth.note_round()
        bandwidth.set_gauge("replay/hbm_bytes", 1.0)
        assert bandwidth.emit_scalars() == {}
        assert bandwidth.status_block() is None

    def test_spawn_accounting_and_gate(self, monkeypatch):
        chunk = [(_tr(), None)]
        bandwidth.note_spawn("mint", chunk)
        bandwidth.note_spawn("drain", chunk, frames=1)
        acct = bandwidth.get_accountant()
        assert acct.totals(link="spawn", verb="mint") == (48, 1)
        assert acct.totals(link="spawn", direction="rx") == (48, 1)
        monkeypatch.setenv("TPU_APEX_WIRE_SPAWN", "0")
        bandwidth.reset_for_tests()
        bandwidth.note_spawn("mint", chunk)
        assert bandwidth.get_accountant().totals(link="spawn") == (0, 0)

    def test_replay_gauges(self):
        class _Mem:
            state0 = np.zeros((8, 4), dtype=np.float32)
            action = np.zeros((8,), dtype=np.int32)

        bandwidth.note_host_replay(_Mem())
        out = bandwidth.get_accountant().emit_scalars()
        assert out["replay/host_bytes"] == 128 + 32
        assert out["replay/host_bytes/state0"] == 128.0

    def test_device_replay_gauge_sums_fields(self):
        from pytorch_distributed_tpu.memory.device_replay import (
            DeviceReplayIngest,
        )

        ing = DeviceReplayIngest(16, (4,), state_dtype=np.float32)
        ing.attach()
        out = bandwidth.get_accountant().emit_scalars()
        assert out["replay/hbm_bytes"] > 0
        assert out["replay/hbm_bytes/state0"] >= 16 * 4 * 4


# ---------------------------------------------------------------------------
# the wire: byte equality + the conservation ledger's three buckets
# ---------------------------------------------------------------------------


@pytest.fixture()
def plane():
    clock = GlobalClock()
    stats = ActorStats()
    store = ParamStore(8)
    store.publish(np.zeros(8, dtype=np.float32))
    log = ChunkLog()
    gw = DcnGateway(store, clock, stats, put_chunk=log,
                    host="127.0.0.1", port=0, idle_deadline=30.0,
                    flow_params=FlowParams(dwell_s=0.0, recover_s=0.0),
                    pressure=lambda: 0.0)
    gw.flow._next_update = time.monotonic() + 3600  # tests drive it
    holder = {"gw": gw}
    yield holder, log
    holder["gw"].close()


def _client(gw, slot=0, **kw):
    kw.setdefault("heartbeat_interval", 0)
    kw.setdefault("reconnect_timeout", 10.0)
    return DcnClient(("127.0.0.1", gw.port), process_ind=slot, **kw)


class TestWireByteEquality:
    def test_round_trip_frame_and_ledger_equality(self, plane):
        """Clean run: every exp frame's bytes land once on each side of
        the loopback (client tx == gateway rx, header included), and
        the payload-level ledger balances EXACTLY."""
        holder, log = plane
        gw = holder["gw"]
        client = _client(gw)
        for i in range(3):
            client.send_chunk(_chunk(i * 10, n=2))
        client.tick()                             # ships the byte report
        acct = bandwidth.get_accountant()
        tx_b, tx_f = acct.totals(link="client", verb="exp",
                                 direction="tx")
        rx_b, rx_f = acct.totals(link="gateway", verb="exp",
                                 direction="rx")
        assert tx_f == rx_f == 3
        assert tx_b == rx_b > 0
        assert client.flow_acked_bytes == gw.flow.ingested_bytes > 0
        cons = gw.flow.conservation()
        assert cons["bytes_balanced"], cons
        assert cons["acked_bytes"] == cons["accounted_bytes"]
        assert cons["rejected_bytes"] == cons["shed_bytes"] == 0
        assert acct.bytes_per_transition() > 0
        client.close()

    def test_status_wire_block_over_the_wire(self, plane):
        holder, log = plane
        gw = holder["gw"]
        client = _client(gw)
        client.send_chunk(_chunk(0, n=4))
        client.tick()
        status = fetch_status(("127.0.0.1", gw.port))
        wire = status["wire"]
        assert wire["links"]["gateway"]["rx_bytes"] > 0
        assert wire["transitions"] == 4
        assert wire["bytes_per_transition"] > 0
        led = wire["ledger"]
        assert led["bytes_balanced"]
        assert led["acked_bytes"] == led["accounted_bytes"] > 0
        # the probe link itself is accounted (fetch_status is
        # sessionless): fleet_top polls are not invisible traffic
        acct = bandwidth.get_accountant()
        assert acct.totals(link="probe")[0] > 0
        client.close()

    def test_rejected_frame_bytes_bucketed(self, plane):
        """A well-framed, schema-invalid EXP frame is acked and its
        bytes land in the rejected bucket — frame-granular, exact."""
        holder, log = plane
        gw = holder["gw"]
        sock = socket.create_connection(("127.0.0.1", gw.port),
                                        timeout=5.0)
        sock.settimeout(5.0)
        _send_frame(sock, T_HELLO, json.dumps(
            {"role": "actor", "process_ind": 0,
             "incarnation": 1}).encode())
        assert _recv_frame(sock)[0] == T_CLOCK
        payload = encode_chunk([(tagged_transition(1), None),
                                (tagged_transition(2), None)])
        with np.load(io.BytesIO(payload)) as z:
            cols = {k: z[k] for k in z.files}
        cols["priority"] = cols["priority"][:1]   # truncated column
        buf = io.BytesIO()
        np.savez(buf, **cols)
        bad = buf.getvalue()
        _send_frame(sock, T_EXP, bad)
        assert _recv_frame(sock)[0] == T_CLOCK    # acked, not dropped
        assert gw.flow.rejected_bytes == len(bad)
        assert gw.flow.ingested_bytes == 0
        assert log.tags == []
        sock.close()

    def test_shed_frame_bytes_bucketed_per_tier(self):
        """Brownout tier 3 with a dry bucket sheds the frame: its
        bytes land in shed_bytes (and the per-tier map), and the
        ledger still balances exactly — shed, never silently lost."""
        clock = GlobalClock()
        stats = ActorStats()
        store = ParamStore(8)
        store.publish(np.zeros(8, dtype=np.float32))
        log = ChunkLog()
        gw = DcnGateway(store, clock, stats, put_chunk=log,
                        host="127.0.0.1", port=0, idle_deadline=30.0,
                        flow_params=FlowParams(dwell_s=0.0, recover_s=0.0,
                                               bucket_rate=0.0,
                                               bucket_burst=0.0),
                        pressure=lambda: 0.0)
        gw.flow._next_update = time.monotonic() + 3600
        client = _client(gw)
        try:
            client.send_chunk(_chunk(0))          # tier < 3: admitted
            gov = gw.flow.governor
            gov.update(1.0)
            gov.update(1.0)                       # -> shedding
            gov.tier = 3                          # the brownout rung
            client.send_chunk(_chunk(5))          # shed: bucket is dry
            client.tick()
            assert gw.flow.shed_chunks == 1
            assert gw.flow.shed_bytes > 0
            assert gw.flow.shed_bytes_by_tier == {3: gw.flow.shed_bytes}
            cons = gw.flow.conservation()
            assert cons["acked_bytes"] == cons["accounted_bytes"], cons
            assert cons["acked_bytes"] == (gw.flow.ingested_bytes
                                           + gw.flow.shed_bytes)
            assert cons["bytes_balanced"]
            assert log.tags == [0]                # the shed never landed
        finally:
            client.close()
            gw.close()

    def test_ledger_exact_under_corrupt_retransmit(self, plane):
        """A corrupted frame dies mid-wire (decode ConnectionError,
        conn dropped): NEITHER side counts it; the clean retransmit is
        counted ONCE on each — the ledger stays exact, not one-sided."""
        holder, log = plane
        gw = holder["gw"]
        client = _client(gw, faults=FaultInjector.scripted("corrupt@1"))
        client.send_chunk(_chunk(7))
        client.send_chunk(_chunk(8))
        client.tick()
        assert sorted(log.tags) == [7, 8]
        assert client.reconnects == 1
        cons = gw.flow.conservation()
        assert cons["acked_bytes"] == cons["accounted_bytes"] > 0, cons
        assert gw.flow.ingested_bytes == client.flow_acked_bytes
        client.close()

    def test_ledger_exact_under_sever(self, plane):
        holder, log = plane
        gw = holder["gw"]
        client = _client(gw, faults=FaultInjector.scripted("sever@1"))
        client.send_chunk(_chunk(3))
        client.tick()
        assert log.tags == [3]
        cons = gw.flow.conservation()
        assert cons["acked_bytes"] == cons["accounted_bytes"] > 0, cons
        assert cons["bytes_balanced"]

    def test_fleet_top_wire_panel(self, plane):
        from tools.fleet_top import render, wire_line

        holder, log = plane
        gw = holder["gw"]
        client = _client(gw)
        client.send_chunk(_chunk(0, n=2))
        client.tick()
        status = fetch_status(("127.0.0.1", gw.port))
        line = wire_line(status)
        assert line and "gateway" in line and "B/transition" in line
        assert "IMBALANCED" not in line
        assert "wire:" in render(status)
        # a cooked imbalance (more acked than accounted) goes LOUD
        status["wire"]["ledger"] = {"acked_bytes": 100,
                                    "accounted_bytes": 40,
                                    "bytes_balanced": False}
        assert "IMBALANCED" in wire_line(status)
        client.close()

    def test_panel_absent_without_plane(self):
        from tools.fleet_top import wire_line

        assert wire_line({"learner_step": 0}) is None


# ---------------------------------------------------------------------------
# the journal: byte legs ride the HA state records
# ---------------------------------------------------------------------------


GP = GatewayParams(enabled=True, lease_s=0.4, sync_s=0.05)


def make_gateway(tmp, log, role="primary", gp=GP):
    clock = GlobalClock()
    store = ParamStore(8)
    store.publish(np.zeros(8, dtype=np.float32))
    return DcnGateway(store, clock, ActorStats(), put_chunk=log,
                      host="127.0.0.1", port=0, idle_deadline=30.0,
                      gateway_params=gp, log_dir=str(tmp), ha_role=role)


class TestByteCarryJournal:
    def test_seed_records_byte_legs_idempotent(self, tmp_path):
        log = ChunkLog()
        gw = make_gateway(tmp_path, log)
        try:
            recs = [{"seq": 1, "kind": "state",
                     "data": {"tick_seq": {}, "chunks_in": 4, "lost": 0,
                              "ledger": {"ingested": 10, "shed": 0,
                                         "quarantined": 0,
                                         "ingested_bytes": 4096,
                                         "rejected_bytes": 128,
                                         "shed_bytes": 256}}}]
            gw._seed_records(recs)
            first = dict(gw._ha_carry)
            gw._seed_records(recs)      # replay: absolute, max-applied
            assert gw._ha_carry == first
            assert gw._ha_carry["ingested_bytes"] == 4096
            assert gw._ha_carry["rejected_bytes"] == 128
            assert gw._ha_carry["shed_bytes"] == 256
            # the live ledger = carry + this term's own flow counters
            gw.flow.note_ingested_bytes(1000)
            led = gw._ha_ledger()
            assert led["ingested_bytes"] == 5096
            assert led["shed_bytes"] == 256
        finally:
            gw.close()

    def test_warm_restart_carries_byte_ledger(self, tmp_path):
        log = ChunkLog()
        gw = make_gateway(tmp_path, log)
        gw._ha_append("state", {
            "tick_seq": {}, "chunks_in": 2, "lost": 0,
            "ledger": {"ingested": 5, "shed": 0, "quarantined": 0,
                       "ingested_bytes": 7777, "rejected_bytes": 0,
                       "shed_bytes": 33}})
        gw.close()
        gw2 = make_gateway(tmp_path, log)
        try:
            snap = gw2.status_snapshot()["gateway"]
            assert snap["carry"]["ingested_bytes"] == 7777
            assert snap["carry"]["shed_bytes"] == 33
            # and the promoted ledger REPORTS the carried bytes
            assert gw2._ha_ledger()["ingested_bytes"] == 7777
        finally:
            gw2.close()


# ---------------------------------------------------------------------------
# acceptance: a live CPU topology exports the wire plane
# ---------------------------------------------------------------------------


class TestBandwidthAcceptance:
    @pytest.mark.timeout(240)
    def test_short_cpu_run_exports_wire_series(self, tmp_path):
        """ISSUE 18 acceptance: an unmodified short CPU run (the plane
        is ON by default) exports wire/<link>/bytes_per_s,
        wire/bytes_per_transition and the replay occupancy gauges as
        role-stamped metrics rows, live-readable through the STATUS
        ``wire`` block with a balanced byte ledger.  The actor joins
        over the REAL DCN session (a remote host in thread clothing) —
        local queue-fed actors never touch the wire, so they cannot
        exercise the exp byte path this plane exists to meter."""
        from pytorch_distributed_tpu.fleet import (
            FleetTopology, _remote_actor_main,
        )

        opt = build_options(
            1, memory_type="device", root_dir=str(tmp_path),
            refs="wirerun", num_actors=1, seed=5,
            steps=10 ** 9, max_seconds=120.0, max_replay_ratio=8.0,
            learn_start=16, memory_size=512, batch_size=16,
            actor_freq=25, actor_sync_freq=100, param_publish_freq=50,
            learner_freq=10, logger_freq=2, evaluator_nepisodes=0,
            early_stop=60, checkpoint_freq=0)
        topo = FleetTopology(opt, local_actors=0, port=0)
        done = threading.Event()

        def run():
            try:
                topo.run(backend="thread")
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        actor = threading.Thread(
            target=_remote_actor_main,
            args=(opt, f"127.0.0.1:{topo.port}", 0), daemon=True)
        actor.start()
        addr = ("127.0.0.1", topo.port)
        try:
            status = None
            deadline = time.monotonic() + 100
            while time.monotonic() < deadline and not done.is_set():
                try:
                    status = fetch_status(addr, timeout=5.0)
                except (ConnectionError, OSError):
                    status = None
                if status and (status.get("wire") or {}).get(
                        "bytes_per_transition", 0) > 0:
                    break
                time.sleep(0.25)
            assert status is not None and "wire" in status, \
                "wire block never appeared in STATUS"
            wire = status["wire"]
            assert wire["bytes_per_transition"] > 0
            assert wire["links"]["gateway"]["rx_bytes"] > 0
            assert wire["links"]["client"]["tx_bytes"] > 0
            assert wire["ledger"]["bytes_balanced"], wire["ledger"]
            # hold the run until the learner's stats cadence has
            # emitted the headline series at least twice (rates need a
            # delta window) and the rows reached the metrics stream
            want = {"wire/bytes_per_transition",
                    "wire/client/bytes_per_s",
                    "wire/gateway/bytes_per_s", "replay/hbm_bytes"}
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline and not done.is_set():
                tags = {r.get("tag") for r in read_scalars(opt.log_dir)}
                if want <= tags:
                    break
                time.sleep(0.5)
        finally:
            topo.clock.stop.set()
            t.join(120)
            actor.join(60)
        assert not t.is_alive()

        rows = read_scalars(opt.log_dir)
        by_tag = {}
        for r in rows:
            if "value" in r:
                by_tag.setdefault(r["tag"], []).append(r)
        assert "wire/bytes_per_transition" in by_tag, sorted(by_tag)[:40]
        assert any(r["value"] > 0
                   for r in by_tag["wire/bytes_per_transition"])
        rate_tags = [tg for tg in by_tag
                     if tg.startswith("wire/") and
                     tg.endswith("/bytes_per_s")]
        assert rate_tags, sorted(by_tag)[:40]
        assert {"wire/client/bytes_per_s",
                "wire/gateway/bytes_per_s"} <= set(rate_tags)
        assert "replay/hbm_bytes" in by_tag
        assert any(r["value"] > 0 for r in by_tag["replay/hbm_bytes"])
        assert by_tag["wire/bytes_per_transition"][0]["role"] == "learner"
